"""Llama family — the flagship pretraining model.

Parity anchor: the reference trains this architecture in its hybrid-strategy tests
(/root/reference/test/auto_parallel/hybrid_strategy/semi_auto_llama.py:33 — hidden
4096, GQA, RoPE, RMSNorm, SwiGLU) using ColumnParallelLinear/RowParallelLinear
(fleet/layers/mpu/mp_layers.py:334,541) + flash attention
(nn/functional/flash_attention.py:195).

TPU-native design: one set of plain Layers whose parameters carry *logical axis*
names; sharding (tp / fsdp / sep / dp) is applied by rules at the mesh boundary
(distributed/auto_parallel/logical_sharding.py) and GSPMD inserts the collectives.
The same model class is therefore the single-chip model, the TP model, and the
FSDP model — no per-strategy layer forks like the reference's mpu vs plain nn.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...distributed.auto_parallel.logical_sharding import annotate, constrain, current_mesh
from ...distributed.auto_parallel.serving_sharding import gather_output_shards
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer, LayerList
from ..generation_utils import GenerationMixin, causal_cache_bias


class LlamaConfig:
    def __init__(
        self,
        vocab_size: int = 32000,
        hidden_size: int = 4096,
        intermediate_size: int = 11008,
        num_hidden_layers: int = 32,
        num_attention_heads: int = 32,
        num_key_value_heads: Optional[int] = None,
        max_position_embeddings: int = 4096,
        rms_norm_eps: float = 1e-6,
        rope_theta: float = 10000.0,
        initializer_range: float = 0.02,
        tie_word_embeddings: bool = False,
        dtype: str = "float32",
        recompute: bool = False,
        remat_policy: str = "flash",
        remat_every: int = 1,
        use_flash_attention: bool = True,
        sequence_parallel: bool = False,
        num_experts: int = 1,
        moe_topk: int = 2,
        moe_dispatch: str = "auto",
        moe_gate: str = "gshard",
        moe_aux_weight: float = 0.01,
        moe_capacity_factor: float = 1.25,
        fused_ce: bool = True,
        fused_ce_chunk: int = 1024,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads or num_attention_heads
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.initializer_range = initializer_range
        self.tie_word_embeddings = tie_word_embeddings
        self.dtype = dtype
        self.recompute = recompute
        if remat_policy not in ("flash", "flash_qkv", "flash_mlp", "full"):
            raise ValueError(f"remat_policy must be 'flash', 'flash_qkv', "
                             f"'flash_mlp' or 'full', got {remat_policy!r}")
        self.remat_policy = remat_policy
        # partial remat: layer i is rematerialized iff i % remat_every == 0
        # (1 = every layer, the reference recompute default; 2 = half the
        # stack — trades activation memory back for the recompute FLOPs,
        # the measured ~13% remat tax on the north-star shape)
        if remat_every < 1:
            raise ValueError(f"remat_every must be >= 1 (got {remat_every}); "
                             "use recompute=False to disable remat")
        self.remat_every = remat_every
        self.use_flash_attention = use_flash_attention
        self.sequence_parallel = sequence_parallel
        self.num_experts = num_experts
        self.moe_topk = moe_topk
        self.moe_dispatch = moe_dispatch
        self.moe_gate = moe_gate
        self.moe_aux_weight = moe_aux_weight
        self.moe_capacity_factor = moe_capacity_factor
        # chunked lm-head+CE (ops/fused_ce.py) — skips the [b, s, V] logits
        # materialization in the training loss; generation is unaffected
        self.fused_ce = fused_ce
        self.fused_ce_chunk = fused_ce_chunk

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    def num_params(self) -> int:
        """Parameter count (for MFU math)."""
        h, v, m = self.hidden_size, self.vocab_size, self.intermediate_size
        kvh = self.num_key_value_heads * self.head_dim
        per_layer = (
            h * h + 2 * h * kvh + h * h  # q, k, v, o
            + 3 * h * m                   # gate, up, down
            + 2 * h                       # two rmsnorms
        )
        total = v * h + self.num_hidden_layers * per_layer + h
        if not self.tie_word_embeddings:
            total += h * v
        return total

    @classmethod
    def tiny(cls, **over):
        """Small config for tests / multichip dry-runs. Dims divide tp/fsdp/sep=2."""
        d = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
                 max_position_embeddings=128)
        d.update(over)
        return cls(**d)


def _rope_cos_sin(seq_len: int, head_dim: int, theta: float, dtype):
    """Rotary tables [seq, head_dim] (half-rotated layout, GPT-NeoX style — matches
    reference fused_rotary_position_embedding use_neox_rotary_style=True)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)                       # [s, d/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)       # [s, d]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin):
    """q,k: [b, s, h, d]; cos/sin: [s, d] (shared positions) or [b, s, d]
    (per-row positions, e.g. left-padded decode) — broadcast over heads."""
    if cos.ndim == 3:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    else:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    return q * cos + _rotate_half(q) * sin, k * cos + _rotate_half(k) * sin


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        hd = config.head_dim
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        init = I.Normal(std=config.initializer_range)
        mk = lambda din, dout: self.create_parameter(
            [din, dout], dtype=config.dtype, default_initializer=init)
        self.q_proj_weight = annotate(mk(h, self.num_heads * hd), "embed", "heads")
        self.k_proj_weight = annotate(mk(h, self.num_kv_heads * hd), "embed", "heads")
        self.v_proj_weight = annotate(mk(h, self.num_kv_heads * hd), "embed", "heads")
        self.o_proj_weight = annotate(mk(self.num_heads * hd, h), "heads", "embed")

    def forward(self, hidden, cos, sin, attn_bias=None):
        b, s, h = hidden.shape if isinstance(hidden, Tensor) else hidden.shape
        hd = self.config.head_dim
        x = hidden._data if isinstance(hidden, Tensor) else hidden
        q = jnp.matmul(x, self.q_proj_weight._data).reshape(b, s, self.num_heads, hd)
        k = jnp.matmul(x, self.k_proj_weight._data).reshape(b, s, self.num_kv_heads, hd)
        v = jnp.matmul(x, self.v_proj_weight._data).reshape(b, s, self.num_kv_heads, hd)
        q = constrain(q, "batch", "seq", "heads", "head_dim")
        k = constrain(k, "batch", "seq", "kv_heads", "head_dim")
        v = constrain(v, "batch", "seq", "kv_heads", "head_dim")
        q, k = apply_rotary_pos_emb(q, k, cos, sin)
        # named for the 'flash_qkv' remat policy: saving the rope'd q/k/v
        # (~100MB/layer at the 853M b4 seq-4096 shape) lets backward skip the
        # qkv-projection + rope + input-norm recompute entirely
        from jax.ad_checkpoint import checkpoint_name

        q = checkpoint_name(q, "attn_q")
        k = checkpoint_name(k, "attn_k")
        v = checkpoint_name(v, "attn_v")
        out = _attention(q, k, v, self.config, attn_bias)
        out = out.reshape(b, s, self.num_heads * hd)
        out = jnp.matmul(out, self.o_proj_weight._data)
        out = constrain(out, "batch", "seq", "embed")
        return out

    def decode_step(self, x, cos, sin, k_cache, v_cache, pos, pad_bias=None):
        """KV-cache attention for generation (used for prefill AND decode).

        x: [b, s, h] chunk occupying absolute positions [pos, pos+s);
        caches: [b, max_len, kv_heads, hd]; cos/sin sliced for the chunk's
        positions ([s, d] shared or [b, s, d] per-row when left-padded).
        ``pad_bias`` [b, 1, 1, max_len] masks pad cache columns.
        Returns (out, k_cache, v_cache).
        """
        x = x._data if isinstance(x, Tensor) else x
        b, s, _ = x.shape
        hd = self.config.head_dim
        q = jnp.matmul(x, self.q_proj_weight._data).reshape(b, s, self.num_heads, hd)
        k = jnp.matmul(x, self.k_proj_weight._data).reshape(b, s, self.num_kv_heads, hd)
        v = jnp.matmul(x, self.v_proj_weight._data).reshape(b, s, self.num_kv_heads, hd)
        q, k = apply_rotary_pos_emb(q, k, cos, sin)
        k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype),
                                               (0, pos, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype),
                                               (0, pos, 0, 0))
        bias = causal_cache_bias(k_cache, pos, s, pad_bias)
        from ...nn.functional.flash_attention import _xla_attention

        out = _xla_attention(q, k_cache, v_cache, bias=bias, causal=False)
        out = out.reshape(b, s, self.num_heads * hd)
        return jnp.matmul(out, self.o_proj_weight._data), k_cache, v_cache

    def paged_decode_step(self, x, cos, sin, k_pages, v_pages, tables, pos):
        """Paged-KV generation step (serving suite, ops/paged_attention.py).

        Pools [num_pages, kv_heads, page, hd]; tables [b, pages_per_seq].
        Prefill chunks (s > 1, pos == 0) run causal flash over the chunk;
        decode steps (s == 1) run the paged decode kernel over the whole
        cache. K/V always scatter into the pages. Returns (out, k_pages,
        v_pages)."""
        from ...ops.flash_attention import flash_attention
        from ...ops.paged_attention import append_paged_kv, paged_decode_attention

        x = x._data if isinstance(x, Tensor) else x
        b, s, _ = x.shape
        hd = self.config.head_dim
        q = jnp.matmul(x, self.q_proj_weight._data).reshape(b, s, self.num_heads, hd)
        k = jnp.matmul(x, self.k_proj_weight._data).reshape(b, s, self.num_kv_heads, hd)
        v = jnp.matmul(x, self.v_proj_weight._data).reshape(b, s, self.num_kv_heads, hd)
        q, k = apply_rotary_pos_emb(q, k, cos, sin)
        seq_ids = jnp.repeat(jnp.arange(b, dtype=jnp.int32), s)
        positions = jnp.tile(pos + jnp.arange(s, dtype=jnp.int32), b)
        k_pages, v_pages = append_paged_kv(
            k_pages, v_pages, k.reshape(b * s, self.num_kv_heads, hd),
            v.reshape(b * s, self.num_kv_heads, hd), tables, positions, seq_ids)
        if s == 1:
            ctx = jnp.full((b,), pos + 1, jnp.int32)
            out = paged_decode_attention(q[:, 0], k_pages, v_pages, tables,
                                         ctx)[:, None]
        else:
            out = flash_attention(q, k, v, causal=True)
        out = out.reshape(b, s, self.num_heads * hd)
        return jnp.matmul(out, self.o_proj_weight._data), k_pages, v_pages

    def paged_prefill_chunk(self, x, cos, sin, k_pages, v_pages, tables,
                            starts):
        """Prefill CHUNK at PER-ROW absolute offsets over cached history
        (prefix-cache / chunked-prefill serving path). x: [b, s, h] — row b
        holds tokens at absolute positions [starts[b], starts[b]+s);
        cos/sin [b, s, d] gathered per row. The chunk's k/v scatter into the
        pages first, then attention gathers the FULL table extent with an
        absolute-position causal mask — see paged_prefill_attention for the
        bit-identity-across-chunkings argument.

        Head counts come off the weight/pool shapes (not config), so the
        same body serves a tp shard inside the engine's serving shard_map
        (LOCAL heads + local kv pages per device — all math head-local);
        the attention output is all-gathered before the replicated o_proj
        (serving_sharding.py's column-parallel identity discipline)."""
        from ...ops.paged_attention import (append_paged_kv,
                                            paged_prefill_attention)

        x = x._data if isinstance(x, Tensor) else x
        b, s, _ = x.shape
        hd = self.config.head_dim
        page = k_pages.shape[2]
        max_len = tables.shape[1] * page
        q = jnp.matmul(x, self.q_proj_weight._data).reshape(b, s, -1, hd)
        k = jnp.matmul(x, self.k_proj_weight._data).reshape(b, s, -1, hd)
        v = jnp.matmul(x, self.v_proj_weight._data).reshape(b, s, -1, hd)
        nkv = k.shape[2]
        q, k = apply_rotary_pos_emb(q, k, cos, sin)
        seq_ids = jnp.repeat(jnp.arange(b, dtype=jnp.int32), s)
        # pad rows of a final chunk land past the prompt; clipping keeps the
        # scatter in-table (garbage there is masked, then overwritten as
        # decode advances — the standard padded-prefill invariant)
        positions = jnp.clip(starts[:, None] + jnp.arange(s, dtype=jnp.int32),
                             0, max_len - 1).reshape(-1)
        k_pages, v_pages = append_paged_kv(
            k_pages, v_pages, k.reshape(b * s, nkv, hd),
            v.reshape(b * s, nkv, hd), tables, positions,
            seq_ids)
        out = paged_prefill_attention(q, k_pages, v_pages, tables, starts)
        out = gather_output_shards(out.reshape(b, s, -1))
        return jnp.matmul(out, self.o_proj_weight._data), k_pages, v_pages

    def paged_token_step(self, x, cos, sin, k_pages, v_pages, tables, pos_vec):
        """ONE token per row at PER-ROW positions (continuous batching:
        every slot is at a different decode offset). x: [b, 1, h];
        cos/sin [b, 1, d] gathered per row; pos_vec [b] int32. Head counts
        come off the weight shapes so a tp shard (local heads, local kv
        pages) runs the same body; see paged_prefill_chunk."""
        from ...ops.paged_attention import append_paged_kv, paged_decode_attention

        x = x._data if isinstance(x, Tensor) else x
        b = x.shape[0]
        hd = self.config.head_dim
        q = jnp.matmul(x, self.q_proj_weight._data).reshape(b, 1, -1, hd)
        k = jnp.matmul(x, self.k_proj_weight._data).reshape(b, 1, -1, hd)
        v = jnp.matmul(x, self.v_proj_weight._data).reshape(b, 1, -1, hd)
        q, k = apply_rotary_pos_emb(q, k, cos, sin)
        k_pages, v_pages = append_paged_kv(
            k_pages, v_pages, k[:, 0], v[:, 0], tables, pos_vec)
        out = paged_decode_attention(q[:, 0], k_pages, v_pages, tables,
                                     pos_vec + 1)
        out = gather_output_shards(out.reshape(b, 1, -1))
        return jnp.matmul(out, self.o_proj_weight._data), k_pages, v_pages


def _attention(q, k, v, config, attn_bias=None):
    """Causal attention on raw arrays; routes to the Pallas kernel on TPU.

    Routing under a mesh:
      - no mesh / 1-device mesh → direct Pallas flash attention
      - sep (context-parallel) axis sharded → ring attention (ppermute over ICI)
      - dp/fsdp/tp sharded, seq whole → shard_map over (batch, heads), Pallas
        flash attention per shard (batched GQA kept in the index_map)
    """
    if config.use_flash_attention and attn_bias is None:
        from ...ops.flash_attention import flash_attention as fa
        from ...distributed.auto_parallel.pipeline import in_manual_pipeline

        mesh = current_mesh()
        if in_manual_pipeline():
            # inside shard_map(pp): no nested manual meshes — plain attention,
            # GSPMD still shards batch/heads over the auto axes
            from ...nn.functional.flash_attention import _xla_attention

            return _xla_attention(q, k, v, bias=attn_bias, causal=True)
        if mesh is None or mesh.size == 1:
            return fa(q, k, v, causal=True)
        sep = mesh.shape.get("sep", 1)
        if sep > 1:
            from ...ops.ring_attention import ring_attention

            return ring_attention(q, k, v, mesh, axis_name="sep", causal=True)
        from ...framework.jax_compat import shard_map
        from ...distributed.auto_parallel.logical_sharding import logical_to_spec

        tp = mesh.shape.get("tp", 1)
        dbatch = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        if q.shape[0] % dbatch == 0 and q.shape[2] % tp == 0 and k.shape[2] % tp == 0:
            qspec = logical_to_spec(("batch", None, "heads", None), mesh)
            kspec = logical_to_spec(("batch", None, "kv_heads", None), mesh)
            f = shard_map(
                lambda a, b, c: fa(a, b, c, causal=True),
                mesh=mesh,
                in_specs=(qspec, kspec, kspec),
                out_specs=qspec,
                check_vma=False,
            )
            return f(q, k, v)
    from ...nn.functional.flash_attention import _xla_attention

    return _xla_attention(q, k, v, bias=attn_bias, causal=True)


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        init = I.Normal(std=config.initializer_range)
        mk = lambda din, dout: self.create_parameter(
            [din, dout], dtype=config.dtype, default_initializer=init)
        self.gate_proj_weight = annotate(mk(h, m), "embed", "mlp")
        self.up_proj_weight = annotate(mk(h, m), "embed", "mlp")
        self.down_proj_weight = annotate(mk(m, h), "mlp", "embed")

    def forward(self, x):
        from jax.ad_checkpoint import checkpoint_name

        x = x._data if isinstance(x, Tensor) else x
        g = jnp.matmul(x, self.gate_proj_weight._data)
        u = jnp.matmul(x, self.up_proj_weight._data)
        act = jax.nn.silu(g) * u   # swiglu — XLA fuses this into the matmuls
        act = constrain(act, "batch", "seq", "mlp")
        # named for the 'flash_mlp' remat policy (saveable, not saved by default)
        act = checkpoint_name(act, "mlp_act")
        # serving tp shard: gate/up are column-sharded, so the activation is
        # mlp-sharded — gather it whole before the replicated down_proj
        # (no-op outside a serving shard_map; see serving_sharding.py)
        act = gather_output_shards(act)
        out = jnp.matmul(act, self.down_proj_weight._data)
        return constrain(out, "batch", "seq", "embed")


class LlamaRMSNorm(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.eps = config.rms_norm_eps
        self.weight = annotate(
            self.create_parameter([config.hidden_size], dtype=config.dtype,
                                  default_initializer=I.Constant(1.0)),
            "norm")

    def forward(self, x):
        x = x._data if isinstance(x, Tensor) else x
        dt = x.dtype
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        out = (xf * jax.lax.rsqrt(var + self.eps)).astype(dt)
        return out * self.weight._data


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.input_layernorm = LlamaRMSNorm(config)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)
        if config.num_experts > 1:
            # Mixtral-class MoE FFN: swiglu experts over the ep mesh axis
            from ...incubate.distributed.models.moe import MoELayer, SwiGLUExpertFFN

            self.mlp = MoELayer(
                config.hidden_size, config.num_experts,
                experts=SwiGLUExpertFFN(config.num_experts, config.hidden_size,
                                        config.intermediate_size,
                                        dtype=config.dtype,
                                        initializer_range=config.initializer_range),
                gate=config.moe_gate, top_k=config.moe_topk,
                capacity_factor=config.moe_capacity_factor,
                dispatch_mode=getattr(config, "moe_dispatch", "auto"))
        else:
            self.mlp = LlamaMLP(config)

    def forward(self, hidden, cos, sin, attn_bias=None):
        x = hidden._data if isinstance(hidden, Tensor) else hidden
        x = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_bias)
        y = self.mlp(self.post_attention_layernorm(x))
        x = x + (y._data if isinstance(y, Tensor) else y)
        if self.config.sequence_parallel:
            # Megatron-SP: the residual stream (and the norms computed from
            # it) lives sequence-sharded over sep AND tp between blocks;
            # GSPMD all-gathers into the projections and reduce-scatters out
            # of them (reference ColumnSequenceParallelLinear:427 semantics)
            return constrain(x, "batch", "seq_sp", "embed")
        return constrain(x, "batch", "seq", "embed")

    def decode_step(self, hidden, cos, sin, k_cache, v_cache, pos,
                    pad_bias=None):
        x = hidden._data if isinstance(hidden, Tensor) else hidden
        a, k_cache, v_cache = self.self_attn.decode_step(
            self.input_layernorm(x), cos, sin, k_cache, v_cache, pos,
            pad_bias=pad_bias)
        x = x + a
        y = self.mlp(self.post_attention_layernorm(x))
        x = x + (y._data if isinstance(y, Tensor) else y)
        return x, k_cache, v_cache

    def paged_decode_step(self, hidden, cos, sin, k_pages, v_pages, tables, pos):
        x = hidden._data if isinstance(hidden, Tensor) else hidden
        a, k_pages, v_pages = self.self_attn.paged_decode_step(
            self.input_layernorm(x), cos, sin, k_pages, v_pages, tables, pos)
        x = x + a
        y = self.mlp(self.post_attention_layernorm(x))
        x = x + (y._data if isinstance(y, Tensor) else y)
        return x, k_pages, v_pages

    def paged_token_step(self, hidden, cos, sin, k_pages, v_pages, tables,
                         pos_vec):
        x = hidden._data if isinstance(hidden, Tensor) else hidden
        a, k_pages, v_pages = self.self_attn.paged_token_step(
            self.input_layernorm(x), cos, sin, k_pages, v_pages, tables,
            pos_vec)
        x = x + a
        y = self.mlp(self.post_attention_layernorm(x))
        x = x + (y._data if isinstance(y, Tensor) else y)
        return x, k_pages, v_pages

    def paged_prefill_chunk(self, hidden, cos, sin, k_pages, v_pages, tables,
                            starts):
        x = hidden._data if isinstance(hidden, Tensor) else hidden
        a, k_pages, v_pages = self.self_attn.paged_prefill_chunk(
            self.input_layernorm(x), cos, sin, k_pages, v_pages, tables,
            starts)
        x = x + a
        y = self.mlp(self.post_attention_layernorm(x))
        x = x + (y._data if isinstance(y, Tensor) else y)
        return x, k_pages, v_pages


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = I.Normal(std=config.initializer_range)
        self.embed_tokens_weight = annotate(
            self.create_parameter([config.vocab_size, config.hidden_size],
                                  dtype=config.dtype, default_initializer=init),
            "vocab_in", "embed")
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = LlamaRMSNorm(config)

    def embed_and_rope(self, input_ids):
        """Token embedding + rope tables (shared by the plain and pp paths)."""
        ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
        cfg = self.config
        # FSDP-style: all-gather the (embed-sharded) table before the lookup so
        # the gather is local — otherwise GSPMD falls back to full remat.
        table = constrain(self.embed_tokens_weight._data, None, None)
        x = jnp.take(table, ids, axis=0)
        x = constrain(x, "batch", "seq", "embed")
        cos, sin = _rope_cos_sin(ids.shape[1], cfg.head_dim, cfg.rope_theta, x.dtype)
        return x, cos, sin

    def forward(self, input_ids, attn_bias=None):
        cfg = self.config
        x, cos, sin = self.embed_and_rope(input_ids)
        remat = cfg.recompute and isinstance(x, jax.core.Tracer)
        moe = cfg.num_experts > 1
        aux_total = jnp.zeros((), jnp.float32) if moe else 0.0
        every = max(1, getattr(cfg, "remat_every", 1))
        for li, layer in enumerate(self.layers):
            if remat and li % every == 0:
                # closure holds the params (inputs, not recomputed); activations
                # inside the layer are rematerialized in backward — the TPU
                # analogue of fleet/recompute/recompute.py:455. The MoE aux loss
                # must be a checkpoint OUTPUT (reading the gate's side channel
                # outside the remat region would leak a tracer).
                def blk(h, c, s, lyr=layer):
                    y = lyr(h, c, s, attn_bias)
                    a = (_raw(lyr.mlp.get_loss()) if moe
                         else jnp.zeros((), jnp.float32))
                    return y, a

                x, aux = _remat(blk, cfg)(x, cos, sin)
            else:
                x = layer(x, cos, sin, attn_bias)
                aux = _raw(layer.mlp.get_loss()) if moe else 0.0
            if moe:
                aux_total = aux_total + aux
        self._moe_aux = aux_total
        return self.norm(x)


def remat_policy_of(cfg):
    """The jax.checkpoint policy for cfg.remat_policy: 'flash' SAVES the
    attention kernel's out+lse residuals (named in
    ops/flash_attention._flash_fwd) so backward skips re-running the flash
    forward kernel (verified: grad jaxpr drops from 4 to 3 pallas calls);
    'full' (None) recomputes everything."""
    p = getattr(cfg, "remat_policy", "flash")
    if p == "flash":
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse")
    if p == "flash_qkv":
        # additionally saves the rope'd q/k/v heads — kills the qkv-proj +
        # rope + input-norm recompute for ~100MB/layer (853M b4 seq-4096);
        # the remat tax then reduces to o-proj + MLP recompute
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "attn_q", "attn_k", "attn_v")
    if p == "flash_mlp":
        # additionally saves the swiglu product — measured OOM on the 853M
        # seq-4096 batch-4 config (16.8G > 15.75G hbm); viable for smaller
        # models/batches only
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "mlp_act")
    return None


def _remat(fn, cfg):
    return jax.checkpoint(fn, policy=remat_policy_of(cfg))


def _decode_model(model: "LlamaModel", ids, caches, pos, pad_bias=None,
                  rope_offset=None):
    """Run a chunk through all layers with KV caches. ids: [b, s] at absolute
    positions [pos, pos+s); caches: list of (k, v) per layer.

    ``pad_bias``: [b, 1, 1, max_len] additive bias masking left-pad cache
    columns; ``rope_offset``: [b] per-row position shift (left padding moves
    each row's position 0 to its first real token)."""
    cfg = model.config
    table = model.embed_tokens_weight._data
    x = jnp.take(table, ids, axis=0)
    max_len = caches[0][0].shape[1]
    cos_full, sin_full = _rope_cos_sin(max_len, cfg.head_dim, cfg.rope_theta,
                                       x.dtype)
    s = ids.shape[1]
    if rope_offset is None:
        cos = jax.lax.dynamic_slice_in_dim(cos_full, pos, s, 0)
        sin = jax.lax.dynamic_slice_in_dim(sin_full, pos, s, 0)
    else:
        # per-row positions: [b, s] gather -> [b, s, d], clipped at 0 for pads
        positions = jnp.clip(pos + jnp.arange(s)[None, :]
                             - rope_offset[:, None], 0, max_len - 1)
        cos = cos_full[positions]
        sin = sin_full[positions]
    new_caches = []
    for layer, (kc, vc) in zip(model.layers, caches):
        x, kc, vc = layer.decode_step(x, cos, sin, kc, vc, pos,
                                      pad_bias=pad_bias)
        new_caches.append((kc, vc))
    return model.norm(x), new_caches


def _decode_model_paged(model: "LlamaModel", ids, caches, pos):
    """Paged-KV chunk decode: caches = {"kv": [(k_pages, v_pages)] per layer,
    "tables": [b, pages_per_seq]}. Left padding is not supported on this path
    (generate() rejects attention_mask with cache_impl='paged')."""
    cfg = model.config
    x = jnp.take(model.embed_tokens_weight._data, ids, axis=0)
    tables = caches["tables"]
    page = caches["kv"][0][0].shape[2]
    max_len = tables.shape[1] * page
    cos_full, sin_full = _rope_cos_sin(max_len, cfg.head_dim, cfg.rope_theta,
                                       x.dtype)
    s = ids.shape[1]
    cos = jax.lax.dynamic_slice_in_dim(cos_full, pos, s, 0)
    sin = jax.lax.dynamic_slice_in_dim(sin_full, pos, s, 0)
    new_kv = []
    for layer, (kp, vp) in zip(model.layers, caches["kv"]):
        x, kp, vp = layer.paged_decode_step(x, cos, sin, kp, vp, tables, pos)
        new_kv.append((kp, vp))
    return model.norm(x), {"kv": new_kv, "tables": tables}


class LlamaForCausalLM(GenerationMixin, Layer):
    #: serving-mesh opt-in (inference/serving.py MeshConfig): the paged
    #: hooks derive head counts from weight shapes and gather
    #: column-sharded outputs, so they run correctly as tp shards inside
    #: the engine's shard_map. Models whose paged hooks slice fused or
    #: interleaved projections (gpt's qkv) must NOT set this — a column
    #: shard of the fused weight would mix q/k/v.
    tp_serving = True

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head_weight = None
        else:
            init = I.Normal(std=config.initializer_range)
            self.lm_head_weight = annotate(
                self.create_parameter([config.hidden_size, config.vocab_size],
                                      dtype=config.dtype, default_initializer=init),
                "embed", "vocab")

    def _lm_head_w(self):
        """[hidden, vocab] projection — tied embedding transpose or lm_head."""
        return (self.model.embed_tokens_weight._data.T
                if self.lm_head_weight is None else self.lm_head_weight._data)

    def logits(self, hidden):
        out = jnp.matmul(hidden, self._lm_head_w())
        if self.lm_head_weight is not None:
            # serving tp shard: an UNTIED lm_head is vocab-column-sharded, so
            # gather the full-vocab logits before sampling/argmax (no-op
            # outside a serving shard_map; tied heads ride the replicated
            # embedding and are already full-width)
            out = gather_output_shards(out)
        return constrain(out, "batch", "seq", "vocab")

    def forward(self, input_ids, labels=None, attn_bias=None):
        hidden = self.model(input_ids, attn_bias)
        logits = self.logits(hidden)
        if labels is None:
            return Tensor(logits) if not isinstance(logits, jax.core.Tracer) else logits
        loss = LlamaPretrainingCriterion.compute(logits, _raw(labels))
        return loss

    def paged_token_step(self, toks, caches, pos_vec):
        """Continuous-batching hook: ONE token per slot at per-slot positions.
        toks [b] int32, pos_vec [b] int32, caches from _init_paged_caches.
        Returns (logits [b, vocab] f32, caches).

        Contract the serving engine's fused mega-step leans on
        (inference/serving.py): inactive rows arrive at pos_vec == 0 with
        their table row pointing at a parking page — the dummy k/v append
        must land wherever THAT table maps (never a page another row
        shares), and the row's logits are computed but ignored. This body
        runs inside a lax.scan over all max_batch rows; everything here
        must stay shape-static in the row count."""
        cfg = self.config
        model = self.model
        x = jnp.take(model.embed_tokens_weight._data, toks[:, None], axis=0)
        tables = caches["tables"]
        page = caches["kv"][0][0].shape[2]
        max_len = tables.shape[1] * page
        cos_full, sin_full = _rope_cos_sin(max_len, cfg.head_dim,
                                           cfg.rope_theta, x.dtype)
        posc = jnp.clip(pos_vec, 0, max_len - 1)
        cos = cos_full[posc][:, None, :]
        sin = sin_full[posc][:, None, :]
        new_kv = []
        for layer, (kp, vp) in zip(model.layers, caches["kv"]):
            x, kp, vp = layer.paged_token_step(x, cos, sin, kp, vp, tables,
                                               pos_vec)
            new_kv.append((kp, vp))
        hidden = model.norm(x)
        hidden = hidden._data if isinstance(hidden, Tensor) else hidden
        logits = self.logits(hidden[:, -1:])
        return logits[:, -1].astype(jnp.float32), {"kv": new_kv,
                                                   "tables": tables}

    def paged_prefill_chunk(self, ids, caches, starts):
        """Serving hook: prefill ONE chunk per row at per-row absolute
        offsets, attending over the already-cached prefix (prefix-cache /
        chunked-prefill path — inference/serving.py). ids [b, s] int32,
        starts [b] int32; returns updated caches only (the first sampled
        token comes from the subsequent paged_token_step re-step, so no
        lm-head work here).

        Packed-rows contract (the fused engine's ``_run_pack``): several
        rows may carry the SAME sequence's table at different ``starts``
        (multiple chunks of one prompt in one call), plus parked dummy
        rows. Per layer, every row's k/v is appended BEFORE attention
        gathers — so a later chunk reads an earlier chunk's pages written
        in this very program; the absolute-position mask keeps the result
        bit-identical to sequential chunk calls (see
        ops.paged_prefill_attention)."""
        cfg = self.config
        model = self.model
        x = jnp.take(model.embed_tokens_weight._data, ids, axis=0)
        tables = caches["tables"]
        page = caches["kv"][0][0].shape[2]
        max_len = tables.shape[1] * page
        cos_full, sin_full = _rope_cos_sin(max_len, cfg.head_dim,
                                           cfg.rope_theta, x.dtype)
        s = ids.shape[1]
        positions = jnp.clip(starts[:, None] + jnp.arange(s)[None, :],
                             0, max_len - 1)
        cos = cos_full[positions]
        sin = sin_full[positions]
        new_kv = []
        for layer, (kp, vp) in zip(model.layers, caches["kv"]):
            x, kp, vp = layer.paged_prefill_chunk(x, cos, sin, kp, vp,
                                                  tables, starts)
            new_kv.append((kp, vp))
        return {"kv": new_kv, "tables": tables}

    def paged_verify_step(self, toks, caches, pos_vec):
        """Speculative-decode VERIFY hook (inference/serving.py spec
        mega-step): score a K+1-token window per row in ONE pass.

        ``toks`` [b, s] int32 — per row the window
        ``[last_token, draft_1..draft_K]`` at absolute positions
        ``pos_vec[b] + i``; returns (logits [b, s, vocab] f32, caches) with
        the window's k/v appended. The body is the K-wide sibling of
        ``paged_token_step``: same embed/rope/layer math run through the
        chunk machinery (``paged_prefill_chunk`` layers over
        ``ops.paged_verify_attention``'s append-then-gather +
        absolute-position masking), plus the lm head over EVERY window
        position — so position i's logits match what a sequential
        ``paged_token_step`` at that position would compute given the same
        cache bytes (the greedy byte-identity the engine's in-graph
        accept/reject rests on). Honors the parked-row contract: inactive
        rows arrive at pos_vec == 0 over a parking-page table; their
        appends and logits are inert."""
        cfg = self.config
        model = self.model
        ids = toks
        x = jnp.take(model.embed_tokens_weight._data, ids, axis=0)
        tables = caches["tables"]
        page = caches["kv"][0][0].shape[2]
        max_len = tables.shape[1] * page
        cos_full, sin_full = _rope_cos_sin(max_len, cfg.head_dim,
                                           cfg.rope_theta, x.dtype)
        s = ids.shape[1]
        positions = jnp.clip(pos_vec[:, None] + jnp.arange(s)[None, :],
                             0, max_len - 1)
        cos = cos_full[positions]
        sin = sin_full[positions]
        new_kv = []
        for layer, (kp, vp) in zip(model.layers, caches["kv"]):
            x, kp, vp = layer.paged_prefill_chunk(x, cos, sin, kp, vp,
                                                  tables, pos_vec)
            new_kv.append((kp, vp))
        hidden = model.norm(x)
        hidden = hidden._data if isinstance(hidden, Tensor) else hidden
        logits = self.logits(hidden)
        return logits.astype(jnp.float32), {"kv": new_kv, "tables": tables}

    def remat_policy(self):
        """Engine hook: the jax.checkpoint policy for this model's blocks."""
        return remat_policy_of(self.config)

    def moe_aux_loss(self):
        """Sum of gate load-balance losses from the last forward (0 if dense).

        Collected as checkpoint outputs during LlamaModel.forward — safe under
        recompute (reading gate side channels here would leak remat tracers).
        """
        if self.config.num_experts <= 1:
            return 0.0
        return getattr(self.model, "_moe_aux", 0.0)

    def _decode_chunk(self, ids, caches, pos, pad_bias, pos_offset):
        if isinstance(caches, dict):  # paged-KV serving path
            hidden, caches = _decode_model_paged(self.model, ids, caches, pos)
        else:
            hidden, caches = _decode_model(self.model, ids, caches, pos,
                                           pad_bias, pos_offset)
        hidden = hidden._data if isinstance(hidden, Tensor) else hidden
        # lm head only on the position we sample from
        logits = self.logits(hidden[:, -1:])
        return logits[:, -1].astype(jnp.float32), caches

    def loss_fn(self, input_ids, labels):
        """Raw-array loss for jit'ed training steps."""
        hidden = self.model(input_ids)
        loss = self._lm_loss(hidden, labels)
        if self.config.num_experts > 1:
            loss = loss + self.config.moe_aux_weight * self.moe_aux_loss()
        return loss

    def _lm_loss(self, hidden, labels):
        """Shifted CE from final hidden states; fused-chunked by default."""
        hidden = hidden._data if isinstance(hidden, Tensor) else hidden
        if self.config.fused_ce:
            from ...ops.fused_ce import fused_linear_cross_entropy

            return fused_linear_cross_entropy(
                hidden, self._lm_head_w(), _raw(labels),
                chunk=self.config.fused_ce_chunk)
        return LlamaPretrainingCriterion.compute(self.logits(hidden),
                                                 _raw(labels))

    # ---- pipeline-parallel protocol (used by Engine when mesh has pp > 1) ----
    @property
    def pipeline_with_aux(self) -> bool:
        """Blocks emit a scalar aux output (MoE gate load-balance loss)."""
        return self.config.num_experts > 1

    def pipeline_blocks(self):
        """The homogeneous block stack to be sharded over the pp axis."""
        return list(self.model.layers)

    def pipeline_loss(self, input_ids, labels, run_blocks):
        """Loss with the decoder stack replaced by ``run_blocks(x, cos, sin)``.

        Embedding / final norm / lm-head run outside the pipeline (replicated
        over pp, sharded over the other axes) — the analogue of the reference
        putting embedding+head on first/last stages (pp_layers.py SharedLayerDesc),
        collapsed here because GSPMD dedupes replicated compute. ``run_blocks``
        may return ``(x, aux)`` — the per-microbatch-averaged MoE gate loss.
        """
        x, cos, sin = self.model.embed_and_rope(input_ids)
        res = run_blocks(x, cos, sin)
        x, aux = res if isinstance(res, tuple) else (res, None)
        x = self.model.norm(x)
        x = x._data if isinstance(x, Tensor) else x
        loss = self._lm_loss(x, labels)
        if aux is not None:
            loss = loss + self.config.moe_aux_weight * aux
        return loss

    def pipeline_block_fn(self, block):
        """Functional single-block forward for stacked-param execution."""
        tensors = [t for _, t in block.named_parameters()]
        with_aux = self.pipeline_with_aux

        def fn(param_arrays, x, cos, sin):
            from ...jit.api import _Swap

            with _Swap(tensors, param_arrays):
                y = block(x, cos, sin)
                if with_aux:
                    return y, _raw(block.mlp.get_loss())
                return y

        return fn


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class LlamaPretrainingCriterion(Layer):
    """Shifted causal-LM cross entropy, fp32 softmax (bf16-safe)."""

    @staticmethod
    def compute(logits, labels, ignore_index: int = -100):
        lg = logits[:, :-1, :].astype(jnp.float32)
        lb = labels[:, 1:]
        logz = jax.scipy.special.logsumexp(lg, axis=-1)
        picked = jnp.take_along_axis(lg, lb[..., None].astype(jnp.int32), axis=-1)[..., 0]
        nll = logz - picked
        mask = (lb != ignore_index)
        nll = jnp.where(mask, nll, 0.0)
        return nll.sum() / jnp.maximum(mask.sum().astype(jnp.float32), 1.0)

    def forward(self, prediction_scores, masked_lm_labels):
        return Tensor(self.compute(_raw(prediction_scores), _raw(masked_lm_labels)))
