"""hapi Model — Keras-style fit/evaluate/predict (reference: python/paddle/hapi/model.py:1082).

TPU-native: ``prepare(jit=True)`` (default) compiles the whole train step — forward,
loss, backward, optimizer update — into ONE XLA executable over the parameter pytree
(functionalized via paddle_tpu.jit), with buffer donation on params/opt-state. This is
the redesign of the reference's dygraph train loop + _ExecutorCache static path.
"""

from __future__ import annotations

import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd_engine
from ..core.tensor import Tensor
from ..framework.random import next_key, rng_guard
from ..jit.api import _collect_state, _Swap
from ..metric import Metric
from ..nn.layer.layers import Layer
from .callbacks import CallbackList, ProgBarLogger


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._jit = True
        self._train_step_fn = None
        self._eval_fn = None
        self.stop_training = False

    # ---- configuration ----
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None, jit=True):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        self._jit = jit
        self._train_step_fn = None
        self._eval_fn = None

    # ---- jitted train step ----
    def _build_train_step(self):
        layer = self.network
        loss_fn = self._loss
        opt = self._optimizer
        names, tensors = _collect_state(layer)
        param_mask = [n.startswith("P:") and getattr(t, "trainable", True) and not t.stop_gradient
                      for n, t in zip(names, tensors)]

        def forward_loss(state_arrays, x_arrays, y_arrays, key):
            with autograd_engine.no_grad(), _Swap(tensors, state_arrays), rng_guard(key):
                xs = [Tensor(a) for a in x_arrays]
                ys = [Tensor(a) for a in y_arrays]
                out = layer(*xs)
                outs = out if isinstance(out, (list, tuple)) else [out]
                loss = loss_fn(*outs, *ys)
                if isinstance(loss, (list, tuple)):
                    loss = loss[0]
                preds = [o._data for o in outs]
                # buffer updates staged during the traced forward (e.g. BN stats)
                buf_updates = {}
                for i, t in enumerate(tensors):
                    upd = t.__dict__.pop("_pending_update", None)
                    if upd is not None:
                        buf_updates[i] = upd
                return loss._data, (preds, buf_updates)

        grad_fn = jax.value_and_grad(forward_loss, argnums=0, has_aux=True)
        clip = opt._grad_clip

        def train_step(state_arrays, opt_state, x_arrays, y_arrays, key, lr, step_no):
            (loss, (preds, buf_updates)), grads = grad_fn(state_arrays, x_arrays, y_arrays, key)
            p_idx = [i for i, m in enumerate(param_mask) if m and grads[i] is not None]
            p_grads = [grads[i].astype(jnp.float32) for i in p_idx]
            if clip is not None:
                from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

                if isinstance(clip, ClipGradByGlobalNorm):
                    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in p_grads))
                    scale = clip.clip_norm / jnp.maximum(gnorm, clip.clip_norm)
                    p_grads = [g * scale for g in p_grads]
                elif isinstance(clip, ClipGradByNorm):
                    p_grads = [
                        g * jnp.minimum(clip.clip_norm / jnp.maximum(jnp.sqrt(jnp.sum(jnp.square(g))), 1e-12), 1.0)
                        for g in p_grads
                    ]
                elif isinstance(clip, ClipGradByValue):
                    p_grads = [jnp.clip(g, clip.min, clip.max) for g in p_grads]
            p_vals = [state_arrays[i] for i in p_idx]
            p_params = [tensors[i] for i in p_idx]
            new_vals, new_opt_state = opt._functional_update(p_grads, p_vals, p_params, opt_state, lr, step_no)
            new_state = list(state_arrays)
            for i, v in zip(p_idx, new_vals):
                new_state[i] = v
            for i, v in buf_updates.items():
                new_state[i] = v
            return loss, preds, new_state, new_opt_state

        self._jitted = jax.jit(train_step, donate_argnums=(0, 1))
        self._state_tensors = tensors
        self._param_mask = param_mask
        self._opt_state = {}

        def step(x_list, y_list):
            opt._step_count += 1
            state_arrays = [t._data for t in tensors]
            lr = opt.get_lr()
            loss, preds, new_state, self._opt_state = self._jitted(
                state_arrays,
                self._opt_state,
                [x._data for x in x_list],
                [y._data for y in y_list],
                next_key(),
                jnp.float32(lr),
                jnp.int32(opt._step_count),
            )
            for t, a in zip(tensors, new_state):
                t._data = a
            return loss, preds

        return step

    def _eager_train_step(self, x_list, y_list):
        out = self.network(*x_list)
        outs = out if isinstance(out, (list, tuple)) else [out]
        loss = self._loss(*outs, *y_list)
        if isinstance(loss, (list, tuple)):
            loss = loss[0]
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return loss._data, [o._data for o in outs]

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        x_list = self._as_list(inputs)
        y_list = self._as_list(labels)
        if self._jit:
            if self._train_step_fn is None:
                self._train_step_fn = self._build_train_step()
            loss, preds = self._train_step_fn(x_list, y_list)
        else:
            loss, preds = self._eager_train_step(x_list, y_list)
        metrics = self._update_metrics(preds, y_list)
        return [float(np.asarray(loss))], metrics

    @autograd_engine.no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        x_list = self._as_list(inputs)
        y_list = self._as_list(labels)
        out = self.network(*x_list)
        outs = out if isinstance(out, (list, tuple)) else [out]
        loss = None
        if self._loss is not None and y_list:
            loss = self._loss(*outs, *y_list)
            if isinstance(loss, (list, tuple)):
                loss = loss[0]
        metrics = self._update_metrics([o._data for o in outs], y_list)
        return ([float(np.asarray(loss._data))] if loss is not None else []), metrics

    @autograd_engine.no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        out = self.network(*self._as_list(inputs))
        return out

    def _update_metrics(self, preds, y_list):
        results = []
        for m in self._metrics:
            inp = m.compute(Tensor(preds[0]), *y_list)
            r = m.update(np.asarray(inp._data if isinstance(inp, Tensor) else inp))
            results.append(r)
        return results

    @staticmethod
    def _as_list(x):
        if x is None:
            return []
        return list(x) if isinstance(x, (list, tuple)) else [x]

    # ---- high level ----
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1,
            log_freq=10, save_dir=None, save_freq=1, verbose=2, drop_last=False,
            shuffle=True, num_workers=0, callbacks=None, accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader
        from ..io.dataset import Dataset

        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                                      drop_last=drop_last, num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        else:
            eval_loader = eval_data

        cbks = CallbackList(callbacks, model=self, verbose=verbose,
                            metrics=["loss"] + [m.name() for m in self._metrics], log_freq=log_freq)
        cbks.on_begin("train")
        steps = None
        try:
            steps = len(train_loader)
        except TypeError:
            pass
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch, {"steps": steps})
            for m in self._metrics:
                m.reset()
            it = 0
            for batch in train_loader:
                data = self._split_batch(batch)
                cbks.on_batch_begin("train", it, {})
                losses, metrics = self.train_batch(*data)
                logs = {"loss": losses[0]}
                for m, r in zip(self._metrics, metrics):
                    logs[m.name()] = r
                cbks.on_batch_end("train", it, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            if isinstance(self._optimizer._lr, object) and hasattr(self._optimizer, "_lr_step"):
                self._optimizer._lr_step()
            epoch_logs = {"loss": losses[0]}
            for m in self._metrics:
                epoch_logs[m.name()] = m.accumulate()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0, _as_dict=True)
                epoch_logs.update({"eval_" + k: v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, epoch_logs)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
        cbks.on_end("train")
        if save_dir is not None:
            self.save(f"{save_dir}/final")

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0,
                 callbacks=None, num_iters=None, _as_dict=False):
        from ..io import DataLoader
        from ..io.dataset import Dataset

        loader = DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers) \
            if isinstance(eval_data, Dataset) else eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        it = 0
        for batch in loader:
            data = self._split_batch(batch)
            l, _ = self.eval_batch(*data)
            if l:
                losses.append(l[0])
            it += 1
            if num_iters is not None and it >= num_iters:
                break
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, verbose=1, callbacks=None):
        from ..io import DataLoader
        from ..io.dataset import Dataset

        loader = DataLoader(test_data, batch_size=batch_size, num_workers=num_workers) \
            if isinstance(test_data, Dataset) else test_data
        outputs = []
        for batch in loader:
            data = self._split_batch(batch)
            out = self.predict_batch(data[0])
            outputs.append(out)
        return outputs

    def _split_batch(self, batch):
        """Split a loader batch into (inputs, labels) following hapi convention."""
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return [batch[:-1], [batch[-1]]] if len(batch) > 2 else [[batch[0]], [batch[1]]]
        return [[batch], []]

    # ---- persistence ----
    def save(self, path, training=True):
        from ..framework import io as fio

        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import io as fio

        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state)
        import os

        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fio.load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size)

    def flops(self, input_size, print_detail=False):
        """FLOPs of one forward at ``input_size`` (XLA cost model — see
        ``paddle.flops``)."""
        from .. import flops as _flops

        return _flops(self.network, input_size, print_detail=print_detail)
