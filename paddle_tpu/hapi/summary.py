"""Model summary (reference: python/paddle/hapi/model_summary.py)."""

from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total_params = 0
    trainable = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = 0
        for p in layer._parameters.values():
            if p is None:
                continue
            n = int(np.prod(p.shape)) if p.shape else 1
            n_params += n
        if n_params or not layer._sub_layers:
            rows.append((name or layer.__class__.__name__, layer.__class__.__name__, n_params))
    seen = set()
    for p in net.parameters():
        if id(p) in seen:
            continue
        seen.add(id(p))
        n = int(np.prod(p.shape)) if p.shape else 1
        total_params += n
        if getattr(p, "trainable", True):
            trainable += n
    width = max((len(r[0]) for r in rows), default=20) + 2
    print(f"{'Layer':<{width}}{'Type':<28}{'Params':>12}")
    print("-" * (width + 40))
    for name, typ, n in rows:
        print(f"{name:<{width}}{typ:<28}{n:>12,}")
    print("-" * (width + 40))
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable:,}")
    return {"total_params": total_params, "trainable_params": trainable}
