"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import numbers
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None, model=None, verbose=2, metrics=None, log_freq=10):
        cbs = list(callbacks) if callbacks else []
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.insert(0, ProgBarLogger(log_freq, verbose=verbose))
        self.callbacks = cbs
        for c in self.callbacks:
            c.set_model(model)
            c.set_params({"verbose": verbose, "metrics": metrics or []})

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_begin")(logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_end")(logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_begin")(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_end")(step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = (logs or {}).get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            msg = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            total = f"/{self.steps}" if self.steps else ""
            print(f"step {step}{total} - {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            msg = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {msg}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            self.model._optimizer._lr_step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            self.model._optimizer._lr_step()


class VisualDL(Callback):
    """Reference logs to VisualDL; here: CSV/JSONL scalars for TensorBoard-free envs."""

    def __init__(self, log_dir="./vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None

    def on_train_begin(self, logs=None):
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(f"{self.log_dir}/scalars.jsonl", "a")

    def on_train_batch_end(self, step, logs=None):
        import json

        if self._f:
            self._f.write(json.dumps({"step": step, **{k: float(v) if isinstance(v, numbers.Number) else str(v) for k, v in (logs or {}).items()}}) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


class ReduceLROnPlateau(Callback):
    """Reduce LR when a monitored metric plateaus (reference:
    hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.greater = mode == "max" or (mode == "auto" and "acc" in monitor)
        self._best = None
        self._wait = 0
        self._cool = 0

    def _better(self, cur):
        if self._best is None:
            return True
        if self.greater:
            return cur > self._best + self.min_delta
        return cur < self._best - self.min_delta

    def on_eval_end(self, logs=None):
        self._check(logs or {})

    def on_epoch_end(self, epoch, logs=None):
        self._check(logs or {})

    def _check(self, logs):
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self._better(cur):
            self._best = cur
            self._wait = 0
            return
        if self._cool > 0:
            # cooling down: hold the LR, don't accumulate patience
            self._cool -= 1
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                from ..optimizer.lr import LRScheduler

                if isinstance(opt._lr, LRScheduler):
                    import warnings

                    warnings.warn(
                        "ReduceLROnPlateau: optimizer uses an LRScheduler — "
                        "set_lr would replace the schedule; skipping the "
                        "reduction (reference paddle raises here)")
                else:
                    old = opt.get_lr()
                    new = max(old * self.factor, self.min_lr)
                    if new < old:
                        opt.set_lr(new)
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
            self._cool = self.cooldown
            self._wait = 0


class WandbCallback(Callback):
    """Weights & Biases logging (reference: hapi/callbacks.py WandbCallback).
    Gated on the wandb package being importable; otherwise a no-op logger."""

    def __init__(self, project=None, name=None, dir=None, mode=None, **kwargs):
        super().__init__()
        self._kw = dict(project=project, name=name, dir=dir, mode=mode,
                        **kwargs)
        try:
            import wandb  # noqa: F401

            self._wandb = wandb
        except ImportError:
            self._wandb = None

    def on_train_begin(self, logs=None):
        if self._wandb is not None:
            self._run = self._wandb.init(**{k: v for k, v in self._kw.items()
                                            if v is not None})

    def on_epoch_end(self, epoch, logs=None):
        if self._wandb is not None and logs:
            self._wandb.log({f"train/{k}": v for k, v in logs.items()},
                            step=epoch)

    def on_train_end(self, logs=None):
        if self._wandb is not None:
            self._wandb.finish()
