"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import numbers
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None, model=None, verbose=2, metrics=None, log_freq=10):
        cbs = list(callbacks) if callbacks else []
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.insert(0, ProgBarLogger(log_freq, verbose=verbose))
        self.callbacks = cbs
        for c in self.callbacks:
            c.set_model(model)
            c.set_params({"verbose": verbose, "metrics": metrics or []})

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_begin")(logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_end")(logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_begin")(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_end")(step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = (logs or {}).get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            msg = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            total = f"/{self.steps}" if self.steps else ""
            print(f"step {step}{total} - {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            msg = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {msg}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            self.model._optimizer._lr_step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            self.model._optimizer._lr_step()


class VisualDL(Callback):
    """Reference logs to VisualDL; here: CSV/JSONL scalars for TensorBoard-free envs."""

    def __init__(self, log_dir="./vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None

    def on_train_begin(self, logs=None):
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(f"{self.log_dir}/scalars.jsonl", "a")

    def on_train_batch_end(self, step, logs=None):
        import json

        if self._f:
            self._f.write(json.dumps({"step": step, **{k: float(v) if isinstance(v, numbers.Number) else str(v) for k, v in (logs or {}).items()}}) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
