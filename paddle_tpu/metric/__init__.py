"""paddle_tpu.metric (reference: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Metric:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred)
        label_np = np.asarray(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = top == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            tot = c.shape[0] if c.ndim > 1 else 1
            self.total[i] += float(num)
            self.count[i] += int(np.prod(c.shape[:-1]))
            accs.append(float(num) / max(int(np.prod(c.shape[:-1])), 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = np.asarray(labels).reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0, self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (new_pos + tot_pos) / 2
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp

    from ..core.tensor import unwrap

    pred = unwrap(input)
    lab = unwrap(label)
    if lab.ndim == pred.ndim and lab.shape[-1] == 1:
        lab = lab[..., 0]
    topk = jnp.argsort(-pred, axis=-1)[..., :k]
    hit = (topk == lab[..., None]).any(axis=-1)
    return Tensor(hit.mean(dtype=jnp.float32))
