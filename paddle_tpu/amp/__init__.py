"""paddle_tpu.amp — automatic mixed precision (reference: python/paddle/amp).

TPU-first: bf16 is the native fast dtype (MXU), needs no loss scaling; fp16 +
GradScaler kept for API parity. ``auto_cast`` installs an AMP state consulted by the
op dispatcher (core/op_registry.py) exactly where the reference's generated ad_funcs
call AmpAutoCasts (eager_manual/forwards/add_n_fwd_func.cc:31-50).
"""

from __future__ import annotations

import contextlib

from ..core import op_registry
from ..core.dtype import convert_dtype
from .amp_lists import BLACK_LIST, WHITE_LIST
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401


class _AmpState:
    def __init__(self, enabled, dtype, level, custom_white, custom_black):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.white = set(WHITE_LIST) | set(custom_white or ())
        self.black = set(BLACK_LIST) | set(custom_black or ())
        self.low_precision_ops = {}

    def classify(self, op_name, default_cat):
        if op_name in self.black:
            return op_registry.AMP_BLACK
        if op_name in self.white:
            return op_registry.AMP_WHITE
        if self.level == "O2":
            # pure-low-precision mode: everything except black runs low precision
            return op_registry.AMP_WHITE if default_cat != op_registry.AMP_BLACK else op_registry.AMP_BLACK
        return default_cat

    def record_op(self, name):
        self.low_precision_ops[name] = self.low_precision_ops.get(name, 0) + 1


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16", use_promote=True):
    """Reference: amp/auto_cast.py:1014. Default dtype is bfloat16 on TPU."""
    prev = op_registry.amp_state
    dt = convert_dtype("float16" if dtype == "float16" else "bfloat16")
    op_registry.amp_state = _AmpState(enable, dt, level, custom_white_list, custom_black_list)
    try:
        yield
    finally:
        op_registry.amp_state = prev


amp_guard = auto_cast


def is_auto_cast_enabled():
    st = op_registry.amp_state
    return bool(st and st.enabled)


def get_amp_dtype():
    st = op_registry.amp_state
    from ..core.dtype import dtype_name

    return dtype_name(st.dtype) if st else "float32"


def decorate(models, optimizers=None, level="O1", dtype="bfloat16", master_weight=None,
             save_dtype=None, master_grad=False, excluded_layers=None):
    """Reference: amp/auto_cast.py decorate — O2 casts model params to low precision."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        dt = convert_dtype("float16" if dtype == "float16" else "bfloat16")
        import jax.numpy as jnp

        for m in model_list:
            skip = set()
            if excluded_layers:
                excl = excluded_layers if isinstance(excluded_layers, (list, tuple)) else [excluded_layers]
                for l in m.sublayers(include_self=True):
                    if isinstance(l, tuple(e for e in excl if isinstance(e, type))) or l in excl:
                        skip.add(id(l))
            for l in m.sublayers(include_self=True):
                from ..nn.layer.norm import LayerNorm, _BatchNormBase

                if id(l) in skip or isinstance(l, (_BatchNormBase, LayerNorm)):
                    continue
                for p in l._parameters.values():
                    if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                        p._data = p._data.astype(dt)
            m._casted_by_pure_fp16 = True
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


from . import debugging  # noqa: F401,E402


def is_float16_supported(device=None):
    """fp16 compute support (TPU MXU is bf16-first; fp16 emulated)."""
    import jax

    return jax.devices()[0].platform in ("gpu", "tpu")


def is_bfloat16_supported(device=None):
    import jax

    return jax.devices()[0].platform in ("tpu", "cpu", "gpu")
