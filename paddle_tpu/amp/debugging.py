"""AMP debugging tools (reference: python/paddle/amp/debugging.py)."""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from .. import flags
from ..core import op_registry
from ..core.tensor import Tensor


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Check a tensor for nan/inf (reference: debugging.py check_numerics).

    Detections report into the shared numeric health word
    (framework/numeric_guard.py: NAN_GRAD / INF_GRAD bits, PT-NUM-001/002)
    and then abort or warn per ``debug_mode`` (falling back to the
    ``check_nan_inf_level`` flag the tensor checker sets): ABORT raises a
    FloatingPointError naming the op and var; CHECK_NAN_INF warns."""
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    num_nan = int(jnp.isnan(arr).sum())
    num_inf = int(jnp.isinf(arr).sum())
    if num_nan or num_inf:
        from ..framework import numeric_guard

        numeric_guard.report_nan_inf(num_nan, num_inf,
                                     source=f"{op_type}:{var_name}")
        msg = f"[check_numerics] op={op_type} var={var_name}: {num_nan} nan, {num_inf} inf"
        mode = debug_mode
        if mode is None:
            mode = (DebugMode.CHECK_NAN_INF_AND_ABORT
                    if flags.get_flag("check_nan_inf_level") == 0
                    else DebugMode.CHECK_NAN_INF)
        if mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise FloatingPointError(msg)
        import warnings

        warnings.warn(msg)
    return Tensor(jnp.asarray(num_nan)), Tensor(jnp.asarray(num_inf))


@contextlib.contextmanager
def enable_operator_stats_collection():
    """Collect per-op low-precision execution counts under AMP."""
    flags.set_flags({"low_precision_op_list": 1})
    st = op_registry.amp_state
    try:
        yield
    finally:
        flags.set_flags({"low_precision_op_list": 0})
        if st is not None and st.low_precision_ops:
            print("<------------------------------ op list ------------------------------->")
            for name, count in sorted(st.low_precision_ops.items()):
                print(f"  {name:<40} low-precision calls: {count}")


def collect_operator_stats():
    st = op_registry.amp_state
    return dict(st.low_precision_ops) if st else {}


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


_checker_config = None
_saved_level = None


def enable_tensor_checker(checker_config=None):
    """Arm the eager-dispatch nan/inf checker per the config's debug mode:
    CHECK_NAN_INF_AND_ABORT raises on the first anomalous op output (the
    error names the op), CHECK_NAN_INF warns and keeps going; both report
    into the shared numeric health word."""
    global _checker_config, _saved_level
    cfg = checker_config if checker_config is not None else TensorCheckerConfig()
    if not cfg.enable:
        disable_tensor_checker()
        return
    if _checker_config is None:     # stash the pre-checker level once
        _saved_level = flags.get_flag("check_nan_inf_level")
    _checker_config = cfg
    level = (0 if cfg.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT else 1)
    flags.set_flags({"check_nan_inf": True, "check_nan_inf_level": level})


def disable_tensor_checker():
    """Disarm the checker and restore the pre-enable ``check_nan_inf_level``
    — a warn-mode checker must not permanently downgrade later direct
    check_numerics calls from raise to warn."""
    global _checker_config, _saved_level
    _checker_config = None
    restore = {"check_nan_inf": False}
    if _saved_level is not None:
        restore["check_nan_inf_level"] = _saved_level
        _saved_level = None
    flags.set_flags(restore)


def tensor_checker_config():
    """The active TensorCheckerConfig (None when the checker is off)."""
    return _checker_config


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT, **kw):
        self.enable = enable
        self.debug_mode = debug_mode


class _StatsRecorder:
    """Per-op output statistics collector plugged into the eager dispatcher
    (core/op_registry.stats_recorder). Stats — not tensors — are dumped: the
    reference's comparer also works off per-op summaries unless
    dump_all_tensors is requested (/root/reference/python/paddle/amp/debugging.py:595)."""

    def __init__(self):
        self.records = []

    def record(self, op_name, outs):
        for out_idx, o in enumerate(outs):
            arr = o._data if isinstance(o, Tensor) else o
            if not (hasattr(arr, "dtype")
                    and jnp.issubdtype(arr.dtype, jnp.floating)):
                continue
            a32 = jnp.asarray(arr, jnp.float32)
            finite = jnp.isfinite(a32)
            masked = jnp.where(finite, jnp.abs(a32), 0.0)
            self.records.append({
                "op": op_name,
                "out": out_idx,
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
                "mean_abs": float(masked.sum() / jnp.maximum(finite.sum(), 1)),
                "max_abs": float(masked.max()) if arr.size else 0.0,
                "num_nan": int(jnp.isnan(a32).sum()),
                "num_inf": int(jnp.isinf(a32).sum()),
            })


@contextlib.contextmanager
def dump_tensor_stats(dump_path):
    """Record per-op output stats for every eager op executed in the scope and
    write them as JSONL to ``dump_path`` — the dump format consumed by
    :func:`compare_accuracy`. Ops inside jit-compiled regions are opaque to
    this hook (run the module eagerly for debugging, as the reference does)."""
    import json

    rec = _StatsRecorder()
    prev = op_registry.stats_recorder
    op_registry.stats_recorder = rec
    try:
        yield rec
    finally:
        op_registry.stats_recorder = prev
        with open(dump_path, "w") as f:
            for r in rec.records:
                f.write(json.dumps(r) + "\n")


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False,
                     rtol=1e-2, atol=1e-6):
    """Compare two :func:`dump_tensor_stats` dumps op by op.

    Reference: ``paddle.amp.debugging.compare_accuracy``
    (/root/reference/python/paddle/amp/debugging.py:595) — a run in fp32 and a
    run in low precision are dumped, then aligned by (op, occurrence) and the
    per-op error table is written out. Here the table is CSV at
    ``output_filename``; the return value is the list of rows exceeding
    ``rtol``/``atol`` on mean|max abs (after dividing run-2 stats by
    ``loss_scale``) or introducing nan/inf the first run didn't have.
    """
    import json

    if dump_all_tensors:
        import warnings

        warnings.warn("dump_all_tensors is not supported; comparing op stats")

    def load(p):
        with open(p) as f:
            return [json.loads(line) for line in f if line.strip()]

    a_recs, b_recs = load(dump_path), load(another_dump_path)
    # align by (op, occurrence-index) like the reference's workerlog pairing
    from collections import defaultdict

    def keyed(recs):
        seen = defaultdict(int)
        out = {}
        for r in recs:
            k = (r["op"], r["out"], seen[(r["op"], r["out"])])
            seen[(r["op"], r["out"])] += 1
            out[k] = r
        return out

    a_by, b_by = keyed(a_recs), keyed(b_recs)
    rows, flagged = [], []
    for k in sorted(set(a_by) | set(b_by), key=str):
        ra, rb = a_by.get(k), b_by.get(k)
        row = {"op": k[0], "out": k[1], "call": k[2]}
        if ra is None or rb is None:
            row.update(status="MISSING_IN_" + ("A" if ra is None else "B"))
            rows.append(row)
            flagged.append(row)
            continue
        scale = float(loss_scale) or 1.0
        mean_b, max_b = rb["mean_abs"] / scale, rb["max_abs"] / scale
        mean_err = abs(ra["mean_abs"] - mean_b)
        max_err = abs(ra["max_abs"] - max_b)
        denom_mean = max(abs(ra["mean_abs"]), atol)
        denom_max = max(abs(ra["max_abs"]), atol)
        new_nonfinite = (rb["num_nan"] + rb["num_inf"]) > (
            ra["num_nan"] + ra["num_inf"])
        bad = (mean_err > atol + rtol * denom_mean
               or max_err > atol + rtol * denom_max
               or new_nonfinite)
        row.update(dtype_a=ra["dtype"], dtype_b=rb["dtype"],
                   mean_abs_a=ra["mean_abs"], mean_abs_b=mean_b,
                   max_abs_a=ra["max_abs"], max_abs_b=max_b,
                   mean_abs_err=mean_err, max_abs_err=max_err,
                   nan_inf_a=ra["num_nan"] + ra["num_inf"],
                   nan_inf_b=rb["num_nan"] + rb["num_inf"],
                   status="EXCESS_ERROR" if bad else "OK")
        rows.append(row)
        if bad:
            flagged.append(row)

    import csv

    fields = ["op", "out", "call", "status", "dtype_a", "dtype_b",
              "mean_abs_a", "mean_abs_b", "mean_abs_err",
              "max_abs_a", "max_abs_b", "max_abs_err",
              "nan_inf_a", "nan_inf_b"]
    with open(output_filename, "w", newline="") as f:
        wr = csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
        wr.writeheader()
        wr.writerows(rows)
    return flagged
