"""AMP debugging tools (reference: python/paddle/amp/debugging.py)."""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from .. import flags
from ..core import op_registry
from ..core.tensor import Tensor


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Check a tensor for nan/inf (reference: debugging.py check_numerics)."""
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    num_nan = int(jnp.isnan(arr).sum())
    num_inf = int(jnp.isinf(arr).sum())
    if num_nan or num_inf:
        msg = f"[check_numerics] op={op_type} var={var_name}: {num_nan} nan, {num_inf} inf"
        if flags.get_flag("check_nan_inf_level") == 0:
            raise FloatingPointError(msg)
        import warnings

        warnings.warn(msg)
    return Tensor(jnp.asarray(num_nan)), Tensor(jnp.asarray(num_inf))


@contextlib.contextmanager
def enable_operator_stats_collection():
    """Collect per-op low-precision execution counts under AMP."""
    flags.set_flags({"low_precision_op_list": 1})
    st = op_registry.amp_state
    try:
        yield
    finally:
        flags.set_flags({"low_precision_op_list": 0})
        if st is not None and st.low_precision_ops:
            print("<------------------------------ op list ------------------------------->")
            for name, count in sorted(st.low_precision_ops.items()):
                print(f"  {name:<40} low-precision calls: {count}")


def collect_operator_stats():
    st = op_registry.amp_state
    return dict(st.low_precision_ops) if st else {}


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


def enable_tensor_checker(checker_config=None):
    flags.set_flags({"check_nan_inf": True})


def disable_tensor_checker():
    flags.set_flags({"check_nan_inf": False})


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT, **kw):
        self.enable = enable
        self.debug_mode = debug_mode


def compare_accuracy(dump_path, another_dump_path, output_filename, loss_scale=1, dump_all_tensors=False):
    raise NotImplementedError("accuracy-compare tooling lands with the profiler dump format")
