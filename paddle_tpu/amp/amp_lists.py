"""AMP op lists (reference: python/paddle/amp/amp_lists.py)."""

# ops that are numerically safe + fast in low precision (MXU-bound)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "bmm", "mv", "inner", "outer",
    "einsum", "multi_dot", "scaled_dot_product_attention",
}

# ops that must stay fp32 (reductions / exponentials prone to overflow)
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "cos_sim",
    "softmax", "log_softmax", "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "bce_with_logits", "binary_cross_entropy", "nll_loss", "kl_div",
    "layer_norm", "group_norm", "instance_norm", "batch_norm", "rms_norm",
    "logsumexp", "erfinv", "pow", "cumprod", "prod", "linspace", "acos", "asin",
    "cosh", "sinh", "tan", "atanh", "acosh", "asinh",
}
