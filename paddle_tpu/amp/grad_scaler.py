"""Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py:62 AmpScaler, :645 GradScaler).

Needed for fp16 parity; bf16 training on TPU doesn't require scaling (scaler becomes
a transparent pass-through when ``enable=False``).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0**15, incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _unscale_and_check(self, optimizer):
        """Unscale every grad and set ``found_inf`` with ONE aggregated
        check: per-tensor finiteness reductions stay on device and fold
        into a single scalar — one host sync for the whole parameter list,
        not a round-trip per parameter. A detected overflow reports the
        OVERFLOW bit into the shared numeric health word (PT-NUM-005)."""
        flags = []
        for p in optimizer._parameter_list or []:
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32) / self._scale
            flags.append(jnp.logical_not(jnp.isfinite(g).all()))
            p.grad._data = g.astype(p.grad.dtype)
        found = bool(jnp.stack(flags).any()) if flags else False
        self._found_inf = found
        if found:
            from ..framework import numeric_guard

            numeric_guard.record_health(numeric_guard.OVERFLOW,
                                        source="amp.grad_scaler")

    def minimize(self, optimizer, loss):
        loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self._unscale_and_check(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


class GradScaler(AmpScaler):
    """Reference: grad_scaler.py:645 — public API over AmpScaler."""

    def unscale_(self, optimizer):
        self._unscale_and_check(optimizer)
        # after explicit unscale, step() must not divide again
        self._scale_after_unscale = self._scale
        self._already_unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if getattr(self, "_already_unscaled", False):
            self._already_unscaled = False
            if not self._found_inf:
                optimizer.step()
            return
        super().step(optimizer)
