"""uci_housing reader creators (reference: python/paddle/dataset/uci_housing.py).

Deterministic synthetic 13-feature regression table with the reference's
feature names and normalization contract (features standardized, target in
its own column) — the same shapes/types the reference's readers yield.
"""

from __future__ import annotations

import numpy as np

__all__ = ["feature_names", "train", "test"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
    "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT",
]

_N_TRAIN, _N_TEST = 404, 102


def _table(seed):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((_N_TRAIN + _N_TEST, 13)).astype(np.float32)
    w = rng.standard_normal(13).astype(np.float32)
    y = (x @ w + 0.1 * rng.standard_normal(len(x))).astype(np.float32)
    return x, y[:, None]


def train():
    """Reader creator: yields (features [13] f32, target [1] f32)."""

    def reader():
        x, y = _table(0)
        for i in range(_N_TRAIN):
            yield x[i], y[i]

    return reader


def test():
    def reader():
        x, y = _table(0)
        for i in range(_N_TRAIN, _N_TRAIN + _N_TEST):
            yield x[i], y[i]

    return reader
