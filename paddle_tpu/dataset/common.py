"""Legacy dataset helpers (reference: python/paddle/dataset/common.py).

The reference's download/md5 machinery is egress-bound; what survives here
is the reader-combinator surface its users actually compose with.
"""

from __future__ import annotations

DATA_HOME = None  # no download cache in the egress-free runtime


def cluster_files_reader(files_pattern, trainer_count, trainer_id):
    """Round-robin shard of sorted glob matches (common.py cluster_files_reader)."""
    import glob

    def reader():
        flist = sorted(glob.glob(files_pattern))
        for i, path in enumerate(flist):
            if i % trainer_count == trainer_id:
                with open(path, "rb") as f:
                    yield f.read()

    return reader
