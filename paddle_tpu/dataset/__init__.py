"""paddle_tpu.dataset — the legacy reader-creator dataset API.

Parity anchor: python/paddle/dataset (uci_housing.py, mnist.py, cifar.py,
common.py) — the pre-2.0 API whose surface is *reader creators*: zero-arg
functions returning generators of per-sample tuples, composed with
``paddle.batch``-style combinators. The reference marks the whole package
deprecated and it downloads from URLs; this runtime has no egress, so the
readers here serve the SAME API shape over the in-repo synthetic datasets
(vision/datasets) and a deterministic synthetic housing table — real
iterables, not stubs.
"""

from __future__ import annotations

import numpy as np

from . import common  # noqa: F401
from . import uci_housing  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401

__all__ = ["common", "uci_housing", "mnist", "cifar"]
