"""cifar reader creators (reference: python/paddle/dataset/cifar.py): yields
(flattened CHW f32 in [0, 1], label int) over the synthetic vision datasets."""

from __future__ import annotations

import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(cls_name, mode, n):
    from ..vision import datasets as D

    ds = getattr(D, cls_name)(mode=mode, size=n)

    def reader():
        for i in range(len(ds)):
            img, label = ds[i]
            a = np.asarray(img, np.float32)
            if a.ndim == 3:          # HWC -> CHW like the reference
                a = a.transpose(2, 0, 1)
            yield (a / 255.0).reshape(-1), int(np.asarray(label).reshape(-1)[0])

    return reader


def train10(n: int = 512):
    return _reader("Cifar10", "train", n)


def test10(n: int = 128):
    return _reader("Cifar10", "test", n)


def train100(n: int = 512):
    return _reader("Cifar100", "train", n)


def test100(n: int = 128):
    return _reader("Cifar100", "test", n)
