"""mnist reader creators (reference: python/paddle/dataset/mnist.py): yields
(image [784] f32 in [-1, 1], label int) over the synthetic vision dataset."""

from __future__ import annotations

import numpy as np

__all__ = ["train", "test"]


def _reader(mode, n):
    from ..vision.datasets import MNIST

    ds = MNIST(mode=mode, size=n)

    def reader():
        for i in range(len(ds)):
            img, label = ds[i]
            flat = np.asarray(img, np.float32).reshape(-1) / 127.5 - 1.0
            yield flat, int(np.asarray(label).reshape(-1)[0])

    return reader


def train(n: int = 512):
    return _reader("train", n)


def test(n: int = 128):
    return _reader("test", n)
