"""paddle_tpu.device — device management (reference: python/paddle/device).

On TPU, XLA owns streams/events/memory; this module provides the paddle-parity
surface (set_device/synchronize/Stream/Event) mapped onto JAX device semantics.
"""

from __future__ import annotations

import jax


def set_device(device):
    return device


def get_device():
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def get_all_custom_device_type():
    return ["tpu"]


def is_compiled_with_custom_device(device_type):
    return device_type == "tpu"


def device_count():
    return jax.device_count()


def synchronize(device=None):
    """Block until all dispatched work completes (paddle.device.synchronize)."""
    jax.effects_barrier()


def cuda_device_count():
    return 0


class Stream:
    """Parity object: XLA has no user-visible streams on TPU; ops on one device
    execute in dispatch order, collectives get their own async scope from XLA."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._t = None

    def record(self, stream=None):
        import time

        jax.effects_barrier()
        self._t = time.perf_counter()

    def query(self):
        return True

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end_event):
        if self._t is None or end_event._t is None:
            return 0.0
        return (end_event._t - self._t) * 1000.0


def current_stream(device=None):
    return Stream(device)


def stream_guard(stream):
    import contextlib

    return contextlib.nullcontext()


class cuda:
    """Alias namespace kept for API compatibility (paddle.device.cuda)."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        return 0

    @staticmethod
    def memory_allocated(device=None):
        return 0


# breadth shims (reference: device/__init__.py misc queries)
def get_cudnn_version():
    return None  # no cuDNN on TPU


class XPUPlace:
    def __init__(self, dev_id=0):
        self.dev_id = dev_id


class IPUPlace:
    def __init__(self, dev_id=0):
        self.dev_id = dev_id


def is_compiled_with_ipu():
    return False


# single source of truth: the top-level predicates (paddle_tpu/__init__)
def is_compiled_with_xpu():
    from .. import is_compiled_with_xpu as _f

    return _f()


def is_compiled_with_cinn():
    from .. import is_compiled_with_cinn as _f

    return _f()


def is_compiled_with_cuda():
    from .. import is_compiled_with_cuda as _f

    return _f()


def is_compiled_with_rocm():
    from .. import is_compiled_with_rocm as _f

    return _f()


def is_compiled_with_distribute():
    from .. import is_compiled_with_distribute as _f

    return _f()


def get_all_device_type():
    import jax

    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    import jax

    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def set_stream(stream=None):
    return stream  # XLA owns streams; API parity no-op
