"""Breadth completion of paddle_tpu.distribution — the remaining reference
distributions (python/paddle/distribution/: cauchy.py, chi2.py,
continuous_bernoulli.py, exponential_family.py, multivariate_normal.py,
independent.py, laplace.py, lognormal.py, lkj_cholesky.py, gumbel.py,
geometric.py, binomial.py, poisson.py, student_t.py, kl.py register_kl)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap
from ..framework.random import next_key
from . import Distribution, Normal, _t

__all__ = [
    "Cauchy", "Chi2", "ContinuousBernoulli", "ExponentialFamily",
    "MultivariateNormal", "Independent", "Laplace", "LogNormal",
    "LKJCholesky", "Gumbel", "Geometric", "Binomial", "Poisson", "StudentT",
    "register_kl",
]


def _arr(x):
    return jnp.asarray(unwrap(x), jnp.float32)


class ExponentialFamily(Distribution):
    """Base class marking exponential-family members; entropy via Bregman
    divergence of the log-normalizer (reference: exponential_family.py)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural):
        raise NotImplementedError


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(self.loc + self.scale * jax.random.cauchy(next_key(), shp))

    def log_prob(self, value):
        v = _arr(value)
        z = (v - self.loc) / self.scale
        return _t(-jnp.log(math.pi * self.scale * (1 + z * z)))

    def entropy(self):
        return _t(jnp.broadcast_to(jnp.log(4 * math.pi * self.scale),
                                   self._batch_shape))

    def cdf(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return _t(jnp.arctan(z) / math.pi + 0.5)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(2 * self.scale ** 2, self._batch_shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(self.loc + self.scale * jax.random.laplace(next_key(), shp))

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        return _t(-jnp.abs(v - self.loc) / self.scale
                  - jnp.log(2 * self.scale))

    def entropy(self):
        return _t(jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                   self._batch_shape))

    def cdf(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return _t(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, q):
        qv = _arr(q)
        return _t(self.loc - self.scale * jnp.sign(qv - 0.5)
                  * jnp.log1p(-2 * jnp.abs(qv - 0.5)))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _arr(loc), _arr(scale)
        self._normal = Normal(loc, scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _t(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return _t(jnp.expm1(s2) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        return _t(jnp.exp(unwrap(self._normal.sample(shape))))

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        lv = jnp.log(v)
        return _t(unwrap(self._normal.log_prob(_t(lv))) - lv)

    def entropy(self):
        return _t(unwrap(self._normal.entropy()) + self.loc)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _t(self.loc + self.scale * np_euler)

    @property
    def variance(self):
        return _t((math.pi ** 2 / 6) * self.scale ** 2)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(self.loc + self.scale * jax.random.gumbel(next_key(), shp))

    rsample = sample

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return _t(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return _t(jnp.broadcast_to(jnp.log(self.scale) + 1 + np_euler,
                                   self._batch_shape))


np_euler = 0.5772156649015329


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    def __init__(self, probs=None, logits=None, name=None):
        if probs is None:
            probs = jax.nn.sigmoid(_arr(logits))
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape)

    @property
    def mean(self):
        return _t((1 - self.probs_arr) / self.probs_arr)

    @property
    def variance(self):
        return _t((1 - self.probs_arr) / self.probs_arr ** 2)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        u = jax.random.uniform(next_key(), shp, minval=1e-7, maxval=1.0)
        return _t(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_arr)))

    def log_prob(self, value):
        k = _arr(value)
        return _t(k * jnp.log1p(-self.probs_arr) + jnp.log(self.probs_arr))

    def entropy(self):
        p = self.probs_arr
        return _t(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _t(self.rate)

    @property
    def variance(self):
        return _t(self.rate)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(jax.random.poisson(next_key(), self.rate, shp).astype(jnp.float32))

    def log_prob(self, value):
        k = _arr(value)
        return _t(k * jnp.log(self.rate) - self.rate
                  - jax.scipy.special.gammaln(k + 1))


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _arr(total_count)
        self.probs_arr = _arr(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs_arr.shape))

    @property
    def mean(self):
        return _t(self.total_count * self.probs_arr)

    @property
    def variance(self):
        return _t(self.total_count * self.probs_arr * (1 - self.probs_arr))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        n = int(jnp.max(self.total_count))
        u = jax.random.uniform(next_key(), shp + (n,))
        counts = jnp.sum(
            (u < self.probs_arr[..., None])
            & (jnp.arange(n) < self.total_count[..., None]), -1)
        return _t(counts.astype(jnp.float32))

    def log_prob(self, value):
        k, n, p = _arr(value), self.total_count, self.probs_arr
        logc = (jax.scipy.special.gammaln(n + 1)
                - jax.scipy.special.gammaln(k + 1)
                - jax.scipy.special.gammaln(n - k + 1))
        return _t(logc + k * jnp.log(p) + (n - k) * jnp.log1p(-p))


class Chi2(Distribution):
    def __init__(self, df, name=None):
        self.df = _arr(df)
        super().__init__(self.df.shape)

    @property
    def mean(self):
        return _t(self.df)

    @property
    def variance(self):
        return _t(2 * self.df)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(2 * jax.random.gamma(next_key(), self.df / 2, shp))

    def log_prob(self, value):
        v, k = _arr(value), self.df
        return _t((k / 2 - 1) * jnp.log(v) - v / 2 - (k / 2) * math.log(2.0)
                  - jax.scipy.special.gammaln(k / 2))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df, self.loc, self.scale = _arr(df), _arr(loc), _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return _t(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        v = self.scale ** 2 * self.df / (self.df - 2)
        return _t(jnp.where(self.df > 2, v, jnp.nan))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(self.loc + self.scale * jax.random.t(next_key(), self.df, shp))

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        nu = self.df
        lg = jax.scipy.special.gammaln
        return _t(lg((nu + 1) / 2) - lg(nu / 2)
                  - 0.5 * jnp.log(nu * math.pi) - jnp.log(self.scale)
                  - (nu + 1) / 2 * jnp.log1p(z * z / nu))


class ContinuousBernoulli(ExponentialFamily):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs_arr = _arr(probs)
        self._lims = lims
        super().__init__(self.probs_arr.shape)

    def _log_norm_const(self):
        p = self.probs_arr
        # C(p) = 2 atanh(1-2p) / (1-2p), continuous at p=1/2 where C=2
        safe = jnp.clip(p, 1e-6, 1 - 1e-6)
        cut = (safe < self._lims[0]) | (safe > self._lims[1])
        x = jnp.where(cut, safe, 0.25)  # dummy inside the removable singularity
        c = 2 * jnp.arctanh(1 - 2 * x) / (1 - 2 * x)
        return jnp.log(jnp.where(cut, c, 2.0))

    def log_prob(self, value):
        v, p = _arr(value), jnp.clip(self.probs_arr, 1e-6, 1 - 1e-6)
        return _t(v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                  + self._log_norm_const())

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        p = jnp.clip(self.probs_arr, 1e-6, 1 - 1e-6)
        u = jax.random.uniform(next_key(), shp, minval=1e-6, maxval=1 - 1e-6)
        # inverse cdf; at p ~ 1/2 the icdf degenerates to u
        icdf = jnp.log1p((2 * p - 1) * u / (1 - p)) / jnp.log(p / (1 - p))
        mid = (p > self._lims[0]) & (p < self._lims[1])
        return _t(jnp.where(mid, u, icdf))

    rsample = sample


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None, name=None):
        self.loc = _arr(loc)
        if scale_tril is not None:
            self.scale_tril = _arr(scale_tril)
        else:
            self.scale_tril = jnp.linalg.cholesky(_arr(covariance_matrix))
        super().__init__(self.loc.shape[:-1], self.loc.shape[-1:])

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def variance(self):
        return _t(jnp.sum(self.scale_tril ** 2, -1))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape + self._event_shape
        eps = jax.random.normal(next_key(), shp)
        return _t(self.loc + jnp.einsum("...ij,...j->...i", self.scale_tril, eps))

    rsample = sample

    def log_prob(self, value):
        d = self.loc.shape[-1]
        diff = _arr(value) - self.loc
        L = jnp.broadcast_to(self.scale_tril,
                             diff.shape[:-1] + self.scale_tril.shape[-2:])
        sol = jax.scipy.linalg.solve_triangular(L, diff[..., None],
                                                lower=True)[..., 0]
        maha = jnp.sum(sol ** 2, -1)
        logdet = 2 * jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                                  axis2=-1)), -1)
        return _t(-0.5 * (maha + d * math.log(2 * math.pi) + logdet))

    def entropy(self):
        d = self.loc.shape[-1]
        logdet = 2 * jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                                  axis2=-1)), -1)
        return _t(0.5 * (d * (1 + math.log(2 * math.pi)) + logdet))


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference: independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.k = int(reinterpreted_batch_rank)
        bs = tuple(base._batch_shape)
        super().__init__(bs[: len(bs) - self.k],
                         bs[len(bs) - self.k:] + tuple(base._event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    rsample = sample

    def log_prob(self, value):
        lp = unwrap(self.base.log_prob(value))
        return _t(jnp.sum(lp, axis=tuple(range(-self.k, 0))))

    def entropy(self):
        e = unwrap(self.base.entropy())
        return _t(jnp.sum(e, axis=tuple(range(-self.k, 0))))


class LKJCholesky(Distribution):
    """LKJ prior over correlation-matrix Cholesky factors
    (reference: lkj_cholesky.py; onion-method sampling)."""

    def __init__(self, dim, concentration=1.0, sample_method="onion", name=None):
        self.dim = int(dim)
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape, (self.dim, self.dim))

    def sample(self, shape=()):
        d = self.dim
        eta = self.concentration
        shp = tuple(shape) + self._batch_shape
        # onion method: build row by row
        key_beta = next_key()
        key_sph = next_key()
        L = jnp.zeros(shp + (d, d), jnp.float32).at[..., 0, 0].set(1.0)
        for i in range(1, d):
            beta_a = eta + (d - 1 - i) / 2.0
            beta_b = jnp.asarray(i / 2.0, jnp.float32)
            r2 = jax.random.beta(jax.random.fold_in(key_beta, i),
                                 beta_b, beta_a, shp)
            u = jax.random.normal(jax.random.fold_in(key_sph, i), shp + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(r2)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.clip(1 - r2, 1e-12)))
        return _t(L)

    def log_prob(self, value):
        L = _arr(value)
        d, eta = self.dim, self.concentration
        diags = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        orders = jnp.arange(d - 1, 0, -1, dtype=jnp.float32)
        expo = 2 * (eta[..., None] - 1) + orders
        unnorm = jnp.sum(expo * jnp.log(diags), -1)
        # normalizer (reference lkj_cholesky.py closed form)
        lg = jax.scipy.special.gammaln
        i = jnp.arange(1, d, dtype=jnp.float32)
        alpha = eta[..., None] + (d - 1 - i) / 2
        norm = jnp.sum(i / 2 * math.log(math.pi) + lg(alpha)
                       - lg(alpha + i / 2), -1)
        return _t(unnorm - norm)


# ---------------------------------------------------------------------------
# register_kl (reference: python/paddle/distribution/kl.py)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Decorator registering a KL(p||q) implementation, dispatched by
    kl_divergence with most-derived-class matching."""

    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def _lookup_kl(p, q):
    best, best_fn = None, None
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            score = (len(type(p).__mro__) - len(pc.__mro__),
                     len(type(q).__mro__) - len(qc.__mro__))
            if best is None or score < best:
                best, best_fn = score, fn
    return best_fn


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    # KL = log(b2/b1) + |mu1-mu2|/b2 + (b1/b2) exp(-|mu1-mu2|/b1) - 1
    d = jnp.abs(p.loc - q.loc)
    return _t(jnp.log(q.scale / p.scale) + d / q.scale
              + (p.scale / q.scale) * jnp.exp(-d / p.scale) - 1)


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return _t(p.rate * (jnp.log(p.rate) - jnp.log(q.rate)) - p.rate + q.rate)


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    pp, qq = p.probs_arr, q.probs_arr
    return _t(jnp.log(pp / qq) + (1 - pp) / pp * jnp.log((1 - pp) / (1 - qq)))
