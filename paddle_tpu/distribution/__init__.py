"""paddle_tpu.distribution (reference: python/paddle/distribution) — core distributions."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap
from ..framework.random import next_key


def _t(x):
    return Tensor(x) if not isinstance(x, Tensor) else x


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _t(jnp.exp(unwrap(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(unwrap(loc), jnp.float32)
        self.scale = jnp.asarray(unwrap(scale), jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(self.scale**2, self._batch_shape))

    @property
    def stddev(self):
        return _t(jnp.broadcast_to(self.scale, self._batch_shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(self.loc + self.scale * jax.random.normal(next_key(), shp))

    def log_prob(self, value):
        v = unwrap(value)
        var = self.scale**2
        return _t(-((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _t(jnp.broadcast_to(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale), self._batch_shape))

    def cdf(self, value):
        v = unwrap(value)
        return _t(0.5 * (1 + jax.scipy.special.erf((v - self.loc) / (self.scale * math.sqrt(2)))))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = jnp.asarray(unwrap(low), jnp.float32)
        self.high = jnp.asarray(unwrap(high), jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return _t((self.low + self.high) / 2)

    @property
    def variance(self):
        return _t((self.high - self.low) ** 2 / 12)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(jax.random.uniform(next_key(), shp) * (self.high - self.low) + self.low)

    def log_prob(self, value):
        v = unwrap(value)
        inside = (v >= self.low) & (v < self.high)
        return _t(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self):
        return _t(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = jnp.asarray(unwrap(logits), jnp.float32)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return _t(jax.nn.softmax(self.logits, -1))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(jax.random.categorical(next_key(), self.logits, shape=shp))

    def log_prob(self, value):
        lp = jax.nn.log_softmax(self.logits, -1)
        v = unwrap(value).astype(jnp.int32)
        return _t(jnp.take_along_axis(lp, v[..., None], -1)[..., 0])

    def entropy(self):
        lp = jax.nn.log_softmax(self.logits, -1)
        return _t(-(jnp.exp(lp) * lp).sum(-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_arr = jnp.asarray(unwrap(probs), jnp.float32)
        super().__init__(self.probs_arr.shape)

    @property
    def mean(self):
        return _t(self.probs_arr)

    @property
    def variance(self):
        return _t(self.probs_arr * (1 - self.probs_arr))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(jax.random.bernoulli(next_key(), self.probs_arr, shp).astype(jnp.float32))

    def log_prob(self, value):
        v = unwrap(value)
        p = self.probs_arr
        return _t(v * jnp.log(jnp.maximum(p, 1e-12)) + (1 - v) * jnp.log(jnp.maximum(1 - p, 1e-12)))

    def entropy(self):
        p = self.probs_arr
        return _t(-(p * jnp.log(jnp.maximum(p, 1e-12)) + (1 - p) * jnp.log(jnp.maximum(1 - p, 1e-12))))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = jnp.asarray(unwrap(rate), jnp.float32)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _t(1.0 / self.rate)

    @property
    def variance(self):
        return _t(self.rate**-2)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(jax.random.exponential(next_key(), shp) / self.rate)

    def log_prob(self, value):
        v = unwrap(value)
        return _t(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return _t(1 - jnp.log(self.rate))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = jnp.asarray(unwrap(alpha), jnp.float32)
        self.beta = jnp.asarray(unwrap(beta), jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return _t(self.alpha / (self.alpha + self.beta))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(jax.random.beta(next_key(), self.alpha, self.beta, shp))

    def log_prob(self, value):
        from jax.scipy.special import betaln

        v = unwrap(value)
        return _t((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v) - betaln(self.alpha, self.beta))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = jnp.asarray(unwrap(concentration), jnp.float32)
        self.rate = jnp.asarray(unwrap(rate), jnp.float32)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape, self.rate.shape))

    @property
    def mean(self):
        return _t(self.concentration / self.rate)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(jax.random.gamma(next_key(), self.concentration, shp) / self.rate)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = unwrap(value)
        a, r = self.concentration, self.rate
        return _t(a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v - gammaln(a))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = jnp.asarray(unwrap(concentration), jnp.float32)
        super().__init__(self.concentration.shape[:-1], self.concentration.shape[-1:])

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return _t(jax.random.dirichlet(next_key(), self.concentration, shp))

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = unwrap(value)
        a = self.concentration
        return _t(((a - 1) * jnp.log(v)).sum(-1) + gammaln(a.sum(-1)) - gammaln(a).sum(-1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = total_count
        self.probs_arr = jnp.asarray(unwrap(probs), jnp.float32)
        super().__init__(self.probs_arr.shape[:-1], self.probs_arr.shape[-1:])

    def sample(self, shape=()):
        n = self.probs_arr.shape[-1]
        draws = jax.random.categorical(
            next_key(), jnp.log(self.probs_arr), shape=tuple(shape) + (self.total_count,) + self._batch_shape
        )
        return _t(jax.nn.one_hot(draws, n).sum(axis=len(shape)))


def kl_divergence(p, q):
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return _t(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        lp = jax.nn.log_softmax(p.logits, -1)
        lq = jax.nn.log_softmax(q.logits, -1)
        return _t((jnp.exp(lp) * (lp - lq)).sum(-1))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return _t(jnp.log((q.high - q.low) / (p.high - p.low)))
    raise NotImplementedError(f"kl_divergence({type(p).__name__}, {type(q).__name__})")


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = transforms if isinstance(transforms, (list, tuple)) else [transforms]
        super().__init__(base._batch_shape, base._event_shape)

    def sample(self, shape=()):
        x = unwrap(self.base.sample(shape))
        for t in self.transforms:
            x = t.forward(x)
        return _t(x)


# breadth completion: remaining reference distributions + register_kl
from .extras import (  # noqa: E402,F401
    Binomial,
    Cauchy,
    Chi2,
    ContinuousBernoulli,
    ExponentialFamily,
    Geometric,
    Gumbel,
    Independent,
    LKJCholesky,
    Laplace,
    LogNormal,
    MultivariateNormal,
    Poisson,
    StudentT,
    register_kl,
)
from .extras import _lookup_kl as _registry_lookup_kl  # noqa: E402

_builtin_kl = kl_divergence


def kl_divergence(p, q):  # noqa: F811 — registry-aware dispatch wraps builtin
    fn = _registry_lookup_kl(p, q)
    if fn is not None:
        return fn(p, q)
    return _builtin_kl(p, q)
