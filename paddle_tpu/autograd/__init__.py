"""paddle_tpu.autograd (reference: python/paddle/autograd).

backward/grad re-export the tape engine; PyLayer (reference autograd/py_layer.py:36)
lets users define custom forward/backward that integrates with both the eager tape
and, via jax.custom_vjp, the traced/compiled path.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..core.autograd_engine import (  # noqa: F401
    GradNode,
    enable_grad,
    grad,
    grad_enabled,
    no_grad,
    run_backward,
    set_grad_enabled,
)
from ..core.tensor import Tensor, unwrap


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    for i, t in enumerate(tensors):
        g = grad_tensors[i] if grad_tensors is not None else None
        run_backward(t, g, retain_graph)


def is_grad_enabled():
    return grad_enabled()


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return self._saved

    saved_tensors = property(lambda self: self._saved)

    def mark_not_inplace(self, *tensors):
        self.not_inplace_tensors = tensors


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op (reference: autograd/py_layer.py:36).

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x): ctx.save_for_backward(x); return x.exp()
        @staticmethod
        def backward(ctx, dy): (x,) = ctx.saved_tensor(); return dy * x.exp()
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import autograd_engine

        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        needs_grad = autograd_engine.grad_enabled() and any(
            not t.stop_gradient for t in tensor_args
        )

        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (tuple, list))
        out_list = [outputs] if single else list(outputs)
        out_tensors = [o if isinstance(o, Tensor) else Tensor(o) for o in out_list]

        if needs_grad:
            diff_inputs = [t for t in tensor_args if jnp.issubdtype(t.dtype, jnp.floating)]

            def vjp_fn(cotangents):
                cots = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                with no_grad():
                    grads = cls.backward(ctx, *[Tensor(c) for c in cots])
                grads = grads if isinstance(grads, (tuple, list)) else (grads,)
                out = []
                gi = 0
                for t in diff_inputs:
                    if gi < len(grads) and grads[gi] is not None:
                        out.append(unwrap(grads[gi]))
                    else:
                        out.append(None)
                    gi += 1
                return tuple(out)

            node = autograd_engine.GradNode(
                cls.__name__,
                vjp_fn,
                diff_inputs,
                [(tuple(t.shape), t.dtype) for t in out_tensors],
            )
            for i, t in enumerate(out_tensors):
                t.stop_gradient = False
                t._node = node
                t._out_idx = i
        return out_tensors[0] if single else tuple(out_tensors)


class PyLayerLegacy(PyLayer):
    pass


def jacobian(ys, xs, batch_axis=None):
    """Reference: autograd/autograd.py — dense jacobian via jax.jacrev on the recorded fn."""
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    ys_list = ys if isinstance(ys, (list, tuple)) else [ys]

    rows = []
    for y in ys_list:
        y_flat_dim = int(jnp.prod(jnp.asarray(y.shape))) if y.shape else 1
        row = []
        for i in range(y_flat_dim):
            seed = jnp.zeros((y_flat_dim,), y.dtype).at[i].set(1.0).reshape(tuple(y.shape))
            gs = grad([y], xs_list, grad_outputs=[Tensor(seed)], retain_graph=True, allow_unused=True)
            row.append([g._data.reshape(-1) if g is not None else None for g in gs])
        rows.append(row)

    jac_per_x = []
    for xi, x in enumerate(xs_list):
        x_dim = int(jnp.prod(jnp.asarray(x.shape))) if x.shape else 1
        blocks = []
        for row in rows:
            mat = jnp.stack([
                r[xi] if r[xi] is not None else jnp.zeros((x_dim,), x.dtype) for r in row
            ])
            blocks.append(mat)
        jac_per_x.append(Tensor(jnp.concatenate(blocks, axis=0)))
    if not isinstance(xs, (list, tuple)):
        return jac_per_x[0]
    return jac_per_x


def hessian(func_out, xs):
    raise NotImplementedError("use jax.hessian via paddle_tpu.jit for higher-order AD")


def saved_tensors_hooks(*args, **kwargs):
    import contextlib

    return contextlib.nullcontext()
