"""paddle_tpu.onnx — model export for external inference backends.

Parity anchor: python/paddle/onnx/export.py:33 (paddle.onnx.export), which
delegates ONNX serialization to the external paddle2onnx package (the
reference itself raises without it).

TPU-native stance: the portable interchange format of the XLA world is
StableHLO, not ONNX — :func:`export` traces the layer exactly like
``paddle.onnx.export`` (jit.save machinery, InputSpec-driven) and writes the
StableHLO artifact at the requested path; that artifact is the deployable
product (inference.Predictor / the C++ stablehlo_runner load it). The final
StableHLO->ONNX serialization is NOT implemented in-repo — export() always
raises after producing the artifact, naming what exists and what is missing,
mirroring the reference's hard paddle2onnx dependency rather than silently
stubbing.
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version: int = 9,
           **configs):
    """Trace ``layer`` with ``input_spec`` and export for external
    inference. Writes the StableHLO artifact at ``path`` (jit.save format,
    loadable by inference.Predictor and the native stablehlo_runner), then
    raises: the final StableHLO->ONNX serialization is not implemented
    in-repo (reference parity: onnx/export.py:33 hard-depends on the
    external paddle2onnx converter)."""
    from ..jit.api import save as jit_save

    if path.endswith(".onnx"):
        path = path[:-5]
    jit_save(layer, path, input_spec=input_spec)
    raise RuntimeError(
        f"paddle_tpu.onnx.export: traced artifact saved at {path!r} "
        "(StableHLO, loadable by inference.Predictor / the C++ "
        "stablehlo_runner). StableHLO->ONNX serialization is not "
        "implemented in-repo (the reference likewise hard-depends on the "
        "external paddle2onnx converter for this step)")
