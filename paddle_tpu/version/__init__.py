"""paddle_tpu.version (reference: the generated python/paddle/version.py —
full_version/major/minor/patch/rc + show()). Version is sourced from the
installed package metadata (pyproject's single source of truth)."""

from __future__ import annotations

full_version = "0.2.0"
try:  # installed: prefer the package metadata
    from importlib.metadata import version as _v

    full_version = _v("paddle-tpu")
except Exception:
    pass

_parts = (full_version.split("+")[0].split(".") + ["0", "0", "0"])[:3]
major, minor, patch = _parts[0], _parts[1], _parts[2]
rc = "0"

__all__ = ["full_version", "major", "minor", "patch", "rc", "show"]


def show() -> None:
    """Print the version breakdown (reference version.py show())."""
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"rc: {rc}")
