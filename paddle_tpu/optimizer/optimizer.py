"""Optimizer base + concrete optimizers (reference: python/paddle/optimizer/).

Redesign vs reference: the reference routes every update through fused CUDA kernels
(e.g. adamw.py:495 -> _C_ops.adamw_). Here each optimizer defines a pure per-tensor
``_update(g, p, state) -> (new_p, new_state)`` in jnp; eager ``step()`` loops params
(XLA fuses per-param chains), while the Trainer/hapi path jit-compiles
``apply_gradients`` over the whole param pytree — one fused update kernel per step,
donation-friendly.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.autograd_engine import no_grad
from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators: Dict[str, Dict[int, jnp.ndarray]] = {}
        self._step_count = 0

    # ---- lr ----
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        self._lr = value

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    @property
    def _learning_rate(self):
        return self._lr

    # ---- state ----
    def _id_to_key(self):
        """Stable serialization keys: position in the parameter list (id(p) is
        runtime-only and would break checkpoint restore across processes)."""
        return {id(p): str(i) for i, p in enumerate(self._parameter_list or [])}

    def state_dict(self):
        out = {"step_count": self._step_count}
        id2key = self._id_to_key()
        acc = {}
        for name, d in self._accumulators.items():
            acc[name] = {id2key.get(k, str(k)): Tensor(v) for k, v in d.items()}
        out["accumulators"] = acc
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("step_count", 0)
        params = self._parameter_list or []
        for name, d in state.get("accumulators", {}).items():
            restored = {}
            for k, v in d.items():
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                idx = int(k)
                if 0 <= idx < len(params):
                    restored[id(params[idx])] = arr
            self._accumulators[name] = restored
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])

    def _acc(self, name, p: Tensor, init=None, dtype=None):
        d = self._accumulators.setdefault(name, {})
        key = id(p)
        if key not in d:
            d[key] = jnp.zeros(tuple(p.shape), dtype or jnp.float32) if init is None else init
        return d[key]

    def _set_acc(self, name, p: Tensor, value):
        self._accumulators[name][id(p)] = value

    # ---- update ----
    def _update(self, grad, param_value, p: Tensor, lr):
        raise NotImplementedError

    def _l2_coeff(self) -> float:
        """L2 regularization coefficient from ``weight_decay`` (a number, or a
        regularizer object carrying a coefficient attribute). Decoupled-decay
        optimizers (AdamW) handle decay inside ``_update`` instead."""
        wd = self._weight_decay
        if wd is None or isinstance(self, _DecoupledWeightDecay):
            return 0.0
        if isinstance(wd, (int, float)):
            return float(wd)
        for attr in ("_regularization_coeff", "_coeff"):
            if hasattr(wd, attr):
                return float(getattr(wd, attr))
        return 0.0

    def _decay_term(self, value, g_dtype, param=None):
        """Decay contribution added to the gradient, or None.

        A regularizer OBJECT (paddle_tpu.regularizer.L1Decay/L2Decay)
        contributes its own term — L1's coeff*sign(p) cannot be expressed by
        a bare coefficient; a number means L2. A per-param regularizer
        (ParamAttr(regularizer=...), stored on the Tensor) OVERRIDES the
        optimizer-level one, matching the reference's precedence
        (regularizer.py: 'ParamAttr has higher priority than optimizer').
        Decoupled-decay optimizers (AdamW) handle decay inside _update."""
        from ..regularizer import WeightDecayRegularizer

        if isinstance(self, _DecoupledWeightDecay):
            return None

        wd = getattr(param, "regularizer", None)
        if not isinstance(wd, WeightDecayRegularizer):
            wd = self._weight_decay
        if wd is None:
            return None
        if isinstance(wd, WeightDecayRegularizer):
            out = wd(value)
            return out.astype(g_dtype) if out.dtype != g_dtype else out
        coeff = self._l2_coeff()
        return coeff * value.astype(g_dtype) if coeff else None

    def _apply_weight_decay(self, p, g):
        """Regularization folded into the gradient (reference 'weight_decay'
        regularizer + per-param ParamAttr regularizers)."""
        d = self._decay_term(p._data, g.dtype, p)
        return g + d if d is not None else g

    @no_grad()
    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer created without parameters")
        pg = [(p, p.grad) for p in params if isinstance(p, Tensor)]
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)
        self._step_count += 1
        for p, g in pg:
            if g is None or not getattr(p, "trainable", True):
                continue
            garr = g._data.astype(jnp.float32) if g.dtype != jnp.float32 else g._data
            garr = self._apply_weight_decay(p, garr)
            plr = self.get_lr() * p.optimize_attr.get("learning_rate", 1.0) if hasattr(p, "optimize_attr") else self.get_lr()
            new_val = self._update(garr, p._data, p, plr)
            p._data = new_val.astype(p.dtype) if new_val.dtype != p.dtype else new_val

    def _functional_update(self, grads, values, params, acc_state, lr, step):
        """Pure-pytree update used by jit-compiled train steps (hapi / Trainer).

        Temporarily swaps the accumulator store and step counter for traced values so
        the per-param ``_update`` rules run unchanged inside a jax trace; the mutated
        accumulator dict becomes the new optimizer state pytree.
        """
        saved_acc, saved_step = self._accumulators, self._step_count
        self._accumulators = acc_state
        self._step_count = step
        try:
            new_vals = []
            for g, v, p in zip(grads, values, params):
                if g is None:
                    new_vals.append(v)
                    continue
                # same regularizer semantics as the eager path (incl. L1's
                # sign decay and per-param ParamAttr regularizers)
                d = self._decay_term(v, g.dtype, p)
                if d is not None:
                    g = g + d
                out = self._update(g, v, p, lr)
                new_vals.append(out.astype(v.dtype) if out.dtype != v.dtype else out)
        finally:
            new_acc = self._accumulators
            self._accumulators = saved_acc
            self._step_count = saved_step
        return new_vals, new_acc

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..core import static_graph

        if isinstance(loss, static_graph.Variable):
            # static mode: mark the program for training — the Executor computes
            # grads via value_and_grad over the replay trace and applies this
            # optimizer each run() (cf. reference appended backward + opt ops)
            prog = loss.block.program
            params = list(parameters or self._parameter_list
                          or prog.all_parameters())
            skip = set(map(id, no_grad_set or []))
            params = [p for p in params
                      if getattr(p, "trainable", True) and id(p) not in skip]
            if not self._parameter_list:
                self._parameter_list = params
            self._static_params = params
            prog._loss = loss
            prog._optimizer = self
            return None, [(p, None) for p in params]

        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in (self._parameter_list or [])]

    @no_grad()
    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    def _lr_step(self):
        if isinstance(self._lr, LRScheduler):
            self._lr.step()


class _DecoupledWeightDecay:
    pass


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update(self, g, val, p, lr):
        return val - lr * g.astype(val.dtype)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update(self, g, val, p, lr):
        v = self._acc("velocity", p)
        v = self._momentum * v + g
        self._set_acc("velocity", p, v)
        if self._nesterov:
            return val - lr * (g + self._momentum * v).astype(val.dtype)
        return val - lr * v.astype(val.dtype)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update(self, g, val, p, lr):
        m = self._acc("moment", p, init=jnp.full(tuple(p.shape), self._init_acc, jnp.float32))
        m = m + g * g
        self._set_acc("moment", p, m)
        return val - (lr * g / (jnp.sqrt(m) + self._epsilon)).astype(val.dtype)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _update(self, g, val, p, lr):
        ms = self._acc("mean_square", p)
        ms = self._rho * ms + (1 - self._rho) * g * g
        self._set_acc("mean_square", p, ms)
        if self._centered:
            mg = self._acc("mean_grad", p)
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_acc("mean_grad", p, mg)
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._acc("momentum", p)
        mom = self._momentum * mom + lr * g / denom
        self._set_acc("momentum", p, mom)
        return val - mom.astype(val.dtype)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _update(self, g, val, p, lr):
        avg_sq = self._acc("avg_squared_grad", p)
        avg_upd = self._acc("avg_squared_update", p)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * g * g
        upd = jnp.sqrt(avg_upd + self._epsilon) / jnp.sqrt(avg_sq + self._epsilon) * g
        avg_upd = self._rho * avg_upd + (1 - self._rho) * upd * upd
        self._set_acc("avg_squared_grad", p, avg_sq)
        self._set_acc("avg_squared_update", p, avg_upd)
        return val - (lr * upd).astype(val.dtype)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._amsgrad = amsgrad

    def _update(self, g, val, p, lr):
        t = self._step_count
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - self._beta1**t)
        if self._amsgrad:
            vmax = self._acc("moment2_max", p)
            vmax = jnp.maximum(vmax, v)
            self._set_acc("moment2_max", p, vmax)
            vhat = vmax / (1 - self._beta2**t)
        else:
            vhat = v / (1 - self._beta2**t)
        return val - (lr * mhat / (jnp.sqrt(vhat) + self._epsilon)).astype(val.dtype)


class AdamW(Adam, _DecoupledWeightDecay):
    """Decoupled weight decay (reference: optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip,
                         lazy_mode, multi_precision, amsgrad=amsgrad, name=name)
        from ..regularizer import L2Decay, WeightDecayRegularizer

        if isinstance(weight_decay, L2Decay):
            # decoupled decay IS multiplicative L2-style decay; the coeff maps
            weight_decay = weight_decay._coeff
        elif isinstance(weight_decay, WeightDecayRegularizer):
            raise TypeError(
                f"AdamW weight_decay must be a number or L2Decay, got "
                f"{weight_decay}: L1 sign semantics cannot be expressed as "
                "decoupled (multiplicative) decay — use Adam with an L1Decay "
                "regularizer instead")
        if weight_decay is None:
            self._wd_coeff = 0.0
        elif isinstance(weight_decay, (str, bytes)):
            raise TypeError(
                f"AdamW weight_decay must be a number or L2Decay, got "
                f"{type(weight_decay).__name__}")
        else:
            try:
                # accepts numpy scalars / 0-d tensors via __float__
                self._wd_coeff = float(weight_decay)
            except (TypeError, ValueError):
                raise TypeError(
                    f"AdamW weight_decay must be a number or L2Decay, got "
                    f"{type(weight_decay).__name__}") from None
        # per-param regularizers don't compose with decoupled decay — L1's
        # sign semantics can't ride the multiplicative path; say so once here
        # rather than silently dropping them at step time
        for p in self._parameter_list or []:
            if isinstance(getattr(p, "regularizer", None),
                          WeightDecayRegularizer):
                import warnings

                warnings.warn(
                    f"ParamAttr regularizer on {getattr(p, 'name', '?')} is "
                    "ignored by decoupled-decay optimizers (AdamW); use a "
                    "coupled optimizer (Adam + weight_decay) to apply it",
                    stacklevel=2)
                break
        self._apply_decay_param_fun = apply_decay_param_fun

    def _update(self, g, val, p, lr):
        decay = True
        if self._apply_decay_param_fun is not None:
            decay = self._apply_decay_param_fun(p.name)
        if decay and self._wd_coeff:
            val = val - lr * self._wd_coeff * val
        return super()._update(g, val, p, lr)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, g, val, p, lr):
        t = self._step_count
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        m = self._beta1 * m + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * u, jnp.abs(g))
        self._set_acc("moment", p, m)
        self._set_acc("inf_norm", p, u)
        return val - (lr / (1 - self._beta1**t) * m / (u + self._epsilon)).astype(val.dtype)


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _update(self, g, val, p, lr):
        t = self._step_count
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mprod = self._acc("mu_product", p, init=jnp.ones((), jnp.float32))
        mprod_new = mprod * mu_t
        self._set_acc("mu_product", p, mprod_new)
        mhat = mu_t1 * m / (1 - mprod_new * mu_t1) + (1 - mu_t) * g / (1 - mprod_new)
        vhat = v / (1 - self._beta2**t)
        return val - (lr * mhat / (jnp.sqrt(vhat) + self._epsilon)).astype(val.dtype)


class RAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, g, val, p, lr):
        t = self._step_count
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        rho_inf = 2 / (1 - self._beta2) - 1
        rho_t = rho_inf - 2 * t * self._beta2**t / (1 - self._beta2**t)
        mhat = m / (1 - self._beta1**t)
        # branch written with jnp.where so `t` may be a traced step counter
        # (jitted Engine/hapi path) as well as a python int (eager step())
        vhat = jnp.sqrt(v / (1 - self._beta2**t))
        ratio = ((rho_t - 4) * (rho_t - 2) * rho_inf) / (
            (rho_inf - 4) * (rho_inf - 2) * rho_t)
        r = jnp.sqrt(jnp.maximum(ratio, 1e-16))
        adaptive = val - (lr * r * mhat / (vhat + self._epsilon)).astype(val.dtype)
        plain = val - (lr * mhat).astype(val.dtype)
        return jnp.where(rho_t > 4, adaptive, plain)


class Lamb(Optimizer):
    """Layer-wise adaptive (reference: optimizer/lamb.py) for large-batch training."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update(self, g, val, p, lr):
        t = self._step_count
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        mhat = m / (1 - self._beta1**t)
        vhat = v / (1 - self._beta2**t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) else self._lamb_wd
        update = r + wd * val.astype(jnp.float32)
        w_norm = jnp.linalg.norm(val.astype(jnp.float32))
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return val - (lr * trust * update).astype(val.dtype)


class LBFGS(Optimizer):
    """Limited-memory BFGS (reference: optimizer/lbfgs.py) — line-search free variant."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None, tolerance_grad=1e-7,
                 tolerance_change=1e-9, history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._max_iter = max_iter
        self._history_size = history_size
        self._s_hist: List = []
        self._y_hist: List = []
        self._prev_flat = None
        self._prev_grad = None

    def _flatten(self, tensors):
        return jnp.concatenate([t.reshape(-1) for t in tensors])

    def step(self, closure=None):
        loss = None
        if closure is not None:
            loss = closure()
        params = [p for p in self._parameter_list if p.grad is not None]
        if not params:
            return loss
        flat_g = self._flatten([p.grad._data.astype(jnp.float32) for p in params])
        flat_p = self._flatten([p._data.astype(jnp.float32) for p in params])
        if self._prev_flat is not None:
            s = flat_p - self._prev_flat
            y = flat_g - self._prev_grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._s_hist.append(s)
                self._y_hist.append(y)
                if len(self._s_hist) > self._history_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
        q = flat_g
        alphas = []
        for s, y in zip(reversed(self._s_hist), reversed(self._y_hist)):
            rho = 1.0 / jnp.dot(y, s)
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._s_hist:
            s, y = self._s_hist[-1], self._y_hist[-1]
            q = q * (jnp.dot(s, y) / jnp.dot(y, y))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        direction = -q
        self._prev_flat = flat_p
        self._prev_grad = flat_g
        lr = self.get_lr()
        new_flat = flat_p + lr * direction
        off = 0
        for p in params:
            n = int(jnp.prod(jnp.asarray(p.shape))) if p.shape else 1
            p._data = new_flat[off:off + n].reshape(tuple(p.shape)).astype(p.dtype)
            off += n
        self._step_count += 1
        return loss


class ASGD(Optimizer):
    """Averaged SGD (reference: python/paddle/optimizer/asgd.py — steps with
    the mean of the last ``batch_num`` gradients, kept in a circular buffer
    ``ys`` with running sum ``d``: d <- d - ys[i] + g)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._batch_num = max(int(batch_num), 1)

    def _update(self, g, val, p, lr):
        n = self._batch_num
        d = self._acc("d", p)
        ys = self._acc("ys", p,
                       init=jnp.zeros((n,) + tuple(p.shape), jnp.float32))
        i = (self._step_count - 1) % n
        oldest = ys[i]
        d = d - oldest + g
        ys = ys.at[i].set(g)
        self._set_acc("d", p, d)
        self._set_acc("ys", p, ys)
        return val - (lr * d / float(n)).astype(val.dtype)


class Rprop(Optimizer):
    """Resilient backprop (reference: python/paddle/optimizer/rprop.py) —
    sign-based per-weight step sizes; full-batch regimes only."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _update(self, g, val, p, lr):
        prev = self._acc("prev_grad", p)
        step = self._acc("step_size", p,
                         init=jnp.full(tuple(p.shape), self.get_lr(), jnp.float32))
        sign = jnp.sign(g * prev)
        factor = jnp.where(sign > 0, self._eta_pos,
                           jnp.where(sign < 0, self._eta_neg, 1.0))
        step = jnp.clip(step * factor, self._lr_min, self._lr_max)
        # where sign flipped, zero the gradient (classic Rprop- variant)
        g_eff = jnp.where(sign < 0, 0.0, g)
        self._set_acc("step_size", p, step)
        self._set_acc("prev_grad", p, g_eff)
        return val - (step * jnp.sign(g_eff)).astype(val.dtype)
