"""Flash attention — Pallas TPU kernels, forward AND backward.

Replaces the reference's vendored CUDA flashattn (dynload wrapper
/root/reference/paddle/phi/backends/dynload/flashattn.cc, python surface
nn/functional/flash_attention.py:195). TPU design:

Forward:
  - grid (batch, q_heads, q_blocks, kv_blocks) — kv INNERMOST, so K/V stream
    through VMEM one [block_k, d] block per grid step and Pallas's grid
    pipeline double-buffers the next block's DMA behind the current block's
    compute. Max context is bounded by HBM, not VMEM (seq 32k+ single chip).
  - online-softmax state (acc, m, l) lives in fp32 VMEM scratch that persists
    across the kv steps of one q block; (re)initialized at kv step 0,
    finalized into out/lse at the last kv step.
  - causal: fully-masked K blocks are skipped via pl.when AND their DMA is
    elided by clamping the K/V BlockSpec index_map to the last valid block
    (Pallas skips re-fetch when consecutive steps map to the same block).
  - GQA: q-head → kv-head mapping folded into the BlockSpec index_map, so
    K/V are never materialized per-q-head (the XLA fallback repeats them)
  - train path emits logsumexp [b, h, LSE_LANES, s_q] (lanes SECOND-minor
    so the tiled HBM layout pads nothing — lanes-minor cost 16x padding) so
    backward can recompute P row-stably; inference skips the write

Backward (FlashAttention-2 style, two kernels sharing the saved lse):
  - dQ kernel: grid (b, kv_heads, q_blocks, kv_blocks), same kv
    streaming/clamping as forward; dS = P*(dP-delta), dQ accumulates in VMEM
    scratch. delta = rowsum(dO * O) is FUSED into kv step 0 (dO and O are
    already VMEM-resident there) and emitted as a lane-broadcast side output
    — no separate XLA pass over dO/O and no extra HBM round-trip for delta.
  - dK/dV kernel: grid (b, kv_heads, k_blocks, q_blocks) — q innermost so the
    fp32 VMEM accumulators persist across q steps. Causal skip is a pl.when.
    Consumes the dQ kernel's delta output.
  - GQA batching (both kernels): all `group` q-heads of one kv-head arrive in
    one head-blocked q/do/lse block and are FOLDED into the matmul M dim —
    [group, BQ, d] -> [group*BQ, d] — so each program issues one large MXU
    contraction instead of `group` small ones, and K/V blocks stream from HBM
    once per kv-head (not once per q-head).

Layouts: public API is [batch, seq, heads, head_dim] (reference layout);
kernels run on [batch, heads, seq, head_dim].
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
LSE_LANES = 8  # trailing lane dim for lse/delta storage (TPU tiling)
# folded-row cap for the GQA-batched backward kernels (see _pallas_backward;
# mutable for in-process block-size A/Bs — value read at TRACE time)
BWD_ROW_CAP = [int(os.environ.get("PADDLE_TPU_FLASH_BWD_ROWCAP", "1024"))]


def _xla_reference(q, k, v, causal, scale):
    """Plain-XLA attention used as fallback and as the VJP recompute path."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _causal_last_block(qi, block_q, offset, block_k, n_kv):
    """Index of the last kv block a causal q block attends to (clipped into
    range — BlockSpec index_maps must return valid indices even for q blocks
    with no valid keys; those programs are compute-gated off by pl.when)."""
    last_k = qi * block_q + block_q - 1 + offset
    return jnp.clip(last_k // block_k, 0, n_kv - 1)


def _make_kv_idx(causal, block_q, offset, block_k, n_kv):
    """kv-block index map component for kv-innermost grids: clamp future
    (fully-masked) blocks onto the last valid one — consecutive grid steps
    then map to the SAME block and Pallas elides the DMA."""
    def kv_idx(qi, ki):
        if not causal:
            return ki
        return jnp.minimum(ki, _causal_last_block(qi, block_q, offset,
                                                  block_k, n_kv))
    return kv_idx


def _make_q_idx(causal, block_q, offset, block_k, n_q):
    """Mirror of :func:`_make_kv_idx` for the dK/dV kernel's q-innermost
    grid: q blocks entirely BEFORE a k block (run=False there) are clamped
    onto the first valid q block, eliding their q/do/lse/delta DMAs."""
    def q_idx(ki, qi):
        if not causal:
            return qi
        first = (ki * block_k - offset) // block_q
        return jnp.maximum(qi, jnp.clip(first, 0, n_q - 1))
    return q_idx


def _fa_fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal,
                   block_q, block_k, kv_len, q_len, n_kv, with_seg=False,
                   with_rowmask=False):
    """One (batch, head, q-block, kv-block) program. K/V arrive one
    [block_k, d] block per grid step (kv innermost — Pallas double-buffers
    the next block's DMA behind this block's compute); the online-softmax
    state (acc, m, l) persists in fp32 VMEM scratch across the kv steps of a
    q block. With ``with_seg`` the first two extra refs are per-position
    segment ids ([b, s, LSE_LANES] int32) and attention is block-diagonal
    over equal segments (varlen packed batches). With ``with_rowmask`` the
    next two refs are per-KV-COLUMN row bounds ([b, h, s_kv, LSE_LANES]
    int32): q rows in [start[col], end[col]) are masked (the reference's
    flashmask LT masks, nn/functional/flash_attention.py:1098)."""
    if with_seg:
        qseg_ref, kseg_ref = refs[0], refs[1]
        refs = refs[2:]
    if with_rowmask:
        start_ref, end_ref = refs[0], refs[1]
        refs = refs[2:]
    o_ref = refs[0]
    # refs after o_ref: [lse_ref (train path only)] + [acc_sc, m_sc, l_sc]
    if len(refs) == 5:
        lse_ref = refs[1]
        acc_sc, m_sc, l_sc = refs[2], refs[3], refs[4]
    else:
        lse_ref = None
        acc_sc, m_sc, l_sc = refs[1], refs[2], refs[3]
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    # End-aligned causal offset: q row i attends k cols <= i + (kv_len - q_len),
    # matching _xla_reference's tril(k=kl-ql) (kv-cache style when kv > q).
    offset = kv_len - q_len
    run = True
    if causal:
        # blocks entirely in the future: no compute (their DMA is already
        # elided by the clamped index_map)
        run = qi * block_q + block_q - 1 + offset >= ki * block_k

    @pl.when(run)
    def _():
        q = q_ref[0, 0].astype(jnp.float32) * scale       # [BQ, d]
        kb = k_ref[0, 0].astype(jnp.float32)              # [BK, d]
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
        if with_seg:
            qs = qseg_ref[0][:, 0]                        # [BQ]
            ks = kseg_ref[0][:, 0]                        # [BK]
            s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
        if with_rowmask:
            st = start_ref[0, 0][:, 0]                    # [BK]
            en = end_ref[0, 0][:, 0]
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            masked = (rows >= st[None, :]) & (rows < en[None, :])
            s = jnp.where(masked, NEG_INF, s)
        m = m_sc[...][:, :1]                              # [BQ, 1]
        l = l_sc[...][:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(ki == n_kv - 1)
    def _():
        l = l_sc[...][:, :1]
        m = m_sc[...][:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_sc[...] / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            # lse (train path only — the primal/inference kernel skips the
            # write) in units of the SCALED logits; rows with no valid keys
            # get NEG_INF. Stored with LSE_LANES trailing lanes (TPU block
            # constraint: the last block dim must be 128-divisible or equal
            # the array dim — 8 lanes beats the library kernel's 128-lane
            # padding on HBM traffic 16x).
            lse = jnp.where(l > 0, m + jnp.log(l_safe), NEG_INF)
            lse_ref[0, 0] = jnp.broadcast_to(jnp.swapaxes(lse, 0, 1),
                                             lse_ref.shape[2:])


def _seg_lanes(seg, s):
    """[b, s] int32 -> [b, s, LSE_LANES] (TPU block tiling)."""
    seg = seg.astype(jnp.int32)
    return jnp.broadcast_to(seg[..., None], seg.shape + (LSE_LANES,))


def _pallas_forward(q, k, v, causal, scale, block_q, block_k, interpret,
                    with_lse=True, q_seg=None, kv_seg=None,
                    row_start=None, row_end=None):
    """q,k,v in [b, s, h, d]. Returns (out [b,s,h,d],
    lse [b, hq, LSE_LANES, s_q] fp32 (lane-broadcast, lanes second-minor so
    the tiled HBM layout pads nothing) — or None when with_lse=False, the
    primal/inference path, which skips the lse HBM write entirely)."""
    from jax.experimental.pallas import tpu as pltpu

    b, s_q, hq, d = q.shape
    _, s_kv, hkv, _ = k.shape
    group = hq // hkv
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    n_kv = s_kv // block_k
    grid = (b, hq, s_q // block_q, n_kv)
    offset = s_kv - s_q
    _kv_idx = _make_kv_idx(causal, block_q, offset, block_k, n_kv)

    with_seg = q_seg is not None
    with_rowmask = row_start is not None
    kernel = functools.partial(
        _fa_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=s_kv, q_len=s_q, n_kv=n_kv,
        with_seg=with_seg, with_rowmask=with_rowmask)
    out_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct(qt.shape, q.dtype)]
    if with_lse:
        # lanes SECOND-minor ([b, h, LANES, s]): the (8,128)-tiled HBM layout
        # then pads nothing, vs 16x expansion for a lanes-minor [.., s, 8]
        # buffer (measured 120MB of padding per 8MB of lse on a 2048-seq
        # batch-8 run — and remat keeps one per layer alive all backward)
        out_specs.append(pl.BlockSpec((1, 1, LSE_LANES, block_q),
                                      lambda bi, hi, qi, ki: (bi, hi, 0, qi)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, hq, LSE_LANES, s_q), jnp.float32))
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki: (bi, hi // group,
                                             _kv_idx(qi, ki), 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki: (bi, hi // group,
                                             _kv_idx(qi, ki), 0)),
    ]
    operands = [qt, kt, vt]
    if with_seg:
        in_specs += [
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bi, hi, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((1, block_k, LSE_LANES),
                         lambda bi, hi, qi, ki: (bi, _kv_idx(qi, ki), 0)),
        ]
        operands += [_seg_lanes(q_seg, s_q), _seg_lanes(kv_seg, s_kv)]
    if with_rowmask:
        # bounds are per kv-HEAD [b, hkv, s_kv]; q-head hi maps via hi//group
        hm = row_start.shape[1]
        in_specs += [
            pl.BlockSpec((1, 1, block_k, LSE_LANES),
                         lambda bi, hi, qi, ki: (bi, (hi // group) % hm,
                                                 _kv_idx(qi, ki), 0)),
            pl.BlockSpec((1, 1, block_k, LSE_LANES),
                         lambda bi, hi, qi, ki: (bi, (hi // group) % hm,
                                                 _kv_idx(qi, ki), 0)),
        ]
        operands += [_seg_lanes(row_start.astype(jnp.int32), s_kv),
                     _seg_lanes(row_end.astype(jnp.int32), s_kv)]
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),          # acc
            pltpu.VMEM((block_q, LSE_LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, LSE_LANES), jnp.float32),  # running sum
        ],
        interpret=interpret,
    )(*operands)
    lse = res[1] if with_lse else None
    return jnp.swapaxes(res[0], 1, 2), lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _fold_heads(x):
    """[group, rows, d] -> [group*rows, d] (contiguous collapse of the two
    leading dims — free on TPU, rows stay sublane-major)."""
    g, r, d = x.shape
    return x.reshape(g * r, d)


def _fold_lanes(ref_slice):
    """[group, LANES, BQ] lane-broadcast lse/delta block -> [group*BQ, 1]
    column (one small [1, BQ] -> [BQ, 1] relayout per group, batched)."""
    g, _, bq = ref_slice.shape
    col = jnp.swapaxes(ref_slice[:, :1, :], 1, 2)          # [g, BQ, 1]
    return col.reshape(g * bq, 1)


def _row_positions(qi, block_q, group, block_k):
    """Absolute q positions for the folded [group*BQ, BK] score rows: row r
    of the fold is q row (r % BQ) of q block qi (heads repeat the rows)."""
    r = jax.lax.broadcasted_iota(jnp.int32, (group * block_q, block_k), 0)
    return qi * block_q + r % block_q


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, *refs,
                      scale, causal, block_q, block_k, kv_len, q_len, n_kv,
                      group, with_glse=False, with_seg=False,
                      with_rowmask=False):
    """dQ for one (batch, KV head, q_block, kv_block); K/V stream through the
    innermost grid dim like forward, fetched ONCE per kv-head (all `group`
    q-heads fold into the matmul M dim). delta = rowsum(dO*O) [− l̄] is
    computed at kv step 0 (dO/O are VMEM-resident) into scratch and emitted
    as a lane-broadcast side output for the dK/dV kernel — the separate XLA
    delta pass and its HBM round-trip are gone."""
    if with_glse:
        glse_ref = refs[0]
        refs = refs[1:]
    if with_seg:
        qseg_ref, kseg_ref = refs[0], refs[1]
        refs = refs[2:]
    if with_rowmask:
        start_ref, end_ref = refs[0], refs[1]
        refs = refs[2:]
    dq_ref, delta_ref, dq_sc, delta_sc = refs
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _():
        do0 = _fold_heads(do_ref[0].astype(jnp.float32))   # [G*BQ, d]
        o0 = _fold_heads(o_ref[0].astype(jnp.float32))
        delta = jnp.sum(do0 * o0, axis=-1, keepdims=True)  # [G*BQ, 1]
        if with_glse:
            # ring attention's lse cotangent folds into delta: ds = p·(dp−δ+l̄)
            delta = delta - _fold_lanes(glse_ref[0])
        dq_sc[...] = jnp.zeros_like(dq_sc)
        delta_sc[...] = jnp.broadcast_to(delta, delta_sc.shape)
        # delta output is lanes-second-minor [group, LANES, BQ] like lse
        dcol = jnp.swapaxes(delta.reshape(group, block_q, 1), 1, 2)
        delta_ref[0] = jnp.broadcast_to(dcol, delta_ref.shape[1:])

    offset = kv_len - q_len
    run = True
    if causal:
        run = qi * block_q + block_q - 1 + offset >= ki * block_k

    @pl.when(run)
    def _():
        q = _fold_heads(q_ref[0].astype(jnp.float32))      # [G*BQ, d]
        do = _fold_heads(do_ref[0].astype(jnp.float32))
        lse = _fold_lanes(lse_ref[0])                      # [G*BQ, 1]
        delta = delta_sc[...][:, :1]                       # [G*BQ, 1]
        kb = k_ref[0, 0].astype(jnp.float32)               # [BK, d]
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = _row_positions(qi, block_q, group, block_k)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (group * block_q, block_k), 1)
            s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
        if with_seg:
            qs = qseg_ref[0][:, 0]                         # [BQ]
            ks = kseg_ref[0][:, 0]
            qs = jnp.broadcast_to(qs[None, :], (group, block_q)).reshape(-1)
            s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
        if with_rowmask:
            st = start_ref[0, 0][:, 0]
            en = end_ref[0, 0][:, 0]
            rows = _row_positions(qi, block_q, group, block_k)
            s = jnp.where((rows >= st[None, :]) & (rows < en[None, :]),
                          NEG_INF, s)
        # rows with no valid keys store lse = NEG_INF; exp(s - lse) would give
        # p = 1 there (s is NEG_INF too) — force those rows to zero instead
        p = jnp.where(lse > NEG_INF / 2, jnp.exp(s - lse), 0.0)  # [G*BQ, BK]
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                      # [G*BQ, BK]
        dq_sc[...] = dq_sc[...] + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kv - 1)
    def _():
        dq_ref[0] = dq_sc[...].reshape(group, block_q, -1).astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       *refs, scale, causal,
                       block_q, block_k, kv_len, q_len, group, with_seg=False,
                       with_rowmask=False):
    """dK/dV for one (batch, kv_head, k_block); q_blocks is the innermost grid
    dim so dk_acc/dv_acc VMEM scratch persists and accumulates across q steps.
    All `group` q-heads of this kv-head arrive in one head-blocked q block and
    fold into the contraction dims: one [G*BQ, BK] score matrix, dV/dK as
    single G*BQ-deep contractions (vs `group` small ones)."""
    if with_seg:
        qseg_ref, kseg_ref = refs[0], refs[1]
        refs = refs[2:]
    if with_rowmask:
        start_ref, end_ref = refs[0], refs[1]
        refs = refs[2:]
    dk_ref, dv_ref, dk_acc, dv_acc = refs
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    offset = kv_len - q_len

    @pl.when(qi == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # causal: skip q blocks entirely in the past of this k block
    run = True
    if causal:
        run = qi * block_q + block_q - 1 + offset >= ki * block_k

    @pl.when(run)
    def _():
        kb = k_ref[0, 0].astype(jnp.float32)               # [BK, d]
        vb = v_ref[0, 0].astype(jnp.float32)               # [BK, d]
        q = _fold_heads(q_ref[0].astype(jnp.float32))      # [G*BQ, d]
        do = _fold_heads(do_ref[0].astype(jnp.float32))
        lse = _fold_lanes(lse_ref[0])                      # [G*BQ, 1]
        delta = _fold_lanes(delta_ref[0])
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = _row_positions(qi, block_q, group, block_k)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (group * block_q, block_k), 1)
            s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
        if with_seg:
            qsg = qseg_ref[0][:, 0]
            ksg = kseg_ref[0][:, 0]
            qsg = jnp.broadcast_to(qsg[None, :], (group, block_q)).reshape(-1)
            s = jnp.where(qsg[:, None] == ksg[None, :], s, NEG_INF)
        if with_rowmask:
            st = start_ref[0, 0][:, 0]
            en = end_ref[0, 0][:, 0]
            rows = _row_positions(qi, block_q, group, block_k)
            s = jnp.where((rows >= st[None, :]) & (rows < en[None, :]),
                          NEG_INF, s)
        # see dq kernel: fully-masked rows (lse == NEG_INF) must give p = 0
        p = jnp.where(lse > NEG_INF / 2, jnp.exp(s - lse), 0.0)  # [G*BQ, BK]
        # dV += P^T · dO — one G*BQ-deep contraction
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                      # [G*BQ, BK]
        # dK += dS^T · Q
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _pallas_backward(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                     interpret, g_lse=None, q_seg=None, kv_seg=None,
                     row_start=None, row_end=None):
    """All arrays in the public [b, s, h, d] layout.

    lse is the forward's [b, hq, LSE_LANES, s_q] output (lanes second-minor,
    value broadcast across the lane dim).
    ``g_lse`` [b, hq, s_q] is an optional cotangent on the lse OUTPUT (ring
    attention's merge differentiates through it): with l̄ present the score
    gradient becomes ds = p·(dp − delta + l̄), i.e. l̄ just shifts delta."""
    from jax.experimental.pallas import tpu as pltpu

    b, s_q, hq, d = q.shape
    _, s_kv, hkv, _ = k.shape
    group = hq // hkv

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    dot = jnp.swapaxes(do, 1, 2)
    ot = jnp.swapaxes(o, 1, 2)

    n_kv = s_kv // block_k
    offset = s_kv - s_q

    with_glse = g_lse is not None
    with_seg = q_seg is not None
    with_rowmask = row_start is not None
    seg_ops = ([_seg_lanes(q_seg, s_q), _seg_lanes(kv_seg, s_kv)]
               if with_seg else [])
    if with_rowmask:
        seg_ops += [_seg_lanes(row_start.astype(jnp.int32), s_kv),
                    _seg_lanes(row_end.astype(jnp.int32), s_kv)]
        hm = row_start.shape[1]

    # GQA folding multiplies the score-matrix rows by `group`; bound the
    # folded [rows, block_k] f32 score/p/dp/ds working set (it must fit the
    # ~16MB scoped-VMEM stack: 2048 rows x 512 cols OOMed). First shrink the
    # q block toward rows <= 1024 (still ≥128: block_q is minor in the lse
    # layout), then — for very wide groups (MQA, group > 8) where even
    # bq=128 exceeds the row cap — shrink the backward's k block so
    # rows * block_k stays <= 1024 * 512.
    # on-chip A/B (benchmarks/flash_block_ab.py, GQA 16/4 d128): folded-row
    # cap 1024 is fastest at seq 4096 (33.6 vs 25.2 TF/s for 2048), while
    # long context flips — at seq 16384 cap 2048 (bq 512, bk auto-halved to
    # 256) wins 67.4 vs 64.7 TF/s. Default: 1024 short, 2048 at >= 8k.
    row_cap = BWD_ROW_CAP[0]
    if s_q >= 8192 and row_cap == 1024:
        row_cap = 2048
    bq_dq = block_q
    for c in (512, 256, 128):
        if group * c <= row_cap and c <= block_q and s_q % c == 0:
            bq_dq = c
            break
    else:
        if 128 <= block_q and s_q % 128 == 0:
            bq_dq = 128
    bk_dq = block_k
    while (group * bq_dq * bk_dq > row_cap * 512 and bk_dq > 128
           and bk_dq % 2 == 0 and s_kv % (bk_dq // 2) == 0):
        bk_dq //= 2

    # ---- dQ (+ fused delta side output) ----
    # grid is over KV heads: all `group` q-heads of a kv-head are handled by
    # one program (folded into the matmul M dim), so K/V stream once per
    # kv-head instead of once per q-head.
    n_kv_b = s_kv // bk_dq
    grid_dq = (b, hkv, s_q // bq_dq, n_kv_b)
    dq_kernel = functools.partial(
        _fa_bwd_dq_kernel, scale=scale, causal=causal,
        block_q=bq_dq, block_k=bk_dq, kv_len=s_kv, q_len=s_q, n_kv=n_kv_b,
        group=group, with_glse=with_glse, with_seg=with_seg,
        with_rowmask=with_rowmask)
    _kv_idx_dq = _make_kv_idx(causal, bq_dq, offset, bk_dq, n_kv_b)
    _qb = pl.BlockSpec((1, group, bq_dq, d),
                       lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    _qlanes = pl.BlockSpec((1, group, LSE_LANES, bq_dq),
                           lambda bi, hi, qi, ki: (bi, hi, 0, qi))
    _kvb = pl.BlockSpec((1, 1, bk_dq, d),
                        lambda bi, hi, qi, ki: (bi, hi,
                                                _kv_idx_dq(qi, ki), 0))
    dq_in_specs = [_qb, _kvb, _kvb, _qb, _qb, _qlanes]
    dq_ops = [qt, kt, vt, dot, ot, lse]
    if with_glse:
        dq_in_specs.append(_qlanes)
        glse_lanes = jnp.broadcast_to(
            g_lse.astype(jnp.float32)[:, :, None, :],
            g_lse.shape[:2] + (LSE_LANES,) + g_lse.shape[2:])
        dq_ops.append(glse_lanes)
    if with_seg:
        dq_in_specs += [
            pl.BlockSpec((1, bq_dq, LSE_LANES),
                         lambda bi, hi, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((1, bk_dq, LSE_LANES),
                         lambda bi, hi, qi, ki: (bi, _kv_idx_dq(qi, ki), 0)),
        ]
    if with_rowmask:
        dq_in_specs += [
            pl.BlockSpec((1, 1, bk_dq, LSE_LANES),
                         lambda bi, hi, qi, ki: (bi, hi % hm,
                                                 _kv_idx_dq(qi, ki), 0)),
            pl.BlockSpec((1, 1, bk_dq, LSE_LANES),
                         lambda bi, hi, qi, ki: (bi, hi % hm,
                                                 _kv_idx_dq(qi, ki), 0)),
        ]
    dq, delta = pl.pallas_call(
        dq_kernel,
        grid=grid_dq,
        in_specs=dq_in_specs,
        out_specs=[_qb, _qlanes],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, hq, LSE_LANES, s_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((group * bq_dq, d), jnp.float32),          # dq acc
            pltpu.VMEM((group * bq_dq, LSE_LANES), jnp.float32),  # delta
        ],
        interpret=interpret,
    )(*dq_ops, *seg_ops)

    # ---- dK / dV ----
    # q-heads blocked by `group` so one program sees every q-head of its
    # kv-head; q_blocks innermost so VMEM accumulators carry across q steps.
    _q_idx = _make_q_idx(causal, bq_dq, offset, bk_dq, s_q // bq_dq)
    grid_dkv = (b, hkv, s_kv // bk_dq, s_q // bq_dq)
    dkv_kernel = functools.partial(
        _fa_bwd_dkv_kernel, scale=scale, causal=causal,
        block_q=bq_dq, block_k=bk_dq, kv_len=s_kv, q_len=s_q, group=group,
        with_seg=with_seg, with_rowmask=with_rowmask)
    dkv_in_specs = [
        pl.BlockSpec((1, group, bq_dq, d),
                     lambda bi, hi, ki, qi: (bi, hi, _q_idx(ki, qi), 0)),
        pl.BlockSpec((1, 1, bk_dq, d),
                     lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        pl.BlockSpec((1, 1, bk_dq, d),
                     lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        pl.BlockSpec((1, group, bq_dq, d),
                     lambda bi, hi, ki, qi: (bi, hi, _q_idx(ki, qi), 0)),
        pl.BlockSpec((1, group, LSE_LANES, bq_dq),
                     lambda bi, hi, ki, qi: (bi, hi, 0, _q_idx(ki, qi))),
        pl.BlockSpec((1, group, LSE_LANES, bq_dq),
                     lambda bi, hi, ki, qi: (bi, hi, 0, _q_idx(ki, qi))),
    ]
    if with_seg:
        dkv_in_specs += [
            pl.BlockSpec((1, bq_dq, LSE_LANES),
                         lambda bi, hi, ki, qi: (bi, _q_idx(ki, qi), 0)),
            pl.BlockSpec((1, bk_dq, LSE_LANES),
                         lambda bi, hi, ki, qi: (bi, ki, 0)),
        ]
    if with_rowmask:
        dkv_in_specs += [
            pl.BlockSpec((1, 1, bk_dq, LSE_LANES),
                         lambda bi, hi, ki, qi: (bi, hi % hm, ki, 0)),
            pl.BlockSpec((1, 1, bk_dq, LSE_LANES),
                         lambda bi, hi, ki, qi: (bi, hi % hm, ki, 0)),
        ]
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=grid_dkv,
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk_dq, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk_dq, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kt.shape, k.dtype),
            jax.ShapeDtypeStruct(vt.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk_dq, d), jnp.float32),
            pltpu.VMEM((bk_dq, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta, *seg_ops)

    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


# ---------------------------------------------------------------------------
# dispatch + custom_vjp
# ---------------------------------------------------------------------------

def _use_pallas(q, k, block_q, block_k, interpret):
    # shape guards apply in interpret mode too — a non-divisible seq would leave
    # output rows unwritten / drop kv tokens silently. block_q additionally
    # sits in the MINOR dim of the lse/delta blocks ([.., LANES, block_q]),
    # so it must be 128-divisible or the whole sequence (Mosaic tiling).
    s_q, s_kv = q.shape[1], k.shape[1]
    shapes_ok = (s_q % block_q == 0 and s_kv % block_k == 0
                 and (block_q % 128 == 0 or block_q == s_q)
                 and q.shape[2] % k.shape[2] == 0)
    if interpret:
        return shapes_ok
    if jax.default_backend() != "tpu":
        return False
    return shapes_ok and q.shape[3] in (64, 128, 256)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    if _use_pallas(q, k, block_q, block_k, interpret):
        # primal (inference) path: skip the lse output entirely
        return _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret, with_lse=False)[0]
    return _xla_reference(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if _use_pallas(q, k, block_q, block_k, interpret):
        from jax.ad_checkpoint import checkpoint_name

        out, lse = _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                                   interpret)
        # named so a remat policy can SAVE these residuals — backward then
        # skips re-running the flash forward kernel (save-attention-out remat)
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return out, (q, k, v, out, lse)
    return _xla_reference(q, k, v, causal, scale), (q, k, v, None, None)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    if lse is not None:
        return _pallas_backward(q, k, v, o, lse, g, causal, scale,
                                block_q, block_k, interpret)
    _, vjp = jax.vjp(lambda a, b, c: _xla_reference(a, b, c, causal, scale),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _xla_reference_lse(q, k, v, causal, scale):
    """XLA fallback returning (out, lse [b, hq, s_q] fp32 of SCALED logits)."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, NEG_INF)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)   # [b, h, q]
    probs = jnp.exp(logits - lse[..., None])
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_with_lse(q, k, v, causal, scale, block_q, block_k,
                             interpret):
    """(out [b,s,h,d], lse [b, hq, s_q] fp32) — differentiable INCLUDING the
    lse output (ring attention's online-softmax merge needs d/dlse; the
    backward folds the lse cotangent into the delta term: ds = p·(dp−δ+l̄))."""
    if _use_pallas(q, k, block_q, block_k, interpret):
        out, lse4 = _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                                    interpret, with_lse=True)
        return out, lse4[:, :, 0, :]
    return _xla_reference_lse(q, k, v, causal, scale)


def _fwl_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if _use_pallas(q, k, block_q, block_k, interpret):
        out, lse4 = _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                                    interpret, with_lse=True)
        return (out, lse4[:, :, 0, :]), (q, k, v, out, lse4)
    out, lse = _xla_reference_lse(q, k, v, causal, scale)
    return (out, lse), (q, k, v, None, None)


def _fwl_bwd(causal, scale, block_q, block_k, interpret, res, cots):
    q, k, v, o, lse4 = res
    g_out, g_lse = cots
    if lse4 is not None:
        return _pallas_backward(q, k, v, o, lse4, g_out, causal, scale,
                                block_q, block_k, interpret, g_lse=g_lse)
    _, vjp = jax.vjp(
        lambda a, b, c: _xla_reference_lse(a, b, c, causal, scale), q, k, v)
    return vjp((g_out, g_lse))


flash_attention_with_lse.defvjp(_fwl_fwd, _fwl_bwd)


def _tuned_block(n: int, kv: bool = False) -> int:
    """Largest of 512/256/128 dividing n (v5e-profiled: 512 blocks reach
    ~25 TF/s fwd+bwd at head_dim 128 vs ~8 TF/s at the library defaults).
    Long-context KV side: 1024 at seq >= 8192 — halves the kv grid steps and
    their DMA issue overhead (on-chip A/B at 16k GQA 16/4: 50.4 vs 54.2 ms
    fwd+bwd, +7.5%; the backward's VMEM guard re-halves its own k block, so
    only the forward stream widens). Sequences shorter than 128 use one
    whole-sequence block; longer sequences not divisible by 128 get the
    default block, which fails the divisibility guard in _use_pallas and
    routes to the XLA fallback (a whole-sequence block there would
    materialize [s, s] scores in VMEM)."""
    if kv and n >= 8192 and n % 1024 == 0:
        return 1024
    for b in (512, 256, 128):
        if n % b == 0:
            return b
    return n if n < 128 else DEFAULT_BLOCK_Q


def _jax_tuned_flash(q, k, v, causal, scale):
    """jax's library TPU flash kernel — kept as an A/B comparison path
    (PADDLE_TPU_FLASH_IMPL=jaxlib). MHA, q_len == kv_len only."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as jfa)

    qh = jnp.swapaxes(q, 1, 2)  # -> [b, h, s, d]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    bq = _tuned_block(qh.shape[2])
    bk = _tuned_block(kh.shape[2])
    bs = BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)
    out = jfa(qh, kh, vh, causal=causal, sm_scale=float(scale),
              block_sizes=bs)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = 0, block_k: int = 0,
                    interpret: bool = False):
    """q,k,v: [batch, seq, heads, head_dim] (reference layout,
    nn/functional/flash_attention.py:195). Returns same layout/dtype as q.

    Production path is the IN-REPO Pallas kernel pair (fwd with logsumexp +
    FlashAttention-2 backward), covering MHA, GQA (q-head→kv-head folded into
    BlockSpec index maps — K/V never repeated), and kv-cache decode
    (q_len != kv_len via END-aligned causal masking, tril(k=kv-q)).
    Set PADDLE_TPU_FLASH_IMPL=jaxlib to A/B against jax's library kernel
    (MHA equal-length shapes only). Non-divisible / odd shapes fall back to
    the XLA reference implementation."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    impl = os.environ.get("PADDLE_TPU_FLASH_IMPL", "")
    if (impl == "jaxlib" and not interpret and jax.default_backend() == "tpu"
            and q.shape[1] == k.shape[1] and q.shape[1] % 128 == 0
            and q.shape[-1] in (64, 128, 256)
            and q.shape[2] == k.shape[2]):
        return _jax_tuned_flash(q, k, v, causal, scale)
    bq = min(block_q or _tuned_block(q.shape[1]), q.shape[1])
    bk = min(block_k or _tuned_block(k.shape[1], kv=True), k.shape[1])
    return _flash(q, k, v, causal, float(scale), bq, bk, interpret)


# ---------------------------------------------------------------------------
# varlen (packed, segment-masked) attention
# ---------------------------------------------------------------------------

def _xla_varlen_reference(q, k, v, q_seg, kv_seg, causal, scale):
    """Dense-mask fallback: attention restricted to equal segment ids."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    mask = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = mask & jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
    logits = jnp.where(mask, logits, NEG_INF)
    # fully-masked rows (padding segments) -> zero output, not NaN
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p / jnp.maximum(l, 1e-30), vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _seg_zero_cot(seg):
    import numpy as _np

    return _np.zeros(seg.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention_varlen(q, k, v, q_seg, kv_seg, causal=True, scale=None,
                           block_q=0, block_k=0, interpret=False):
    """Packed variable-length attention as a KERNEL (reference:
    nn/functional/flash_attention.py:792 varlen over the CUDA varlen kernels).

    q/k/v: [b, s, h, d]; q_seg/kv_seg: [b, s] int32 segment ids — attention is
    block-diagonal over equal segments (plus causal within each segment, since
    packed positions are monotone per segment). Runs the in-repo Pallas flash
    kernels fwd+bwd with the segment mask folded into the score masking; CPU /
    non-divisible shapes take a dense-mask XLA path."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bq = min(block_q or _tuned_block(q.shape[1]), q.shape[1])
    bk = min(block_k or _tuned_block(k.shape[1], kv=True), k.shape[1])
    if _use_pallas(q, k, bq, bk, interpret):
        return _pallas_forward(q, k, v, causal, float(scale), bq, bk,
                               interpret, with_lse=False,
                               q_seg=q_seg, kv_seg=kv_seg)[0]
    return _xla_varlen_reference(q, k, v, q_seg, kv_seg, causal, float(scale))


def _fav_fwd(q, k, v, q_seg, kv_seg, causal, scale, block_q, block_k,
             interpret):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bq = min(block_q or _tuned_block(q.shape[1]), q.shape[1])
    bk = min(block_k or _tuned_block(k.shape[1], kv=True), k.shape[1])
    if _use_pallas(q, k, bq, bk, interpret):
        out, lse = _pallas_forward(q, k, v, causal, float(scale), bq, bk,
                                   interpret, with_lse=True,
                                   q_seg=q_seg, kv_seg=kv_seg)
        return out, (q, k, v, q_seg, kv_seg, out, lse)
    out = _xla_varlen_reference(q, k, v, q_seg, kv_seg, causal, float(scale))
    return out, (q, k, v, q_seg, kv_seg, None, None)


def _fav_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, q_seg, kv_seg, o, lse = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if lse is not None:
        bq = min(block_q or _tuned_block(q.shape[1]), q.shape[1])
        bk = min(block_k or _tuned_block(k.shape[1], kv=True), k.shape[1])
        dq, dk, dv = _pallas_backward(q, k, v, o, lse, g, causal, float(scale),
                                      bq, bk, interpret,
                                      q_seg=q_seg, kv_seg=kv_seg)
        return dq, dk, dv, _seg_zero_cot(q_seg), _seg_zero_cot(kv_seg)
    _, vjp = jax.vjp(
        lambda a, b, c: _xla_varlen_reference(a, b, c, q_seg, kv_seg, causal,
                                              float(scale)), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, _seg_zero_cot(q_seg), _seg_zero_cot(kv_seg)


flash_attention_varlen.defvjp(_fav_fwd, _fav_bwd)


# ---------------------------------------------------------------------------
# flashmask (per-column row-bound sparse masks) attention
# ---------------------------------------------------------------------------

def _xla_rowmask_reference(q, k, v, row_start, row_end, causal, scale):
    """Dense fallback: q row r masked from kv col c iff start[c] <= r < end[c].
    row bounds: [b, hm, s_kv] with hm in {1, kv_heads}."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    hq, hkv = qh.shape[1], kh.shape[1]
    if hkv != hq:
        rep = hq // hkv
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    ql, kl = logits.shape[-2], logits.shape[-1]
    hm = row_start.shape[1]
    st = jnp.repeat(row_start, hq // hm, axis=1) if hm not in (1,) else row_start
    en = jnp.repeat(row_end, hq // hm, axis=1) if hm not in (1,) else row_end
    rows = jnp.arange(ql)[None, None, :, None]
    blocked = (rows >= st[:, :, None, :]) & (rows < en[:, :, None, :])
    keep = ~blocked
    if causal:
        keep = keep & jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
    logits = jnp.where(keep, logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p / jnp.maximum(l, 1e-30), vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention_rowmask(q, k, v, row_start, row_end, causal=True,
                            scale=None, block_q=0, block_k=0,
                            interpret=False):
    """Flashmask attention as a KERNEL (reference:
    nn/functional/flash_attention.py:1098 flashmask_attention): per-KV-column
    row bounds [b, hm, s_kv] (hm in {1, kv_heads}) mask q rows in
    [start[c], end[c]) — the reference's LT sparse-mask encoding — streamed
    through the Pallas flash kernels fwd+bwd. CPU / odd shapes take a dense
    path."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bq = min(block_q or _tuned_block(q.shape[1]), q.shape[1])
    bk = min(block_k or _tuned_block(k.shape[1], kv=True), k.shape[1])
    if _use_pallas(q, k, bq, bk, interpret):
        return _pallas_forward(q, k, v, causal, float(scale), bq, bk,
                               interpret, with_lse=False,
                               row_start=row_start, row_end=row_end)[0]
    return _xla_rowmask_reference(q, k, v, row_start, row_end, causal,
                                  float(scale))


def _far_fwd(q, k, v, row_start, row_end, causal, scale, block_q, block_k,
             interpret):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bq = min(block_q or _tuned_block(q.shape[1]), q.shape[1])
    bk = min(block_k or _tuned_block(k.shape[1], kv=True), k.shape[1])
    if _use_pallas(q, k, bq, bk, interpret):
        out, lse = _pallas_forward(q, k, v, causal, float(scale), bq, bk,
                                   interpret, with_lse=True,
                                   row_start=row_start, row_end=row_end)
        return out, (q, k, v, row_start, row_end, out, lse)
    out = _xla_rowmask_reference(q, k, v, row_start, row_end, causal,
                                 float(scale))
    return out, (q, k, v, row_start, row_end, None, None)


def _far_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, row_start, row_end, o, lse = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if lse is not None:
        bq = min(block_q or _tuned_block(q.shape[1]), q.shape[1])
        bk = min(block_k or _tuned_block(k.shape[1], kv=True), k.shape[1])
        dq, dk, dv = _pallas_backward(q, k, v, o, lse, g, causal,
                                      float(scale), bq, bk, interpret,
                                      row_start=row_start, row_end=row_end)
    else:
        _, vjp = jax.vjp(
            lambda a, b, c: _xla_rowmask_reference(
                a, b, c, row_start, row_end, causal, float(scale)), q, k, v)
        dq, dk, dv = vjp(g)
    return dq, dk, dv, _seg_zero_cot(row_start), _seg_zero_cot(row_end)


flash_attention_rowmask.defvjp(_far_fwd, _far_bwd)


# Back-compat name used by nn.functional
flash_attention_fwd = flash_attention
