"""Flash attention — Pallas TPU kernel (online softmax, block-streamed K/V).

Replaces the reference's vendored CUDA flashattn (dynload wrapper
/root/reference/paddle/phi/backends/dynload/flashattn.cc, python surface
nn/functional/flash_attention.py:195). TPU design:
  - grid (batch, q_heads, q_blocks); K/V stream through VMEM in BLOCK_K chunks
  - fp32 running max/sum (online softmax), bf16 MXU matmuls
  - causal grids skip fully-masked K blocks (upper bound on the fori_loop)
  - GQA: q-head → kv-head mapping folded into the BlockSpec index_map, so
    K/V are never materialized per-q-head (the XLA fallback repeats them)
Backward: rematerialized XLA attention VJP (correct, XLA-fused); a dedicated
Pallas backward kernel is a later optimization.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _xla_reference(q, k, v, causal, scale):
    """Plain-XLA attention used as fallback and as the VJP recompute path."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q, block_k,
               kv_len, q_len):
    """One (batch, head, q-block) program; streams K/V in block_k chunks."""
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [BQ, d]
    d = q.shape[-1]

    # End-aligned causal offset: q row i attends k cols <= i + (kv_len - q_len),
    # matching _xla_reference's tril(k=kl-ql) (kv-cache style when kv > q).
    offset = kv_len - q_len
    num_kv = kv_len // block_k
    if causal:
        # blocks entirely in the future are skipped (dynamic trip count)
        last_k = qi * block_q + block_q - 1 + offset
        num_kv = jnp.clip((last_k + block_k) // block_k, 0, num_kv)

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)


def _pallas_attention(q, k, v, causal, scale, block_q, block_k, interpret):
    b, s_q, hq, d = q.shape
    _, s_kv, hkv, _ = k.shape
    group = hq // hkv
    # [b, h, s, d] layout for blocking
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    grid = (b, hq, s_q // block_q)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=s_kv, q_len=s_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s_kv, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, s_kv, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2)


def _use_pallas(q, k, block_q, block_k, interpret):
    # shape guards apply in interpret mode too — a non-divisible seq would leave
    # output rows unwritten / drop kv tokens silently
    s_q, s_kv = q.shape[1], k.shape[1]
    shapes_ok = s_q % block_q == 0 and s_kv % block_k == 0
    if interpret:
        return shapes_ok
    if jax.default_backend() != "tpu":
        return False
    return shapes_ok and q.shape[3] in (64, 128, 256)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    if _use_pallas(q, k, block_q, block_k, interpret):
        return _pallas_attention(q, k, v, causal, scale, block_q, block_k, interpret)
    return _xla_reference(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _xla_reference(a, b, c, causal, scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _tuned_block(n: int) -> int:
    """Largest of 512/256/128 dividing n (v5e-profiled: 512 blocks reach
    ~25 TF/s fwd+bwd at head_dim 128 vs ~8 TF/s at the library defaults)."""
    for b in (512, 256, 128):
        if n % b == 0:
            return b
    return n


def _jax_tuned_flash(q, k, v, causal, scale):
    """Route to jax's tuned TPU Pallas flash kernels (fwd AND bwd kernels —
    our in-repo kernel still uses the XLA-recompute VJP, which materializes
    [s, s] logits in backward and is ~3x slower at seq 2048)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as jfa)

    qh = jnp.swapaxes(q, 1, 2)  # -> [b, h, s, d]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    bq = _tuned_block(qh.shape[2])
    bk = _tuned_block(kh.shape[2])
    bs = BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)
    out = jfa(qh, kh, vh, causal=causal, sm_scale=float(scale),
              block_sizes=bs)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False):
    """q,k,v: [batch, seq, heads, head_dim] (reference layout,
    nn/functional/flash_attention.py:195). Returns same layout/dtype as q.

    On TPU, MHA self-attention shapes dispatch to jax's tuned Pallas flash
    kernels (fwd + dedicated bwd; ~3x faster at seq 2048). Kept on the
    in-repo online-softmax kernel:
      - GQA (q_heads != kv_heads): the in-repo kernel maps q-head→kv-head in
        its BlockSpec index_map without materializing repeated K/V
      - q_len != kv_len (kv-cache decode): the in-repo kernel/_xla_reference
        use END-aligned causal masking (tril(k=kv-q)); jax's kernel is
        top-left aligned, which would silently mask out the cache
      - CPU/interpret mode (tests)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if (not interpret and jax.default_backend() == "tpu"
            and q.shape[1] == k.shape[1] and q.shape[1] % 128 == 0
            and q.shape[-1] in (64, 128, 256)
            and q.shape[2] == k.shape[2]):
        return _jax_tuned_flash(q, k, v, causal, scale)
    bq = min(block_q, q.shape[1])
    bk = min(block_k, k.shape[1])
    return _flash(q, k, v, causal, float(scale), bq, bk, interpret)


# Back-compat name used by nn.functional
flash_attention_fwd = flash_attention
