"""Paged (block) KV-cache attention — Pallas TPU kernels for batched serving.

TPU-native replacement for the reference's paged serving kernels
(/root/reference/paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
python surface python/paddle/incubate/nn/functional/block_multihead_attention.py):
KV lives in a pool of fixed-size pages; each sequence owns a list of pages via a
block table, so cache memory is bounded by total tokens, not batch × max_len.

Layouts (reference block_multihead_attention):
  k_cache/v_cache: [num_pages, kv_heads, page_size, head_dim]
  block_tables:    [batch, pages_per_seq] int32 (-1 = unassigned)
  context_lens:    [batch] int32 — tokens already in cache (incl. current step)

Decode kernel design (measured 435 GB/s-class architecture, v5e):
  - grid (batch, kv_heads, seq_chunks); each chunk DMAs G pages of ONE kv head
    HBM→VMEM. The chunk loop is a *grid* dimension, so double buffering runs
    across grid steps: an SMEM buffer index persists, and each step prefetches
    the NEXT VALID (b, h, chunk) step's pages while computing its own.
  - context lengths arrive via scalar prefetch; chunks past a sequence's
    length are skipped entirely (no DMA, no compute).
  - online softmax in fp32 with VMEM carry across chunks; GQA computes all
    `group` q-heads of the kv head in one [group, G*page] block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# XLA reference (tests + CPU fallback)
# ---------------------------------------------------------------------------

def paged_decode_reference(q, k_cache, v_cache, block_tables, context_lens,
                           scale=None):
    """Dense-gather paged decode: q [b, hq, d] -> out [b, hq, d]."""
    b, hq, d = q.shape
    n_pages, hkv, page, _ = k_cache.shape
    group = hq // hkv
    if scale is None:
        scale = d ** -0.5
    max_pages = block_tables.shape[1]
    safe_tables = jnp.maximum(block_tables, 0)
    # [b, max_pages, hkv, page, d] -> [b, hkv, L, d]
    kg = jnp.swapaxes(k_cache[safe_tables], 2, 3).reshape(b, max_pages * page, hkv, d)
    vg = jnp.swapaxes(v_cache[safe_tables], 2, 3).reshape(b, max_pages * page, hkv, d)
    kg = jnp.swapaxes(kg, 1, 2)
    vg = jnp.swapaxes(vg, 1, 2)
    qf = q.reshape(b, hkv, group, d).astype(jnp.float32)
    s = jnp.einsum("bhgd,bhld->bhgl", qf, kg.astype(jnp.float32)) * scale
    pos = jnp.arange(max_pages * page)[None, None, None, :]
    s = jnp.where(pos < context_lens[:, None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgl,bhld->bhgd", p, vg.astype(jnp.float32))
    # zero-length rows (freed/parked slots) return zeros, not garbage
    out = jnp.where(context_lens[:, None, None, None] > 0, out, 0.0)
    return out.reshape(b, hq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas decode kernel
# ---------------------------------------------------------------------------

def _paged_decode_kernel(lens_ref, tables_ref, buf_idx, init_ref,
                         q_ref, k_hbm, v_hbm, o_ref,
                         k_buf, v_buf, acc_ref, m_ref, l_ref,
                         sem, *, page, G, max_pages, scale, group, hkv, batch):
    bi, hi, ci = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    chunk_tokens = page * G
    ctx = lens_ref[bi]
    # every (b, h) processes AT LEAST one chunk even at length 0 — otherwise a
    # zero-length row would break the prefetch chain and the next valid row
    # would wait on semaphores armed with the wrong pages (its own output is
    # forced to zeros at the final-store below; neighbors must stay correct)
    n_chunks_b = jnp.maximum((ctx + chunk_tokens - 1) // chunk_tokens, 1)

    def chunk_copies(slot, b2, h2, c2):
        out = []
        for g in range(G):
            pidx = jnp.maximum(tables_ref[b2 * max_pages + c2 * G + g], 0)
            out.append(pltpu.make_async_copy(
                k_hbm.at[pidx, h2], k_buf.at[slot, g], sem.at[slot, 0]))
            out.append(pltpu.make_async_copy(
                v_hbm.at[pidx, h2], v_buf.at[slot, g], sem.at[slot, 1]))
        return out

    def next_step(b2, h2, c2):
        # lexicographic next VALID step in (b, h, chunk) grid order —
        # chunks beyond a sequence's length are skipped by everyone
        # (min 1 chunk per (b, h): matches n_chunks_b above)
        nb = jnp.maximum((lens_ref[b2] + chunk_tokens - 1) // chunk_tokens, 1)
        c3 = c2 + 1
        roll_h = c3 >= nb
        h3 = jnp.where(roll_h, h2 + 1, h2)
        c3 = jnp.where(roll_h, 0, c3)
        roll_b = h3 >= hkv
        b3 = jnp.where(roll_b, b2 + 1, b2)
        h3 = jnp.where(roll_b, 0, h3)
        return b3, h3, c3

    @pl.when(ci < n_chunks_b)
    def _():
        # very first valid step of the whole grid: no one prefetched for us
        # (init flag arrives as a scalar-prefetch input set to 1 by the caller
        # and is cleared here — SMEM scratch is NOT zero-initialized)
        @pl.when(init_ref[0] == 1)
        def _():
            init_ref[0] = 0
            buf_idx[0] = 0
            for c in chunk_copies(0, bi, hi, ci):
                c.start()

        cur = buf_idx[0]
        b3, h3, c3 = next_step(bi, hi, ci)

        @pl.when(b3 < batch)
        def _():
            for c in chunk_copies(1 - cur, b3, h3, c3):
                c.start()
        for c in chunk_copies(cur, bi, hi, ci):
            c.wait()
        buf_idx[0] = 1 - cur

        @pl.when(ci == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)

        d = q_ref.shape[-1]
        q = q_ref[0, 0].astype(jnp.float32) * scale        # [group, d]
        kb = k_buf[cur].reshape(chunk_tokens, d).astype(jnp.float32)
        vb = v_buf[cur].reshape(chunk_tokens, d).astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [group, CT]
        pos = ci * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (group, chunk_tokens), 1)
        s = jnp.where(pos < ctx, s, NEG_INF)

        m_prev = m_ref[:, :1]                               # [group, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

        @pl.when(ci == n_chunks_b - 1)
        def _():
            l_fin = l_ref[:, :1]
            l_safe = jnp.where(l_fin > 0, l_fin, 1.0)
            out = acc_ref[...] / l_safe
            # zero-length rows (freed/parked slots) emit zeros, not garbage —
            # callers may rely on inactive rows being inert
            o_ref[0, 0] = jnp.where(ctx > 0, out, 0.0).astype(o_ref.dtype)


def paged_decode_attention(q, k_cache, v_cache, block_tables, context_lens,
                           scale=None, pages_per_chunk: int = 4,
                           interpret: bool = False):
    """One-token-per-sequence paged decode.

    q: [batch, q_heads, head_dim]; caches [num_pages, kv_heads, page, d];
    block_tables [batch, max_pages_per_seq] int32; context_lens [batch] int32
    (number of valid cache tokens INCLUDING the current position's k/v, which
    must already be appended via append_paged_kv; rows with length 0 return
    ZEROS — freed/parked serving slots are guaranteed inert). Returns
    [batch, hq, d].
    """
    b, hq, d = q.shape
    n_pages, hkv, page, _ = k_cache.shape
    group = hq // hkv
    if scale is None:
        scale = d ** -0.5
    # Mosaic page-DMA slicing needs a 128-aligned trailing dim and a
    # sublane-aligned page dim — 8 sublanes at 4-byte, 16 at 2-byte, 32 at
    # 1-byte (int8 KV cache); other shapes take the dense-gather fallback
    sublane = {4: 8, 2: 16, 1: 32}.get(jnp.dtype(k_cache.dtype).itemsize, 8)
    shapes_ok = d % 128 == 0 and page % sublane == 0
    if not interpret and (jax.default_backend() != "tpu" or not shapes_ok):
        return paged_decode_reference(q, k_cache, v_cache, block_tables,
                                      context_lens, scale)
    max_pages = block_tables.shape[1]
    G = pages_per_chunk
    while max_pages % G:
        G -= 1
    n_chunks = max_pages // G
    # single-chunk rows have nothing to stream: the kernel's serial per-(b,h)
    # DMA chain is pure latency (~measured 3 ms in-situ at b8·h16·2 pages vs
    # ~µs for the XLA gather+einsum), so short-context serving routes to the
    # dense-gather path; the kernel wins once chunks per row >= 2
    if n_chunks < 2 and not interpret:
        return paged_decode_reference(q, k_cache, v_cache, block_tables,
                                      context_lens, scale)
    qr = q.reshape(b, hkv, group, d)

    kernel = functools.partial(
        _paged_decode_kernel, page=page, G=G, max_pages=max_pages,
        scale=float(scale), group=group, hkv=hkv, batch=b)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, hkv, n_chunks),
        in_specs=[
            pl.BlockSpec((1, 1, group, d), lambda bi, hi, ci, *_: (bi, hi, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda bi, hi, ci, *_: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, G, page, d), k_cache.dtype),
            pltpu.VMEM((2, G, page, d), v_cache.dtype),
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        # all three dims "arbitrary": the double-buffer prefetch chain carries
        # SMEM/semaphore state ACROSS batch boundaries, so no grid dim may be
        # split across megacores
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(context_lens, block_tables.reshape(-1),
      jnp.zeros((1,), jnp.int32),   # buffer index
      jnp.ones((1,), jnp.int32),    # init flag
      qr, k_cache, v_cache)
    return out.reshape(b, hq, d)


# ---------------------------------------------------------------------------
# cache maintenance (XLA scatters — bandwidth-bound, no kernel needed)
# ---------------------------------------------------------------------------

def append_paged_kv(k_cache, v_cache, k_new, v_new, block_tables, positions,
                    seq_ids=None):
    """Scatter new tokens into the page pool.

    k_new/v_new: [n_tokens, kv_heads, d]; positions [n_tokens] absolute
    position of each token within its sequence; seq_ids [n_tokens] row of
    block_tables per token (defaults to arange — one token per sequence,
    the decode step). Returns updated (k_cache, v_cache)."""
    n_tokens = k_new.shape[0]
    page = k_cache.shape[2]
    if seq_ids is None:
        seq_ids = jnp.arange(n_tokens, dtype=jnp.int32)
    page_idx = block_tables[seq_ids, positions // page]      # [n]
    offs = positions % page                                   # [n]
    k_cache = k_cache.at[page_idx, :, offs, :].set(k_new)
    v_cache = v_cache.at[page_idx, :, offs, :].set(v_new)
    return k_cache, v_cache


def gather_paged_kv(k_cache, v_cache, block_tables, max_len):
    """Dense [b, max_len, hkv, d] views of the paged cache (prefill path /
    debugging). max_len must be a multiple of page size."""
    b = block_tables.shape[0]
    page = k_cache.shape[2]
    hkv, d = k_cache.shape[1], k_cache.shape[3]
    n = max_len // page
    tables = jnp.maximum(block_tables[:, :n], 0)
    kg = jnp.swapaxes(k_cache[tables], 2, 3).reshape(b, max_len, hkv, d)
    vg = jnp.swapaxes(v_cache[tables], 2, 3).reshape(b, max_len, hkv, d)
    return kg, vg
