"""Paged (block) KV-cache attention — Pallas TPU kernels for batched serving.

TPU-native replacement for the reference's paged serving kernels
(/root/reference/paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
python surface python/paddle/incubate/nn/functional/block_multihead_attention.py):
KV lives in a pool of fixed-size pages; each sequence owns a list of pages via a
block table, so cache memory is bounded by total tokens, not batch × max_len.

Layouts (reference block_multihead_attention):
  k_cache/v_cache: [num_pages, kv_heads, page_size, head_dim]
  block_tables:    [batch, pages_per_seq] int32 (-1 = unassigned)
  context_lens:    [batch] int32 — tokens already in cache (incl. current step)

Decode kernel design (measured 435 GB/s-class architecture, v5e):
  - grid (batch, kv_heads, seq_chunks); each chunk DMAs G pages of ONE kv head
    HBM→VMEM. The chunk loop is a *grid* dimension, so double buffering runs
    across grid steps: an SMEM buffer index persists, and each step prefetches
    the NEXT VALID (b, h, chunk) step's pages while computing its own.
  - context lengths arrive via scalar prefetch; chunks past a sequence's
    length are skipped entirely (no DMA, no compute).
  - online softmax in fp32 with VMEM carry across chunks; GQA computes all
    `group` q-heads of the kv head in one [group, G*page] block.
"""

from __future__ import annotations

import collections
import functools
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

#: int8 KV block format: symmetric absmax quantization, q = round(x / step)
#: with step = scale / KV_QMAX — the same scale convention as
#: quantization.PerChannelAbsmaxObserver / ConvertedLinear (scale == absmax,
#: qmax = 2^(bits-1) - 1), applied per (page, kv_head) block.
KV_QMAX = 127


# ---------------------------------------------------------------------------
# int8 paged-KV block format (opt-in — serving.KVCacheConfig(dtype="int8"))
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class QuantizedKVPool:
    """One side (k or v) of a paged-KV pool in the int8 block format.

    ``data`` [num_pages, kv_heads, page, head_dim] int8 and ``scale``
    [num_pages, kv_heads] float32 — one absmax scale per (page, kv_head)
    block, living beside the pool (reusing the
    ``quantization.PerChannelAbsmaxObserver`` convention: scale == absmax,
    stored value = round(x / (scale / KV_QMAX))). Registered as a jax
    pytree, so it flows through jit/scan carries and ``donate_argnums``
    exactly like the plain array it replaces; ``.shape``/``.dtype``
    delegate to ``data`` so pool-geometry probes (page size, head counts,
    codec compatibility checks) keep working unchanged.

    Write paths quantize on append (:func:`append_paged_kv`): the block
    scale is grown by scatter-max with the incoming tokens' absmax and
    already-stored values are REquantized under the grown scale (one
    elementwise pass over the pool — ratio is 1.0 for untouched blocks, so
    their stored bytes are bit-stable through ``round``). Read paths
    dequantize in the gather (:func:`paged_decode_attention` /
    :func:`paged_prefill_attention` / :func:`paged_verify_attention`), so
    attention math stays fp32. Pool bytes drop ~itemsize-fold (bf16 -> int8
    halves them), doubling effective slots and radix prefix-cache reach at
    equal memory. The Pallas decode kernel does not yet carry the dequant
    (int8 routes to the XLA reference path — open TPU-kernel work)."""

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data = data
        self.scale = scale

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def __repr__(self):
        return (f"QuantizedKVPool(shape={tuple(self.data.shape)}, "
                f"dtype={self.data.dtype})")


def kv_absmax(x):
    """Per-(token, kv_head) absmax of new k/v rows ``x`` [n, kv_heads, d] —
    the head_dim reduction of ``PerChannelAbsmaxObserver`` math, feeding
    the per-block scatter-max on append."""
    return jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)


def quantize_kv(x, scale):
    """Symmetric int8 quantization of ``x`` with per-channel ``scale``
    (broadcast against ``x``): round(x / (scale / KV_QMAX)) clipped to
    +-KV_QMAX. ``scale == 0`` blocks hold only zeros by construction (a
    scale is the absmax of everything ever written)."""
    step = scale.astype(jnp.float32) / KV_QMAX
    safe = jnp.where(step > 0, step, 1.0)
    q = jnp.round(x.astype(jnp.float32) / safe)
    return jnp.clip(q, -KV_QMAX, KV_QMAX).astype(jnp.int8)


def dequantize_kv(q, scale):
    """Inverse of :func:`quantize_kv` (fp32): q * (scale / KV_QMAX).
    Per-block dequant error is bounded by step/2 = scale / (2 * KV_QMAX)
    per quantization event; requant-on-grow events compound boundedly
    (tests pin the end-to-end bound)."""
    return q.astype(jnp.float32) * (scale.astype(jnp.float32) / KV_QMAX)


def _gather_pages(cache, tables):
    """Dense page gather with dequantize-on-gather for int8 pools:
    returns [*tables.shape, kv_heads, page, d] — fp32 when quantized,
    the pool dtype otherwise."""
    if isinstance(cache, QuantizedKVPool):
        pages = cache.data[tables].astype(jnp.float32)
        s = cache.scale[tables]                       # [..., kv_heads]
        return pages * (s[..., None, None] / KV_QMAX)
    return cache[tables]


# ---------------------------------------------------------------------------
# XLA reference (tests + CPU fallback)
# ---------------------------------------------------------------------------

def paged_decode_reference(q, k_cache, v_cache, block_tables, context_lens,
                           scale=None):
    """Dense-gather paged decode: q [b, hq, d] -> out [b, hq, d]."""
    b, hq, d = q.shape
    n_pages, hkv, page, _ = k_cache.shape
    group = hq // hkv
    if scale is None:
        scale = d ** -0.5
    max_pages = block_tables.shape[1]
    safe_tables = jnp.maximum(block_tables, 0)
    # [b, max_pages, hkv, page, d] -> [b, hkv, L, d]
    kg = jnp.swapaxes(_gather_pages(k_cache, safe_tables),
                      2, 3).reshape(b, max_pages * page, hkv, d)
    vg = jnp.swapaxes(_gather_pages(v_cache, safe_tables),
                      2, 3).reshape(b, max_pages * page, hkv, d)
    kg = jnp.swapaxes(kg, 1, 2)
    vg = jnp.swapaxes(vg, 1, 2)
    qf = q.reshape(b, hkv, group, d).astype(jnp.float32)
    s = jnp.einsum("bhgd,bhld->bhgl", qf, kg.astype(jnp.float32)) * scale
    pos = jnp.arange(max_pages * page)[None, None, None, :]
    s = jnp.where(pos < context_lens[:, None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgl,bhld->bhgd", p, vg.astype(jnp.float32))
    # zero-length rows (freed/parked slots) return zeros, not garbage
    out = jnp.where(context_lens[:, None, None, None] > 0, out, 0.0)
    return out.reshape(b, hq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas decode kernel
# ---------------------------------------------------------------------------

def _paged_decode_kernel(lens_ref, tables_ref, buf_idx, init_ref,
                         q_ref, k_hbm, v_hbm, o_ref,
                         k_buf, v_buf, acc_ref, m_ref, l_ref,
                         sem, *, page, G, max_pages, scale, group, hkv, batch):
    bi, hi, ci = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    chunk_tokens = page * G
    ctx = lens_ref[bi]
    # every (b, h) processes AT LEAST one chunk even at length 0 — otherwise a
    # zero-length row would break the prefetch chain and the next valid row
    # would wait on semaphores armed with the wrong pages (its own output is
    # forced to zeros at the final-store below; neighbors must stay correct)
    n_chunks_b = jnp.maximum((ctx + chunk_tokens - 1) // chunk_tokens, 1)

    def chunk_copies(slot, b2, h2, c2):
        out = []
        for g in range(G):
            pidx = jnp.maximum(tables_ref[b2 * max_pages + c2 * G + g], 0)
            out.append(pltpu.make_async_copy(
                k_hbm.at[pidx, h2], k_buf.at[slot, g], sem.at[slot, 0]))
            out.append(pltpu.make_async_copy(
                v_hbm.at[pidx, h2], v_buf.at[slot, g], sem.at[slot, 1]))
        return out

    def next_step(b2, h2, c2):
        # lexicographic next VALID step in (b, h, chunk) grid order —
        # chunks beyond a sequence's length are skipped by everyone
        # (min 1 chunk per (b, h): matches n_chunks_b above)
        nb = jnp.maximum((lens_ref[b2] + chunk_tokens - 1) // chunk_tokens, 1)
        c3 = c2 + 1
        roll_h = c3 >= nb
        h3 = jnp.where(roll_h, h2 + 1, h2)
        c3 = jnp.where(roll_h, 0, c3)
        roll_b = h3 >= hkv
        b3 = jnp.where(roll_b, b2 + 1, b2)
        h3 = jnp.where(roll_b, 0, h3)
        return b3, h3, c3

    @pl.when(ci < n_chunks_b)
    def _():
        # very first valid step of the whole grid: no one prefetched for us
        # (init flag arrives as a scalar-prefetch input set to 1 by the caller
        # and is cleared here — SMEM scratch is NOT zero-initialized)
        @pl.when(init_ref[0] == 1)
        def _():
            init_ref[0] = 0
            buf_idx[0] = 0
            for c in chunk_copies(0, bi, hi, ci):
                c.start()

        cur = buf_idx[0]
        b3, h3, c3 = next_step(bi, hi, ci)

        @pl.when(b3 < batch)
        def _():
            for c in chunk_copies(1 - cur, b3, h3, c3):
                c.start()
        for c in chunk_copies(cur, bi, hi, ci):
            c.wait()
        buf_idx[0] = 1 - cur

        @pl.when(ci == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)

        d = q_ref.shape[-1]
        q = q_ref[0, 0].astype(jnp.float32) * scale        # [group, d]
        kb = k_buf[cur].reshape(chunk_tokens, d).astype(jnp.float32)
        vb = v_buf[cur].reshape(chunk_tokens, d).astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [group, CT]
        pos = ci * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (group, chunk_tokens), 1)
        s = jnp.where(pos < ctx, s, NEG_INF)

        m_prev = m_ref[:, :1]                               # [group, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

        @pl.when(ci == n_chunks_b - 1)
        def _():
            l_fin = l_ref[:, :1]
            l_safe = jnp.where(l_fin > 0, l_fin, 1.0)
            out = acc_ref[...] / l_safe
            # zero-length rows (freed/parked slots) emit zeros, not garbage —
            # callers may rely on inactive rows being inert
            o_ref[0, 0] = jnp.where(ctx > 0, out, 0.0).astype(o_ref.dtype)


def paged_decode_attention(q, k_cache, v_cache, block_tables, context_lens,
                           scale=None, pages_per_chunk: int = 4,
                           interpret: bool = False):
    """One-token-per-sequence paged decode.

    q: [batch, q_heads, head_dim]; caches [num_pages, kv_heads, page, d];
    block_tables [batch, max_pages_per_seq] int32; context_lens [batch] int32
    (number of valid cache tokens INCLUDING the current position's k/v, which
    must already be appended via append_paged_kv; rows with length 0 return
    ZEROS — freed/parked serving slots are guaranteed inert). Returns
    [batch, hq, d].
    """
    b, hq, d = q.shape
    n_pages, hkv, page, _ = k_cache.shape
    group = hq // hkv
    if scale is None:
        scale = d ** -0.5
    if isinstance(k_cache, QuantizedKVPool):
        # int8 block format: the Pallas kernel does not carry the
        # per-block dequant yet — route to the dense-gather reference,
        # which dequantizes in the gather (open TPU-kernel work)
        return paged_decode_reference(q, k_cache, v_cache, block_tables,
                                      context_lens, scale)
    # Mosaic page-DMA slicing needs a 128-aligned trailing dim and a
    # sublane-aligned page dim — 8 sublanes at 4-byte, 16 at 2-byte, 32 at
    # 1-byte (int8 KV cache); other shapes take the dense-gather fallback
    sublane = {4: 8, 2: 16, 1: 32}.get(jnp.dtype(k_cache.dtype).itemsize, 8)
    shapes_ok = d % 128 == 0 and page % sublane == 0
    if not interpret and (jax.default_backend() != "tpu" or not shapes_ok):
        return paged_decode_reference(q, k_cache, v_cache, block_tables,
                                      context_lens, scale)
    max_pages = block_tables.shape[1]
    G = pages_per_chunk
    while max_pages % G:
        G -= 1
    n_chunks = max_pages // G
    # single-chunk rows have nothing to stream: the kernel's serial per-(b,h)
    # DMA chain is pure latency (~measured 3 ms in-situ at b8·h16·2 pages vs
    # ~µs for the XLA gather+einsum), so short-context serving routes to the
    # dense-gather path; the kernel wins once chunks per row >= 2
    if n_chunks < 2 and not interpret:
        return paged_decode_reference(q, k_cache, v_cache, block_tables,
                                      context_lens, scale)
    qr = q.reshape(b, hkv, group, d)

    kernel = functools.partial(
        _paged_decode_kernel, page=page, G=G, max_pages=max_pages,
        scale=float(scale), group=group, hkv=hkv, batch=b)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, hkv, n_chunks),
        in_specs=[
            pl.BlockSpec((1, 1, group, d), lambda bi, hi, ci, *_: (bi, hi, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda bi, hi, ci, *_: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, G, page, d), k_cache.dtype),
            pltpu.VMEM((2, G, page, d), v_cache.dtype),
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        # all three dims "arbitrary": the double-buffer prefetch chain carries
        # SMEM/semaphore state ACROSS batch boundaries, so no grid dim may be
        # split across megacores
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(context_lens, block_tables.reshape(-1),
      jnp.zeros((1,), jnp.int32),   # buffer index
      jnp.ones((1,), jnp.int32),    # init flag
      qr, k_cache, v_cache)
    return out.reshape(b, hq, d)


# ---------------------------------------------------------------------------
# chunk prefill over cached history (prefix cache / chunked-prefill path)
# ---------------------------------------------------------------------------

def paged_prefill_attention(q, k_cache, v_cache, block_tables, chunk_starts,
                            scale=None):
    """Attention for a prefill CHUNK whose rows sit at per-row absolute
    offsets inside already-partially-filled paged caches.

    q: [b, s, hq, d] — queries for tokens at absolute positions
    ``chunk_starts[b] + i`` (i in [0, s)); the chunk's own k/v must already
    be appended into the pages (append-then-gather, so within-chunk keys and
    the cached prefix are read through ONE code path). Returns [b, s, hq, d].

    Keys are gathered densely from the block table (full ``max_pages*page``
    extent) and masked by absolute position: query at position p attends
    keys at positions <= p. The mask depends only on ABSOLUTE positions and
    the gathered extent is fixed per engine, so GIVEN the same cached k/v
    bytes a token's output is bit-identical no matter how the prompt is
    chunked or how much of it came from the prefix cache — the property the
    serving engine's warm==cold token-equality guarantee rests on (the
    engine's module docstring scopes what "same bytes" means at re-stepped
    block-final positions). Rows are independent, so several rows may SHARE
    one sequence's block table at different ``chunk_starts`` — the fused
    engine's prompt-packing prefill flattens (slot, chunk) pairs into the
    rows of one call; because every row's k/v is appended before any row's
    gather, a later chunk reads an earlier chunk's pages written in the
    same program, bit-identical to sequential chunk calls.
    Stays an XLA gather+einsum (no Pallas
    kernel): prefill is projection/MLP-bound at serving chunk sizes and this
    runs once per admitted chunk, unlike the per-token decode kernel."""
    b, s, hq, d = q.shape
    n_pages, hkv, page, _ = k_cache.shape
    group = hq // hkv
    if scale is None:
        scale = d ** -0.5
    max_pages = block_tables.shape[1]
    L = max_pages * page
    safe_tables = jnp.maximum(block_tables, 0)
    kg = jnp.swapaxes(_gather_pages(k_cache, safe_tables),
                      2, 3).reshape(b, L, hkv, d)
    vg = jnp.swapaxes(_gather_pages(v_cache, safe_tables),
                      2, 3).reshape(b, L, hkv, d)
    kg = jnp.swapaxes(kg, 1, 2).astype(jnp.float32)      # [b, hkv, L, d]
    vg = jnp.swapaxes(vg, 1, 2).astype(jnp.float32)
    qf = q.reshape(b, s, hkv, group, d).astype(jnp.float32)
    qf = jnp.transpose(qf, (0, 2, 3, 1, 4))              # [b, hkv, g, s, d]
    sc = jnp.einsum("bhgsd,bhld->bhgsl", qf, kg) * scale
    q_pos = chunk_starts[:, None] + jnp.arange(s)        # [b, s] absolute
    keep = (jnp.arange(L)[None, None, :]
            <= q_pos[:, :, None])                        # [b, s, L]
    sc = jnp.where(keep[:, None, None, :, :], sc, NEG_INF)
    p = jax.nn.softmax(sc, axis=-1)
    out = jnp.einsum("bhgsl,bhld->bhgsd", p, vg)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s, hq, d)
    return out.astype(q.dtype)


def paged_verify_attention(q, k_cache, v_cache, block_tables, row_starts,
                           scale=None):
    """Speculative-decode VERIFY attention: score a K+1-token draft window
    per row in ONE pass (inference/serving.py speculative mega-step).

    q: [b, s, hq, d] — queries for the window [last_token, draft_1..draft_K]
    whose rows sit at per-row absolute offsets ``row_starts[b] + i`` inside
    already-partially-filled paged caches. The window's own k/v must
    already be appended (append-then-gather), exactly the
    :func:`paged_prefill_attention` machinery — which is what this
    delegates to: the absolute-position mask means window position i
    attends the cached prefix plus drafts 0..i, so the logits at position
    i are IDENTICAL (same gather extent, same masked softmax) to what a
    sequential ``paged_token_step`` at that position would compute given
    the same cache bytes — the greedy byte-identity guarantee of
    speculative decoding rests here. Rejected drafts' appended k/v needs
    no explicit rollback: positions past the accepted prefix sit beyond
    the advanced context length, are never attended, and are overwritten
    as decode proceeds (the engine's standard pad-append invariant).
    int8 pools dequantize in the gather like every other read path.

    NOTE this is a NAMED THIN DELEGATION: the production verify program
    (``paged_verify_step`` -> layer ``paged_prefill_chunk``) dispatches
    the shared :func:`paged_prefill_attention` body directly — verify and
    chunk prefill are deliberately ONE implementation, which is what the
    byte-identity argument above rests on. Behavioral changes belong in
    that shared body; changing only this wrapper changes tests, not
    serving."""
    return paged_prefill_attention(q, k_cache, v_cache, block_tables,
                                   row_starts, scale)


def copy_pages(k_cache, v_cache, src, dst):
    """Copy page(s) ``src`` -> ``dst`` across a (k, v) pool pair — the
    copy-on-write primitive for shared prefix blocks. Traced-index
    friendly: one compiled program serves every (src, dst). Accepts a
    scalar pair (the legacy per-admission COW) or equal-length index
    vectors (the fused engine batches a whole admission wave's COW copies
    into one dispatch, padding with park->park self-copies — duplicate
    destinations among the pads write identical bytes, so the scatter
    stays deterministic). int8 pools copy the per-block scales alongside
    the page bytes — a COW copy must carry the whole block format, or the
    private copy would dequantize under the wrong scale."""
    src = jnp.atleast_1d(jnp.asarray(src, jnp.int32))
    dst = jnp.atleast_1d(jnp.asarray(dst, jnp.int32))
    if isinstance(k_cache, QuantizedKVPool):
        return (QuantizedKVPool(k_cache.data.at[dst].set(k_cache.data[src]),
                                k_cache.scale.at[dst].set(k_cache.scale[src])),
                QuantizedKVPool(v_cache.data.at[dst].set(v_cache.data[src]),
                                v_cache.scale.at[dst].set(v_cache.scale[src])))
    k_cache = k_cache.at[dst].set(k_cache[src])
    v_cache = v_cache.at[dst].set(v_cache[src])
    return k_cache, v_cache


# ---------------------------------------------------------------------------
# refcounted block allocator + radix prefix cache (host-side bookkeeping)
# ---------------------------------------------------------------------------

class BlockAllocator:
    """Refcounted allocator over the paged-KV pool's page ids.

    Page states: FREE (in the free list), ACTIVE (refcount >= 1 — mapped
    into at least one request's block table), CACHED-IDLE (refcount == 0 but
    still registered in a :class:`RadixPrefixCache` — its KV content is
    retained for future prefix hits and reclaimed lazily via LRU eviction),
    or HELD (fault-drill resource exhaustion, ``hold()``).

    Refcounts count REQUEST references only: ``alloc`` hands out fresh
    blocks at refcount 1, every additional request sharing a block calls
    ``incref``, and ``decref`` at request completion/eviction returns the
    block to the free list ONLY when nothing else references it and no
    prefix cache retains it — freeing a block another request still reads
    is the corruption class the serving fault drill exercises."""

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        self._free = collections.deque(range(self.num_blocks))
        self._ref: Dict[int, int] = {}
        self._held: List[int] = []
        # wired by the owner after constructing the radix cache:
        # is_cached(block) -> bool keeps refcount-0 blocks out of the free
        # list while a prefix cache still maps them
        self.is_cached: Callable[[int], bool] = lambda b: False

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def hold(self, n: int) -> int:
        """Remove up to ``n`` free blocks from circulation (fault injection:
        seeded pool exhaustion). Returns how many were actually held."""
        took = 0
        while took < n and self._free:
            self._held.append(self._free.popleft())
            took += 1
        return took

    def release_held(self) -> int:
        n = len(self._held)
        self._free.extend(self._held)
        self._held.clear()
        return n

    def alloc(self, n: int,
              evict: Optional[Callable[[int], int]] = None,
              ) -> Optional[List[int]]:
        """Allocate ``n`` blocks at refcount 1. When the free list is short,
        ``evict(shortfall)`` (the radix cache's LRU reclaimer) may free
        cached-idle blocks first. Returns None when the pool genuinely
        cannot satisfy the request — callers defer/backpressure, they never
        overcommit."""
        if n <= 0:
            return []
        if len(self._free) < n and evict is not None:
            evict(n - len(self._free))
        if len(self._free) < n:
            return None
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            rc = self._ref.get(b, 0)
            if rc == 0 and not self.is_cached(b):
                raise RuntimeError(
                    f"incref of free block {b} — a prefix-cache hit mapped "
                    "a block the allocator does not consider live")
            # rc == 0 with is_cached: a CACHED-IDLE block coming back into
            # active service on a prefix hit
            self._ref[b] = rc + 1

    def decref(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            rc = self._ref.get(b, 0)
            if rc <= 0:
                raise RuntimeError(f"decref of free block {b} (double free)")
            if rc == 1:
                del self._ref[b]
                if not self.is_cached(b):
                    self._free.append(b)
            else:
                self._ref[b] = rc - 1

    def free_cached(self, block: int) -> None:
        """Return a CACHED-IDLE block to the free list — only the radix
        cache's eviction path may call this, after unregistering it."""
        if self._ref.get(block, 0):
            raise RuntimeError(
                f"evicting block {block} with refcount "
                f"{self._ref[block]} — still mapped by a live request")
        self._free.append(block)


class _RadixNode:
    __slots__ = ("children", "block", "parent", "key", "last_used")

    def __init__(self, parent=None, key=None, block=None):
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.parent = parent
        self.key = key
        self.block = block
        self.last_used = 0


class RadixPrefixCache:
    """Radix/trie over page-sized prompt-token chunks -> filled KV blocks.

    Each node maps ONE full block of ``page_size`` prompt tokens to the page
    holding that block's k/v (page ids are shared by every layer's pool, so
    one id is the whole transformer's prefix block). ``match`` walks the
    longest fully-cached prefix; ``insert`` registers a request's freshly
    prefilled full prompt blocks (first writer wins — a duplicate chain from
    a same-wave miss simply stays private to its request). Eviction is LRU
    over leaf nodes whose blocks have refcount 0, cascading upward, so a
    cached chain is never broken in the middle."""

    def __init__(self, page_size: int, allocator: BlockAllocator):
        self.page_size = int(page_size)
        self.allocator = allocator
        self.root = _RadixNode()
        self._by_block: Dict[int, _RadixNode] = {}
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        allocator.is_cached = self.has_block

    def __len__(self) -> int:
        return len(self._by_block)

    def has_block(self, block: int) -> bool:
        return block in self._by_block

    def _chunks(self, tokens) -> List[tuple]:
        p = self.page_size
        n = len(tokens) // p
        return [tuple(int(t) for t in tokens[i * p:(i + 1) * p])
                for i in range(n)]

    def match(self, tokens) -> List[int]:
        """Longest-prefix match over FULL blocks; returns the cached block
        ids in order (possibly empty). Bumps LRU recency along the path;
        the caller increfs before mapping them into a table."""
        self._tick += 1
        node = self.root
        out: List[int] = []
        for key in self._chunks(tokens):
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = self._tick
            out.append(child.block)
            node = child
        if out:
            self.hits += 1
        else:
            self.misses += 1
        return out

    def insert(self, tokens, blocks: Sequence[int]) -> List[int]:
        """Register ``blocks[i]`` as the cache entry for the i-th full block
        of ``tokens``. Existing nodes keep their block (the duplicate stays
        private to the inserting request). Returns the block ids newly
        registered."""
        self._tick += 1
        node = self.root
        registered: List[int] = []
        for key, block in zip(self._chunks(tokens), blocks):
            child = node.children.get(key)
            if child is None:
                child = _RadixNode(parent=node, key=key, block=int(block))
                node.children[key] = child
                self._by_block[child.block] = child
                registered.append(child.block)
            child.last_used = self._tick
            node = child
        return registered

    def evict_lru(self, n: int) -> int:
        """Evict up to ``n`` blocks — LRU over refcount-0 LEAVES, cascading
        to parents as they become leaves. Returns how many blocks went back
        to the free list."""
        freed = 0
        while freed < n:
            victims = [nd for nd in self._by_block.values()
                       if not nd.children
                       and self.allocator.refcount(nd.block) == 0]
            if not victims:
                break
            victim = min(victims, key=lambda nd: nd.last_used)
            victim.parent.children.pop(victim.key)
            del self._by_block[victim.block]
            self.allocator.free_cached(victim.block)
            self.evictions += 1
            freed += 1
        return freed


# ---------------------------------------------------------------------------
# cache maintenance (XLA scatters — bandwidth-bound, no kernel needed)
# ---------------------------------------------------------------------------

def append_paged_kv(k_cache, v_cache, k_new, v_new, block_tables, positions,
                    seq_ids=None):
    """Scatter new tokens into the page pool.

    k_new/v_new: [n_tokens, kv_heads, d]; positions [n_tokens] absolute
    position of each token within its sequence; seq_ids [n_tokens] row of
    block_tables per token (defaults to arange — one token per sequence,
    the decode step). Returns updated (k_cache, v_cache)."""
    n_tokens = k_new.shape[0]
    page = k_cache.shape[2]
    if seq_ids is None:
        seq_ids = jnp.arange(n_tokens, dtype=jnp.int32)
    page_idx = block_tables[seq_ids, positions // page]      # [n]
    offs = positions % page                                   # [n]
    if isinstance(k_cache, QuantizedKVPool):
        return (_append_quantized(k_cache, k_new, page_idx, offs),
                _append_quantized(v_cache, v_new, page_idx, offs))
    k_cache = k_cache.at[page_idx, :, offs, :].set(k_new)
    v_cache = v_cache.at[page_idx, :, offs, :].set(v_new)
    return k_cache, v_cache


def _append_quantized(pool: QuantizedKVPool, x_new, page_idx, offs):
    """Quantize-on-append into the int8 block format (one pool side).

    1. Scatter-MAX the per-(page, head) scales with the incoming tokens'
       absmax — correct under duplicate page indices (several tokens of a
       prefill chunk landing in one page), unlike a gather/rewrite.
    2. REquantize already-stored values of grown blocks: one elementwise
       pass over the pool at ratio old_scale/new_scale — the ratio is
       exactly 1.0 everywhere a block did not grow, and round(q * 1.0)
       reproduces q bit-for-bit for every int8 value, so untouched blocks
       are byte-stable. (XLA fuses this into a single pool pass; pushing
       the rescale into a page-local kernel is the open TPU-side work.)
    3. Write the new tokens quantized under the grown scale — the same
       scatter shape as the fp path, so duplicate semantics (parking-page
       dummies) are unchanged.
    """
    s_tok = kv_absmax(x_new)                                  # [n, h]
    new_scale = pool.scale.at[page_idx].max(s_tok)            # [P, h]
    ratio = jnp.where(new_scale > 0,
                      pool.scale / jnp.where(new_scale > 0, new_scale, 1.0),
                      1.0)
    data = jnp.clip(jnp.round(pool.data.astype(jnp.float32)
                              * ratio[:, :, None, None]),
                    -KV_QMAX, KV_QMAX)
    q_new = quantize_kv(x_new, new_scale[page_idx][:, :, None])
    data = data.astype(jnp.int8).at[page_idx, :, offs, :].set(q_new)
    return QuantizedKVPool(data, new_scale)


def gather_chain_pages(kv, blocks):
    """Host-materialize a block chain's page bytes from every layer's
    (k, v) pool pair — the EXPORT half of KV-block migration
    (inference/disagg.py): ``kv`` is the engine's per-layer
    ``[(k_pages, v_pages), ...]`` list, ``blocks`` the chain's page ids in
    block-table order. Returns ``[(k_np, v_np), ...]`` with arrays of
    shape ``[len(blocks), kv_heads, page, head_dim]``. The np.asarray
    readback fences any in-flight append/decode program that wrote these
    pages, so the bytes are exactly what the next decode step would have
    attended. int8 pools export their RAW int8 page bytes (the dequant
    scales travel separately — :func:`gather_chain_scales`), so the wire
    artifact's crc covers the quantized bytes exactly as stored."""
    import numpy as np

    idx = np.asarray(blocks, np.int32)
    out = []
    for k, v in kv:
        if isinstance(k, QuantizedKVPool):
            out.append((np.asarray(k.data[idx]), np.asarray(v.data[idx])))
        else:
            out.append((np.asarray(k[idx]), np.asarray(v[idx])))
    return out


def gather_chain_scales(kv, blocks):
    """Per-layer (k_scales, v_scales) host arrays for a chain's blocks
    ([len(blocks), kv_heads] f32 each) — the scale half of an int8 chain
    export. Returns None for fp pools (no scales in the block format)."""
    import numpy as np

    if not kv or not isinstance(kv[0][0], QuantizedKVPool):
        return None
    idx = np.asarray(blocks, np.int32)
    return [(np.asarray(k.scale[idx]), np.asarray(v.scale[idx]))
            for k, v in kv]


def scatter_chain_pages(kv, blocks, pages, scales=None):
    """Write exported chain bytes into freshly-allocated pool pages — the
    IMPORT half of KV-block migration. ``pages`` is
    :func:`gather_chain_pages` output (host arrays); each layer's pool
    takes one eager scatter (control-plane dispatch — migration happens
    once per request, never on the decode hot path). int8 pools take the
    per-block ``scales`` (from :func:`gather_chain_scales` or the PTKV1
    header) alongside the raw int8 bytes. Returns the updated per-layer
    ``[(k_pages, v_pages), ...]`` list."""
    idx = jnp.asarray(blocks, jnp.int32)
    out = []
    for li, ((k, v), (pk, pv)) in enumerate(zip(kv, pages)):
        if isinstance(k, QuantizedKVPool):
            if scales is None:
                raise ValueError("int8 pool import needs per-block scales")
            ks, vs = scales[li]
            out.append((
                QuantizedKVPool(
                    k.data.at[idx].set(jnp.asarray(pk, jnp.int8)),
                    k.scale.at[idx].set(jnp.asarray(ks, jnp.float32))),
                QuantizedKVPool(
                    v.data.at[idx].set(jnp.asarray(pv, jnp.int8)),
                    v.scale.at[idx].set(jnp.asarray(vs, jnp.float32)))))
        else:
            out.append((k.at[idx].set(jnp.asarray(pk, k.dtype)),
                        v.at[idx].set(jnp.asarray(pv, v.dtype))))
    return out


def gather_paged_kv(k_cache, v_cache, block_tables, max_len):
    """Dense [b, max_len, hkv, d] views of the paged cache (prefill path /
    debugging; int8 pools come back dequantized fp32). max_len must be a
    multiple of page size."""
    b = block_tables.shape[0]
    page = k_cache.shape[2]
    hkv, d = k_cache.shape[1], k_cache.shape[3]
    n = max_len // page
    tables = jnp.maximum(block_tables[:, :n], 0)
    kg = jnp.swapaxes(_gather_pages(k_cache, tables),
                      2, 3).reshape(b, max_len, hkv, d)
    vg = jnp.swapaxes(_gather_pages(v_cache, tables),
                      2, 3).reshape(b, max_len, hkv, d)
    return kg, vg
