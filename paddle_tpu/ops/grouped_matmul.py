"""Grouped (per-expert) matmul — Pallas TPU kernels for MoE expert FFNs.

The megablocks-class dropless regime (reference MoE dispatches with NCCL
alltoall + per-expert GEMMs, incubate/distributed/models/moe/moe_layer.py:263;
``jax.lax.ragged_dot`` measured SLOWER than the capacity-scatter dispatch on
v5e — benchmarks/moe_ab.py): tokens are sorted by expert and PADDED so each
expert's rows start at a tile boundary, then

  - ``pgmm(x, w, tile_gids)``: out[r] = x[r] @ w[g(r)] as one Pallas kernel —
    grid (m_tiles, n_tiles, k_tiles), each m-tile belongs to exactly ONE
    expert (the padding guarantee), whose weight block the index_map selects
    via the scalar-prefetched per-tile group id. fp32 VMEM accumulator across
    the k steps.
  - ``pgmm_dw(x, dout, tile_gids)``: dw[e] = x_e^T @ dout_e — grid
    (k_tiles, n_tiles, m_tiles) with m innermost; tiles of one expert are
    CONTIGUOUS (sorted rows), so the output block for expert e stays resident
    while its m-tiles accumulate and flushes exactly once.

Both are wired into a custom_vjp (``pgmm`` differentiates w.r.t. x and w), so
``routed_ffn(dispatch_mode="pgmm")`` trains. Padding cost is bounded by
E * (tile_m - 1) rows — static shapes throughout (XLA requirement), vs the
capacity formulation's multiplicative 1.25x on EVERY row.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_M = 512
TILE_N = 512
TILE_K = 512


def _pgmm_kernel(gids_ref, x_ref, w_ref, o_ref, acc_ref, *, nk):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], w_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _fit_tile(pref, dim):
    """Largest of pref/512/256/128 dividing dim, else the whole dim."""
    for c in (pref, 512, 256, 128):
        if c <= dim and dim % c == 0:
            return c
    return dim


def _pgmm_raw(x, w, tile_gids, tile_m, interpret=False):
    """x [P, k] (P % tile_m == 0), w [E, k, n], tile_gids [P // tile_m] int32
    -> [P, n] with out rows of tile t multiplied by w[tile_gids[t]]."""
    from jax.experimental.pallas import tpu as pltpu

    p, kdim = x.shape
    e, _, n = w.shape
    tm = tile_m
    tn = _fit_tile(TILE_N, n)
    tk = _fit_tile(TILE_K, kdim)
    assert p % tm == 0 and n % tn == 0 and kdim % tk == 0
    nk = kdim // tk
    grid = (p // tm, n // tn, nk)
    kernel = functools.partial(_pgmm_kernel, nk=nk)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, tk), lambda i, j, kk, g: (i, kk)),
                pl.BlockSpec((1, tk, tn), lambda i, j, kk, g: (g[i], kk, j)),
            ],
            out_specs=pl.BlockSpec((tm, tn), lambda i, j, kk, g: (i, j)),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((p, n), x.dtype),
        interpret=interpret,
    )(tile_gids, x, w)


def _pgmm_dw_kernel(gids_ref, x_ref, g_ref, dw_ref, *, nm):
    mi = pl.program_id(2)
    contrib = jax.lax.dot_general(
        x_ref[...], g_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # first m-tile of this expert initializes its (resident) output block;
    # subsequent contiguous tiles accumulate in place
    prev = gids_ref[jnp.maximum(mi - 1, 0)]
    first = (mi == 0) | (gids_ref[mi] != prev)

    @pl.when(first)
    def _():
        dw_ref[0] = contrib.astype(dw_ref.dtype)

    @pl.when(~first)
    def _():
        dw_ref[0] = (dw_ref[0].astype(jnp.float32) + contrib).astype(
            dw_ref.dtype)


def _pgmm_dw_raw(x, dout, tile_gids, e, tile_m, interpret=False):
    """dw[e] = sum over rows r with g(r)==e of x[r]^T dout[r].
    x [P, k], dout [P, n] -> [E, k, n] fp32.

    Experts owning NO m-tile (zero tokens this step under
    ``padded_group_layout``, which gives an empty expert zero padded rows)
    never run the kernel's init branch, so on real hardware their output
    blocks would be whatever was in the buffer — uninitialized memory
    flowing into dw (ADVICE round-5 high). ``_mask_unvisited_experts``
    zeroes exactly those blocks; interpret mode happens to zero-fill
    outputs, which is why the bug only bites in non-interpret mode."""
    dw = _pgmm_dw_call(x, dout, tile_gids, e, tile_m, interpret)
    return _mask_unvisited_experts(dw, tile_gids, e)


def _mask_unvisited_experts(dw, tile_gids, e):
    """Zero dw blocks of experts that own no m-tile (their correct gradient:
    no rows routed to them contributes nothing). Tile counts come straight
    from ``tile_gids`` — an expert absent from it was never visited by the
    grid, so its block was never written."""
    counts = jnp.zeros((e,), jnp.int32).at[tile_gids].add(1)
    return jnp.where((counts > 0)[:, None, None], dw,
                     jnp.zeros((), dw.dtype))


def _pgmm_dw_call(x, dout, tile_gids, e, tile_m, interpret=False):
    from jax.experimental.pallas import tpu as pltpu

    p, kdim = x.shape
    _, n = dout.shape
    tm = tile_m
    tn = _fit_tile(TILE_N, n)
    tk = _fit_tile(TILE_K, kdim)
    assert p % tm == 0 and n % tn == 0 and kdim % tk == 0
    nm = p // tm
    grid = (kdim // tk, n // tn, nm)   # m innermost: same-expert tiles are
    kernel = functools.partial(_pgmm_dw_kernel, nm=nm)  # consecutive
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tm, tk), lambda i, j, mi, g: (mi, i)),
                pl.BlockSpec((tm, tn), lambda i, j, mi, g: (mi, j)),
            ],
            out_specs=pl.BlockSpec((1, tk, tn),
                                   lambda i, j, mi, g: (g[mi], i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((e, kdim, n), jnp.float32),
        interpret=interpret,
    )(tile_gids, x, dout)


def _gid_zero_cot(gids):
    import numpy as _np

    return _np.zeros(gids.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def pgmm(x, w, tile_gids, tile_m=TILE_M, interpret=False):
    """Padded grouped matmul: rows of m-tile t hit w[tile_gids[t]].

    x [P, k] sorted-by-group and tile-aligned (pad rows zero), w [E, k, n],
    tile_gids [P // tile_m] int32 (monotone non-decreasing). Differentiable
    w.r.t. x and w (pad rows are zero, so they contribute nothing to dw and
    receive garbage-free dx)."""
    return _pgmm_raw(x, w, tile_gids, tile_m, interpret)


def _pgmm_fwd(x, w, tile_gids, tile_m, interpret):
    return _pgmm_raw(x, w, tile_gids, tile_m, interpret), (x, w, tile_gids)


def _pgmm_bwd(tile_m, interpret, res, g):
    x, w, tile_gids = res
    g = g.astype(x.dtype)
    # dx[r] = g[r] @ w[g(r)]^T — the same pgmm over transposed weights
    dx = _pgmm_raw(g, jnp.swapaxes(w, 1, 2), tile_gids, tile_m, interpret)
    dw = _pgmm_dw_raw(x, g, tile_gids, w.shape[0], tile_m, interpret)
    return dx.astype(x.dtype), dw.astype(w.dtype), _gid_zero_cot(tile_gids)


pgmm.defvjp(_pgmm_fwd, _pgmm_bwd)


_GMM_FALLBACK_WARNED = [False]

# what a missing/unsupported megablox path legitimately raises: the import
# itself, shape/dtype validation, or an unimplemented lowering. Anything
# else (a genuine kernel bug, a TPU runtime error) must propagate — a bare
# ``except Exception`` was silently converting those into the slower
# ragged_dot path (ADVICE low).
_GMM_FALLBACK_ERRORS = (ImportError, AttributeError, NotImplementedError,
                        TypeError, ValueError)


def grouped_dot(x, w, group_sizes):
    """Grouped matmul over rows sorted by group (group_sizes [E] row
    counts): jax's megablox ``gmm`` Pallas kernel on TPU (the tuned
    megablocks-class kernel — weight-stationary tiling, no padding),
    ``lax.ragged_dot`` elsewhere. Both differentiate w.r.t. x and w."""
    if jax.default_backend() == "tpu":
        try:
            from jax.experimental.pallas.ops.tpu.megablox import gmm as _mb

            k, n = w.shape[1], w.shape[2]
            tiling = (512, _fit_tile(512, k), _fit_tile(512, n))
            return _mb.gmm(x, w, group_sizes,
                           preferred_element_type=x.dtype, tiling=tiling)
        except _GMM_FALLBACK_ERRORS as e:
            if not _GMM_FALLBACK_WARNED[0]:
                _GMM_FALLBACK_WARNED[0] = True
                import warnings

                warnings.warn(
                    f"megablox gmm unavailable, falling back to "
                    f"lax.ragged_dot: {type(e).__name__}: {e}",
                    RuntimeWarning, stacklevel=2)
    return jax.lax.ragged_dot(x, w, group_sizes)


def padded_group_layout(flat_e, e, n_rows, tile_m=None):
    """Static-shape padded layout for sorted-by-expert rows.

    flat_e [n_rows] int32 expert ids (NOT necessarily sorted). Returns
    (order, padded_pos [n_rows], tile_gids [P//tile_m], P) where P is the
    STATIC worst-case padded length n_rows_padded + e*tile_m; row
    ``order[r]`` of the original goes to padded row ``padded_pos[r]``; tiles
    are owned by exactly one expert each (pad tail tiles are assigned to the
    last expert over zero rows)."""
    tile_m = tile_m or TILE_M
    p_total = ((n_rows + tile_m - 1) // tile_m) * tile_m + e * tile_m
    order = jnp.argsort(flat_e, stable=True)                 # [n]
    se = jnp.take(flat_e, order)                             # sorted experts
    gs = jax.ops.segment_sum(jnp.ones_like(flat_e), flat_e,
                             num_segments=e)                 # [e]
    padded = ((gs + tile_m - 1) // tile_m) * tile_m
    pad_off = jnp.concatenate([jnp.zeros(1, padded.dtype),
                               jnp.cumsum(padded)[:-1]])     # [e]
    off = jnp.concatenate([jnp.zeros(1, gs.dtype),
                           jnp.cumsum(gs)[:-1]])             # [e]
    rank = jnp.arange(n_rows, dtype=jnp.int32) - jnp.take(off, se)
    pos_sorted = jnp.take(pad_off, se) + rank                # [n]
    # tile ownership: tile t belongs to expert e iff t*tile_m < pad_end[e]
    ends = jnp.cumsum(padded)                                # [e]
    tiles = jnp.arange(p_total // tile_m, dtype=jnp.int32) * tile_m
    tile_gids = jnp.searchsorted(ends, tiles, side="right").astype(jnp.int32)
    tile_gids = jnp.minimum(tile_gids, e - 1)
    return order, pos_sorted, tile_gids, p_total
