"""Fused (chunked) linear + softmax cross-entropy for causal-LM training.

Parity anchor: the reference fuses the softmax-CE pair as
``c_softmax_with_cross_entropy`` / ``ParallelCrossEntropy``
(/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_layers.py:742)
and pays the lm-head logits materialization anyway. On TPU the dominant cost
at long sequence is HBM traffic: the naive path writes [b, s, V] bf16 logits,
re-reads them as fp32 for logsumexp, and the backward re-reads them again —
at (b=4, s=4096, V=32k) that is ~1 GB bf16 + ~2 GB fp32 of pure traffic per
step.

TPU-native design: never materialize the full logits. The sequence is split
into chunks; per chunk the lm-head matmul runs on the MXU with fp32
accumulation (`preferred_element_type`), the fp32 log-sum-exp reduces it
immediately, and only the scalar partial sums leave the chunk. Backward is a
``custom_vjp`` that RECOMPUTES the chunk logits (a matmul is cheaper than the
HBM round-trip) and forms

    d_logits = (softmax(logits) - onehot(labels)) * g

in fp32, then downcasts to bf16 before the two grad matmuls so they stay on
the MXU bf16 fast path (an autodiff transpose would run them in fp32 at
~1/4 throughput). ``lax.scan`` over chunks keeps one compiled matmul body;
dW is accumulated across chunks in an fp32 scan carry (bf16 matmul inputs,
fp32 MXU accumulation) and downcast to w.dtype once at the end.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _chunk_fwd_math(hc, w, lc, valid):
    lg = jnp.matmul(hc, w, preferred_element_type=jnp.float32)  # [b, c, V] f32
    logz = jax.scipy.special.logsumexp(lg, axis=-1)             # [b, c]
    safe = jnp.where(valid > 0, lc, 0).astype(jnp.int32)
    picked = jnp.take_along_axis(lg, safe[..., None], axis=-1)[..., 0]
    nll = ((logz - picked) * valid).sum()
    return nll, logz


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _nll_sum_scan(hcs, w, lcs, vcs):
    """Masked-NLL total over all chunks (scan over the leading chunk dim).

    hcs: [n, b, c, h]; w: [h, V]; lcs/vcs: [n, b, c]. The custom_vjp spans
    the WHOLE scan so the backward owns the dW accumulator: per-chunk dW
    partials are produced by a bf16 MXU matmul with fp32 accumulation
    (`preferred_element_type`) and summed across chunks in an fp32 carry —
    downcast to w.dtype exactly once at the end. (A per-chunk custom_vjp
    would be forced to hand XLA w.dtype cotangents, i.e. bf16 accumulation
    across chunks in the default bf16 config.)
    """
    def body(tot, xs):
        hc, lc, vc = xs
        nll, _ = _chunk_fwd_math(hc, w, lc, vc)
        return tot + nll, None

    tot, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (hcs, lcs, vcs))
    return tot


def _scan_fwd(hcs, w, lcs, vcs):
    def body(tot, xs):
        hc, lc, vc = xs
        nll, logz = _chunk_fwd_math(hc, w, lc, vc)
        return tot + nll, logz

    tot, logzs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                              (hcs, lcs, vcs))
    # residuals: inputs + the tiny [n, b, c] logz — logits are recomputed
    return tot, (hcs, w, lcs, vcs, logzs)


def _scan_bwd(res, g):
    hcs, w, lcs, vcs, logzs = res
    h, V = w.shape

    def body(dw_acc, xs):
        hc, lc, vc, logz = xs
        lg = jnp.matmul(hc, w, preferred_element_type=jnp.float32)
        p = jnp.exp(lg - logz[..., None])                       # softmax, f32
        safe = jnp.where(vc > 0, lc, 0).astype(jnp.int32)
        onehot = jax.nn.one_hot(safe, V, dtype=jnp.float32)
        dlg = (p - onehot) * (vc * g)[..., None]
        dlg = dlg.astype(hc.dtype)              # bf16 grad matmuls (MXU path)
        b, c, _ = hc.shape
        dhc = jnp.matmul(dlg, w.T).astype(hc.dtype)
        dw = jnp.matmul(hc.reshape(b * c, h).T, dlg.reshape(b * c, V),
                        preferred_element_type=jnp.float32)
        return dw_acc + dw, dhc

    dw, dhcs = jax.lax.scan(body, jnp.zeros((h, V), jnp.float32),
                            (hcs, lcs, vcs, logzs))
    return dhcs, dw.astype(w.dtype), None, None


_nll_sum_scan.defvjp(_scan_fwd, _scan_bwd)


def fused_linear_cross_entropy(hidden, w, labels, ignore_index: int = -100,
                               chunk: int = 1024, shift: bool = True):
    """Causal-LM loss ``mean(CE(hidden @ w, labels))`` without materializing
    the [b, s, V] logits. ``shift=True`` applies the next-token shift
    (logits[:, :-1] vs labels[:, 1:]) like LlamaPretrainingCriterion.

    Returns the mean NLL over non-ignored positions (fp32 scalar).
    """
    if shift:
        hidden = hidden[:, :-1]
        labels = labels[:, 1:]
    b, s, h = hidden.shape
    chunk = min(chunk, s)
    pad = (-s) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)),
                         constant_values=ignore_index)
    n = (s + pad) // chunk
    valid = (labels != ignore_index).astype(jnp.float32)
    cnt = valid.sum()
    # [n, b, chunk, ...] scan layout
    hcs = hidden.reshape(b, n, chunk, h).transpose(1, 0, 2, 3)
    lcs = labels.reshape(b, n, chunk).transpose(1, 0, 2)
    vcs = valid.reshape(b, n, chunk).transpose(1, 0, 2)

    tot = _nll_sum_scan(hcs, w, lcs, vcs)
    return tot / jnp.maximum(cnt, 1.0)
