"""Ring attention — context-parallel causal attention over the ``sep`` mesh axis.

The reference's long-context story is SP/SEP activation sharding + flash-attention
kernels only — it has NO ring attention (SURVEY.md §5.7, grep-verified). This
exceeds it: Q stays local, K/V blocks rotate around the ring via
``lax.ppermute`` over ICI while each step's partial attention is merged through
logsumexp stats, so attention over sequence length n_dev × local_len never
materializes on one chip.

Two sequence layouts:
  - ``contiguous``: rank r holds global chunk r. Simple, but causal
    block-skipping makes rank i compute i+1 blocks — the ring runs at the
    speed of the LAST rank (n× the first's work).
  - ``zigzag`` (default): the sequence is cut into 2n stripes; rank r holds
    stripes (r, 2n-1-r). Every rank then computes exactly 2n+1 stripe-pairs
    of causal work — balanced. The global<->zigzag permutation is applied
    inside the global view (GSPMD lowers it to collectives).

The inner stripe-pair attention runs the in-repo Pallas flash kernel on TPU
(GQA folded into its BlockSpec index maps — K/V never repeated) and returns
logsumexp for the cross-step merge; CPU/odd shapes use an einsum fallback that
also avoids materializing repeated K/V heads.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..framework.jax_compat import shard_map

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# stripe-pair attention with lse output (merge-ready)
# ---------------------------------------------------------------------------

def _block_attn_lse(q, k, v, causal: bool, scale: float):
    """q [b,sq,h,d], k/v [b,sk,hkv,d] -> (out fp32 [b,sq,h,d], lse fp32
    [b,sq,h]). GQA is computed batched over kv-heads — no jnp.repeat."""
    if (jax.default_backend() == "tpu"
            and q.shape[1] == k.shape[1]
            and q.shape[1] % 8 == 0 and q.shape[-1] in (64, 128, 256)):
        from .flash_attention import (_tuned_block, _use_pallas,
                                      flash_attention_with_lse)

        bq = min(_tuned_block(q.shape[1]), q.shape[1])
        bk = min(_tuned_block(k.shape[1]), k.shape[1])
        if _use_pallas(q, k, bq, bk, False):
            # custom_vjp entry — differentiable through BOTH outputs (the
            # merge needs d/dlse; a bare pallas_call has no transpose rule)
            out, lse = flash_attention_with_lse(q, k, v, causal, scale,
                                                bq, bk, False)
            # lse: [b, h, sq] -> [b, sq, h]
            return out.astype(jnp.float32), jnp.swapaxes(lse, 1, 2)
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, sq, hkv, g, d).astype(jnp.float32)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k.astype(jnp.float32)) * scale
    if causal:
        tri = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(tri[None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bhgqd", p, v.astype(jnp.float32))
    l_safe = jnp.where(l > 0, l, 1.0)
    out = out / l_safe[..., None]
    lse = jnp.where(l > 0, m + jnp.log(l_safe), NEG_INF)
    # [b,hkv,g,q,d] -> [b,q,h,d]; [b,hkv,g,q] -> [b,q,h]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, sq, hq, d)
    lse = jnp.transpose(lse, (0, 3, 1, 2)).reshape(b, sq, hq)
    return out, lse


def _merge(acc, lse, out_j, lse_j):
    """Merge two normalized partial attentions via their logsumexps."""
    new = jnp.logaddexp(lse, lse_j)
    w1 = jnp.exp(lse - new)[..., None]
    w2 = jnp.exp(lse_j - new)[..., None]
    return acc * w1 + out_j * w2, new


# ---------------------------------------------------------------------------
# zigzag layout helpers
# ---------------------------------------------------------------------------

def zigzag_perm(s_global: int, n: int) -> np.ndarray:
    """Index array P with x_zigzag = x[:, P]: rank r's contiguous shard holds
    global stripes (r, 2n-1-r)."""
    c = s_global // (2 * n)
    order = []
    for r in range(n):
        order += [r, 2 * n - 1 - r]
    return np.concatenate([np.arange(ch * c, (ch + 1) * c) for ch in order])


def zigzag_inverse(s_global: int, n: int) -> np.ndarray:
    perm = zigzag_perm(s_global, n)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(s_global)
    return inv


def _zigzag_pair_counts(n: int):
    """Per-rank stripe-pair compute counts (test hook: must be all equal).

    Rank r at ring step j (kv from rank s=(r-j)%n) computes:
      qA(r)      vs kA(s):      iff r >= s
      qB(2n-1-r) vs kA(s):      always
      qB(2n-1-r) vs kB(2n-1-s): iff s >= r
    """
    counts = []
    for r in range(n):
        c = 0
        for j in range(n):
            s = (r - j) % n
            c += (r >= s) + 1 + (s >= r)
        counts.append(c)
    return counts


# ---------------------------------------------------------------------------
# ring bodies
# ---------------------------------------------------------------------------

def _ring_body_zigzag(q, k, v, axis_name: str, scale: float, n: int):
    """Causal ring over zigzag-laid-out shards. Local seq = [stripe A; stripe
    B] with A = global stripe r, B = global stripe 2n-1-r. Balanced: every
    rank computes 2n+1 stripe-pairs total."""
    r = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    c = sl // 2
    perm = [(i, (i + 1) % n) for i in range(n)]

    qA, qB = q[:, :c], q[:, c:]
    accA = jnp.zeros((b, c, h, d), jnp.float32)
    lseA = jnp.full((b, c, h), NEG_INF, jnp.float32)
    accB = jnp.zeros_like(accA)
    lseB = jnp.full_like(lseA, NEG_INF)

    kc, vc = k, v
    for j in range(n):  # n is small and static — unrolled, differentiable
        s = (r - j) % n
        kA, kB = kc[:, :c], kc[:, c:]
        vA, vB = vc[:, :c], vc[:, c:]

        # qB vs kA: B (stripe 2n-1-r) is always in kA's causal future — full
        outBA, lseBA = _block_attn_lse(qB, kA, vA, False, scale)
        accB, lseB = _merge(accB, lseB, outBA, lseBA)

        if j == 0:
            # own K/V (s == r, statically): both diagonals are triangular
            outd, lsed = _block_attn_lse(qA, kA, vA, True, scale)
            accA, lseA = _merge(accA, lseA, outd, lsed)
            outd2, lsed2 = _block_attn_lse(qB, kB, vB, True, scale)
            accB, lseB = _merge(accB, lseB, outd2, lsed2)
        else:
            # s != r here, so EXACTLY ONE of (qA vs kA | qB vs kB) is causal:
            # r > s -> qA attends kA fully; s > r -> qB attends kB fully.
            # One lax.cond computes just that block — per-step work is equal
            # on every rank (the balance claim; see _zigzag_pair_counts).
            def qa_branch(_):
                return _block_attn_lse(qA, kA, vA, False, scale)

            def qb_branch(_):
                return _block_attn_lse(qB, kB, vB, False, scale)

            out_x, lse_x = jax.lax.cond(r > s, qa_branch, qb_branch, None)
            mA = _merge(accA, lseA, out_x, lse_x)
            mB = _merge(accB, lseB, out_x, lse_x)
            pred = r > s
            accA = jnp.where(pred, mA[0], accA)
            lseA = jnp.where(pred, mA[1], lseA)
            accB = jnp.where(pred, accB, mB[0])
            lseB = jnp.where(pred, lseB, mB[1])

        if j + 1 < n:
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)

    return jnp.concatenate([accA, accB], axis=1).astype(q.dtype)


def _ring_body_contiguous(q, k, v, axis_name: str, causal: bool, scale: float,
                          n: int):
    """Plain ring: rank r holds global chunk r (r+1 causal blocks of work)."""
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    # step 0 is ALWAYS the own-block diagonal (src == idx statically):
    # peel it so the loop body computes only full (unmasked) blocks — no
    # double tri+full evaluation per step
    acc0, lse0 = _block_attn_lse(q, k, v, causal, scale)
    kc0 = jax.lax.ppermute(k, axis_name, perm)
    vc0 = jax.lax.ppermute(v, axis_name, perm)

    def body(j, carry):
        acc, lse, kc, vc = carry
        src = (idx - j) % n

        def compute(args):
            acc, lse, kc, vc = args
            out_j, lse_j = _block_attn_lse(q, kc, vc, False, scale)
            return _merge(acc, lse, out_j, lse_j)

        def skip(args):
            acc, lse, _, _ = args
            return acc, lse

        if causal:
            acc, lse = jax.lax.cond(src > idx, skip, compute, (acc, lse, kc, vc))
        else:
            acc, lse = compute((acc, lse, kc, vc))
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return acc, lse, kc, vc

    acc, lse, _, _ = jax.lax.fori_loop(1, n, body, (acc0, lse0, kc0, vc0))
    return acc.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "sep", causal: bool = True,
                   scale: Optional[float] = None, layout: str = "zigzag"):
    """Global-view entry: q,k,v [batch, seq, heads, head_dim] sharded along seq
    on ``axis_name``; batch may be sharded on dp/fsdp, heads on tp.

    ``layout='zigzag'`` (default, causal only) rebalances causal work across
    ranks by permuting the sequence into 2n stripes before the ring and back
    after — GSPMD lowers the permutation to collectives. ``'contiguous'``
    skips the permutation but the last rank does n× the first's FLOPs."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    from ..distributed.auto_parallel.logical_sharding import logical_to_spec

    qspec = logical_to_spec(("batch", "seq", "heads", None), mesh)
    kspec = logical_to_spec(("batch", "seq", "kv_heads", None), mesh)
    n = int(mesh.shape[axis_name])
    s_global = q.shape[1]

    use_zigzag = (layout == "zigzag" and causal and n > 1
                  and s_global % (2 * n) == 0)
    if use_zigzag:
        perm = jnp.asarray(zigzag_perm(s_global, n))
        inv = jnp.asarray(zigzag_inverse(s_global, n))
        q, k, v = q[:, perm], k[:, perm], v[:, perm]
        f = shard_map(
            lambda a, b, c: _ring_body_zigzag(a, b, c, axis_name,
                                              float(scale), n),
            mesh=mesh, in_specs=(qspec, kspec, kspec), out_specs=qspec,
            check_vma=False)
        return f(q, k, v)[:, inv]
    f = shard_map(
        lambda a, b, c: _ring_body_contiguous(a, b, c, axis_name, causal,
                                              float(scale), n),
        mesh=mesh, in_specs=(qspec, kspec, kspec), out_specs=qspec,
        check_vma=False)
    return f(q, k, v)
