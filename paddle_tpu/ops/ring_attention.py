"""Ring attention — context-parallel causal attention over the ``sep`` mesh axis.

The reference's long-context story is SP/SEP activation sharding + flash-attention
kernels only — it has NO ring attention (SURVEY.md §5.7, grep-verified). This
exceeds it: Q stays local, K/V blocks rotate around the ring via
``lax.ppermute`` over ICI while each step's partial attention is merged with an
online-softmax (flash-style) accumulator, so attention over sequence length
n_dev × local_len never materializes on one chip.

Causality is handled at block granularity: a K block strictly in the future is
masked entirely; the diagonal block gets the triangular mask.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, mask, scale):
    """q:[b,sq,h,d] k/v:[b,sk,hkv,d] mask:[sq,sk] bool (True=keep) or None.
    Returns (out fp32 [b,sq,h,d], m fp32 [b,sq,h], l fp32 [b,sq,h])."""
    hq, hkv = q.shape[2], k.shape[2]
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # [b,h,q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                      # [b,h,q]
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    # transpose stats to [b,q,h]
    return out, jnp.swapaxes(m, 1, 2), jnp.swapaxes(l, 1, 2)


def _ring_body(q, k, v, axis_name: str, causal: bool, scale: float, n: int):
    # n is static (mesh axis size) so the fori_loop lowers to a reverse-mode
    # differentiable scan.
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc0 = jnp.zeros((b, sq, h, d), jnp.float32)
    m0 = jnp.full((b, sq, h), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, sq, h), jnp.float32)

    tri = jnp.tril(jnp.ones((sq, k.shape[1]), bool)) if causal else None

    def body(j, carry):
        acc, m, l, kc, vc = carry
        src = (idx - j) % n                      # global block id of kc

        def compute(args):
            acc, m, l, kc, vc = args
            if causal:
                # diagonal block → triangular mask; past block → full
                mask = jnp.where(src == idx, tri, jnp.ones_like(tri))
            else:
                mask = None
            out_j, m_j, l_j = _block_attn(q, kc, vc, mask, scale)
            m_new = jnp.maximum(m, m_j)
            a1 = jnp.exp(m - m_new)
            a2 = jnp.exp(m_j - m_new)
            return (acc * a1[..., None] + out_j * a2[..., None],
                    m_new, l * a1 + l_j * a2)

        def skip(args):
            acc, m, l, _, _ = args
            return acc, m, l

        if causal:
            # a fully-future block contributes exactly nothing (its masked
            # max is NEG_INF → zero softmax weight) — skip its FLOPs entirely
            acc, m, l = jax.lax.cond(src > idx, skip, compute, (acc, m, l, kc, vc))
        else:
            acc, m, l = compute((acc, m, l, kc, vc))
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return acc, m, l, kc, vc

    acc, m, l, _, _ = jax.lax.fori_loop(0, n, body, (acc0, m0, l0, k, v))
    # fully-masked rows (can't happen with causal self-attn) guard:
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "sep", causal: bool = True,
                   scale: Optional[float] = None):
    """Global-view entry: q,k,v [batch, seq, heads, head_dim] sharded along seq
    on ``axis_name``; batch may be sharded on dp/fsdp, heads on tp."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    from ..distributed.auto_parallel.logical_sharding import logical_to_spec

    qspec = logical_to_spec(("batch", "seq", "heads", None), mesh)
    kspec = logical_to_spec(("batch", "seq", "kv_heads", None), mesh)
    n = int(mesh.shape[axis_name])
    f = shard_map(
        lambda a, b, c: _ring_body(a, b, c, axis_name, causal, float(scale), n),
        mesh=mesh,
        in_specs=(qspec, kspec, kspec),
        out_specs=qspec,
        check_vma=False,
    )
    return f(q, k, v)
