"""paddle_tpu.ops — Pallas TPU kernels for the ops XLA can't fuse optimally.

The TPU-native analogue of the reference's hand-written CUDA fusion library
(/root/reference/paddle/phi/kernels/fusion/gpu/, 75 files): most fusions
(bias+act, rmsnorm, rope, swiglu) are left to XLA; Pallas is reserved for
block-streamed attention (flash / ring / paged-KV) where XLA's fusion model
can't express the online-softmax streaming pattern.
"""

from .flash_attention import flash_attention  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
