"""DataLoader (reference: python/paddle/io/reader.py:262 + dataloader/dataloader_iter.py).

Single-process iterator collates on the host and ships batches with one device_put.
Multi-process mode mirrors the reference's worker-pool (dataloader_iter.py:370):
worker processes pull index batches from an index queue, collate numpy samples, and
push them through a result queue; ordering is preserved per batch index. A
prefetch depth (like the reference's outstanding-capacity) overlaps host IO with
device compute.
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import queue
import threading
from collections import namedtuple
from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

WorkerInfo = namedtuple("WorkerInfo", ["id", "num_workers", "dataset"])
_worker_info: Optional[WorkerInfo] = None

# sentinel batch payload: every sample in the batch was corrupt and
# skip_corrupt dropped them — the parent skips the batch index entirely
_BATCH_SKIPPED = "__PT_DATA_BATCH_SKIPPED__"


class DataLoaderWorkerError(RuntimeError):
    """PT-DATA-001: a DataLoader worker process died unexpectedly (and its
    respawn budget is exhausted). Before this error existed a dead worker
    wedged ``_MultiProcessIter._recv`` forever."""

    code = "PT-DATA-001"


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (converted to Tensor at the boundary)."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(s)) for s in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _np_collate(batch):
    """Worker-side collate producing picklable numpy (Tensors only in the parent)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return [_np_collate(list(s)) for s in zip(*batch)]
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    return batch


def _to_tensor(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, list):
        return [_to_tensor(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_tensor(v) for k, v in obj.items()}
    return obj


def _np_sample(s):
    if isinstance(s, tuple):
        return tuple(np.asarray(t._data) if isinstance(t, Tensor) else t
                     for t in s)
    return np.asarray(s._data) if isinstance(s, Tensor) else s


def _worker_loop(dataset, index_queue, result_queue, collate_fn, worker_id, num_workers,
                 init_fn, shm_name=None, skip_corrupt=False):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if init_fn is not None:
        init_fn(worker_id)
    shm = None
    if shm_name is not None:
        from .shm_channel import ShmChannel

        try:
            shm = ShmChannel(shm_name, create=False)
        except Exception:
            shm = None  # fall back to the queue transport

    def emit(batch_idx, data, err):
        if shm is not None:
            try:
                shm.put((batch_idx, data, err))
                return
            except ValueError:
                pass  # batch larger than the ring — use the pickle queue
            except (EOFError, TimeoutError):
                return  # parent closed the channel; shutting down
        result_queue.put((batch_idx, data, err))

    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_idx, indices = item
        try:
            if skip_corrupt:
                # PT-DATA-002: log-and-skip samples whose __getitem__
                # raises instead of killing the epoch
                samples = []
                for i in indices:
                    try:
                        samples.append(_np_sample(dataset[i]))
                    except Exception as e:
                        import warnings

                        warnings.warn(f"[PT-DATA-002] DataLoader worker "
                                      f"{worker_id} skipped sample {i}: {e!r}")
                if not samples:
                    emit(batch_idx, _BATCH_SKIPPED, None)
                    continue
            else:
                samples = [_np_sample(dataset[i]) for i in indices]
            data = collate_fn(samples) if collate_fn is not _np_collate else _np_collate(samples)
            emit(batch_idx, data, None)
        except Exception as e:  # surface worker errors to the parent
            if skip_corrupt:    # collate on a corrupt survivor set
                import warnings

                warnings.warn(f"[PT-DATA-002] DataLoader worker {worker_id} "
                              f"skipped batch {batch_idx} (collate): {e!r}")
                emit(batch_idx, _BATCH_SKIPPED, None)
            else:
                emit(batch_idx, None, repr(e))
    if shm is not None:
        shm.detach()


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None, persistent_workers=False,
                 skip_corrupt=False, worker_respawn_limit=1):
        self.dataset = dataset
        self.num_workers = num_workers
        self.collate_fn = collate_fn
        self.use_shared_memory = use_shared_memory
        self.prefetch_factor = max(prefetch_factor, 2)
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        # robustness policies (docs/NUMERIC_GUARD.md PT-DATA-001/002):
        # skip_corrupt logs-and-skips samples whose __getitem__/collate
        # raises; a dead worker is respawned worker_respawn_limit times
        # (its in-flight batches re-dispatched) before the typed
        # DataLoaderWorkerError surfaces.
        self.skip_corrupt = bool(skip_corrupt)
        self.worker_respawn_limit = max(0, int(worker_respawn_limit))
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.batch_sampler is None:
            return self._iter_no_batch()
        if self.num_workers == 0:
            return self._iter_single()
        return iter(_MultiProcessIter(self))

    def _iter_no_batch(self):
        cf = self.collate_fn or (lambda s: s)
        for i in range(len(self.dataset)):
            yield _to_tensor(cf(self.dataset[i]))

    def _iter_single(self):
        cf = self.collate_fn or default_collate_fn
        for indices in self.batch_sampler:
            if self.skip_corrupt:
                samples = []
                for i in indices:
                    try:
                        samples.append(self.dataset[i])
                    except Exception as e:
                        import warnings

                        warnings.warn(
                            f"[PT-DATA-002] DataLoader skipped sample {i}: {e!r}")
                if not samples:
                    continue
                try:
                    yield cf(samples)
                except Exception as e:
                    import warnings

                    warnings.warn(
                        f"[PT-DATA-002] DataLoader skipped batch (collate): {e!r}")
                continue
            samples = [self.dataset[i] for i in indices]
            yield cf(samples)

    def _iter_iterable(self):
        cf = self.collate_fn or default_collate_fn
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield cf(batch)
                batch = []
        if batch and not self.drop_last:
            yield cf(batch)


class _MultiProcessIter:
    """Ordered multi-process batch pipeline (cf. _DataLoaderIterMultiProcess)."""

    def __init__(self, loader: DataLoader):
        self.loader = loader
        self.collate = loader.collate_fn or _np_collate
        self.num_workers = loader.num_workers
        self._ctx = mp.get_context("fork")
        ctx = self._ctx
        self.index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        self.result_queue = ctx.Queue()
        # Shared-memory ring transport (native shm_ring.cc) keeps bulk array
        # bytes out of the pickle pipe — reference dataloader_iter.py:370's
        # LoDTensorBlockingQueue role.
        self.shm = None
        self._shm_name = None
        if loader.use_shared_memory:
            from .shm_channel import ShmChannel

            if ShmChannel.available():
                shm_name = f"/pt_dl_{os.getpid()}_{id(self) & 0xFFFFFF:x}"
                try:
                    self.shm = ShmChannel(shm_name, capacity=64 << 20, create=True)
                    self._shm_name = shm_name
                except RuntimeError:
                    self.shm = None
        self.workers = []
        for wid in range(self.num_workers):
            self.workers.append(self._spawn_worker(wid))
        self.batches = list(loader.batch_sampler)
        self.send_idx = 0
        self.rcv_idx = 0
        self.cache = {}
        self._owner = {}                    # batch idx -> worker id
        self.respawns = [0] * self.num_workers
        # prime the pipeline
        for _ in range(self.num_workers * loader.prefetch_factor):
            self._dispatch()

    def _spawn_worker(self, wid):
        w = self._ctx.Process(
            target=_worker_loop,
            args=(self.loader.dataset, self.index_queues[wid],
                  self.result_queue, self.collate, wid, self.num_workers,
                  self.loader.worker_init_fn, self._shm_name,
                  self.loader.skip_corrupt),
            daemon=True,
        )
        w.start()
        return w

    def _dispatch(self):
        if self.send_idx >= len(self.batches):
            return
        # round-robin over LIVE workers: a reaped-without-respawn slot
        # (workers[wid] is None) must not swallow batches
        start = self.send_idx % self.num_workers
        for off in range(self.num_workers):
            wid = (start + off) % self.num_workers
            if self.workers[wid] is not None:
                break
        else:
            raise DataLoaderWorkerError(
                "[PT-DATA-001] no live DataLoader workers left to dispatch to")
        self.index_queues[wid].put((self.send_idx, self.batches[self.send_idx]))
        self._owner[self.send_idx] = wid
        self.send_idx += 1

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self.rcv_idx >= len(self.batches):
                self._shutdown()
                raise StopIteration
            while self.rcv_idx not in self.cache:
                idx, data, err = self._recv()
                self._owner.pop(idx, None)
                if err is not None:
                    self._shutdown()
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                self.cache[idx] = data
            data = self.cache.pop(self.rcv_idx)
            self.rcv_idx += 1
            self._dispatch()
            if isinstance(data, str) and data == _BATCH_SKIPPED:
                continue        # every sample was corrupt (PT-DATA-002)
            return _to_tensor(data)

    def _recv(self):
        """Next (idx, data, err) from the shm ring or the queue — polling,
        so a dead worker is detected (PT-DATA-001) instead of wedging the
        epoch in a blocking get."""
        while True:
            if self.shm is not None:
                # Queue first (non-blocking): oversized batches and
                # attach-failed workers use it, and it must not pay the
                # shm wait per batch.
                try:
                    return self.result_queue.get_nowait()
                except queue.Empty:
                    pass
                try:
                    return self.shm.get(timeout=0.1)
                except TimeoutError:
                    pass
            else:
                try:
                    return self.result_queue.get(timeout=0.1)
                except queue.Empty:
                    pass
            self._reap_dead_workers()

    def _reap_dead_workers(self):
        """Detect worker death: respawn (once, by default) re-dispatching
        the dead worker's in-flight batches, or raise the typed
        DataLoaderWorkerError when the respawn budget is spent. A worker
        that died idle is respawned too (or its slot retired so _dispatch
        routes around it) — an idle death must not swallow future batches."""
        for wid, w in enumerate(self.workers):
            if w is None or w.is_alive():
                continue
            pending = sorted(i for i, o in self._owner.items() if o == wid)
            exitcode = w.exitcode
            if self.respawns[wid] >= self.loader.worker_respawn_limit:
                if pending:
                    self._shutdown()
                    raise DataLoaderWorkerError(
                        f"[PT-DATA-001] DataLoader worker {wid} died "
                        f"(exitcode {exitcode}) with batches {pending} in "
                        f"flight and no respawn budget left")
                self.workers[wid] = None    # retired; _dispatch skips it
                continue
            self.respawns[wid] += 1
            # fresh queue: the dead process may have left the old one in an
            # inconsistent state (feeder thread mid-pickle)
            self.index_queues[wid] = self._ctx.Queue()
            self.workers[wid] = self._spawn_worker(wid)
            for idx in pending:
                self.index_queues[wid].put((idx, self.batches[idx]))

    def _shutdown(self):
        for q in self.index_queues:
            try:
                q.put(None)
            except Exception:
                pass
        # Close the ring BEFORE joining: workers parked in a blocking push wake
        # on close (push returns closed) and can then see the None sentinel.
        if self.shm is not None:
            self.shm.close()
            self.shm = None
        for w in self.workers:
            if w is None:
                continue
            w.join(timeout=1)
            if w.is_alive():
                w.terminate()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass
