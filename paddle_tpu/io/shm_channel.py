"""Shared-memory batch channel for DataLoader worker→trainer transport.

Reference: the worker pool in python/paddle/io/dataloader/dataloader_iter.py:370
ships batches through core.LoDTensorBlockingQueue with mmap-backed tensors (C++
blocking queue + shared memory) so bulk array bytes never pass through a pickle
pipe. TPU-native equivalent: a POSIX shared-memory MPMC ring
(paddle_tpu/native/src/shm_ring.cc). Batch structure (nesting, dtypes, shapes)
is pickled; ndarray payloads are written raw into the ring.

Message layout: [u32 manifest_len][pickle(manifest)][array0 bytes][array1 ...].
The manifest is the batch structure with each ndarray replaced by
("__nd__", i, dtype_str, shape); oversized batches (> ring capacity) raise and
the caller falls back to the queue transport.
"""

from __future__ import annotations

import ctypes
import pickle
import struct
from typing import Any, List, Optional, Tuple

import numpy as np

from .. import native

__all__ = ["ShmChannel", "pack_batch", "unpack_batch"]


def _extract(obj: Any, arrays: List[np.ndarray]) -> Any:
    if isinstance(obj, np.ndarray):
        idx = len(arrays)
        arrays.append(np.ascontiguousarray(obj))
        return ("__nd__", idx, obj.dtype.str, obj.shape)
    if isinstance(obj, tuple):
        return tuple(_extract(o, arrays) for o in obj)
    if isinstance(obj, list):
        return [_extract(o, arrays) for o in obj]
    if isinstance(obj, dict):
        return {k: _extract(v, arrays) for k, v in obj.items()}
    return obj


def _rebuild(obj: Any, buf: memoryview, offsets: List[Tuple[int, int]]) -> Any:
    if isinstance(obj, tuple):
        if len(obj) == 4 and obj[0] == "__nd__":
            _, idx, dtype, shape = obj
            start, nbytes = offsets[idx]
            return np.frombuffer(buf[start:start + nbytes], dtype=np.dtype(dtype)).reshape(shape)
        return tuple(_rebuild(o, buf, offsets) for o in obj)
    if isinstance(obj, list):
        return [_rebuild(o, buf, offsets) for o in obj]
    if isinstance(obj, dict):
        return {k: _rebuild(v, buf, offsets) for k, v in obj.items()}
    return obj


def pack_batch(payload: Any) -> bytes:
    arrays: List[np.ndarray] = []
    manifest = _extract(payload, arrays)
    head = pickle.dumps((manifest, [(a.dtype.str, a.shape, a.nbytes) for a in arrays]),
                        protocol=pickle.HIGHEST_PROTOCOL)
    parts = [struct.pack("<I", len(head)), head]
    parts.extend(a.tobytes() for a in arrays)
    return b"".join(parts)


def unpack_batch(data: bytes) -> Any:
    (hlen,) = struct.unpack_from("<I", data, 0)
    manifest, metas = pickle.loads(data[4:4 + hlen])
    buf = memoryview(data)
    offsets = []
    pos = 4 + hlen
    for _, _, nbytes in metas:
        offsets.append((pos, nbytes))
        pos += nbytes
    return _rebuild(manifest, buf, offsets)


class ShmChannel:
    """MPMC byte-record channel over a named POSIX shm ring."""

    def __init__(self, name: str, capacity: int = 64 << 20, create: bool = True):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError(f"native library unavailable: {native.load_error()}")
        self.name = name
        self.capacity = capacity
        if create:
            self._handle = self._lib.pt_shmring_create(name.encode(), capacity)
        else:
            self._handle = self._lib.pt_shmring_attach(name.encode())
        if not self._handle:
            raise RuntimeError(f"shm ring {'create' if create else 'attach'}({name}) failed")
        self._owner = create

    @classmethod
    def available(cls) -> bool:
        return native.available()

    def put(self, payload: Any, timeout: Optional[float] = None) -> None:
        data = pack_batch(payload)
        tmo = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.pt_shmring_push(self._handle, data, len(data), tmo)
        if rc == -2:
            raise ValueError(
                f"record of {len(data)} bytes exceeds ring capacity {self.capacity}")
        if rc != 0:
            raise TimeoutError("shm ring push timed out or channel closed")

    def get(self, timeout: Optional[float] = None) -> Any:
        tmo = -1 if timeout is None else int(timeout * 1000)
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.pt_shmring_pop(self._handle, ctypes.byref(out), tmo)
        if n == -3:
            raise EOFError("shm ring closed")
        if n < 0:
            raise TimeoutError("shm ring pop timed out")
        length = ctypes.c_int(int(n))
        data = native.take_bytes(self._lib, out, length)
        return unpack_batch(data)

    def qsize_bytes(self) -> int:
        return int(self._lib.pt_shmring_size(self._handle))

    def close(self) -> None:
        if self._handle:
            self._lib.pt_shmring_close(self._handle)
            self._handle = None
            if self._owner:
                self._lib.pt_shmring_unlink(self.name.encode())

    def detach(self) -> None:
        if self._handle:
            self._lib.pt_shmring_detach(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close() if self._owner else self.detach()
        except Exception:
            pass
