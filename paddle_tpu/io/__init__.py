"""paddle_tpu.io — Dataset/DataLoader/samplers (reference: python/paddle/io).

TPU-native DataLoader notes: the accelerator consumes whole batches via a single
device_put (host->HBM over PCIe/tunnel); prefetching overlaps host collate with
device compute. Multi-process workers use the same worker-pool design as the
reference's _DataLoaderIterMultiProcess (io/dataloader/dataloader_iter.py:370) with
an in-memory queue instead of LoDTensorBlockingQueue shared memory.
"""

from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .dataloader import (  # noqa: F401
    DataLoader,
    DataLoaderWorkerError,
    default_collate_fn,
    get_worker_info,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
