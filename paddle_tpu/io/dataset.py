"""Dataset abstractions (reference: python/paddle/io/dataloader/dataset.py)."""

from __future__ import annotations

import bisect
from typing import List, Sequence


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        import numpy as np

        assert all(t.shape[0] == tensors[0].shape[0] for t in tensors)
        self.tensors = tensors
        # device->host once; per-sample indexing must not dispatch device ops
        self._arrays = [np.asarray(getattr(t, "_data", t)) for t in tensors]

    def __getitem__(self, index):
        return tuple(a[index] for a in self._arrays)

    def __len__(self):
        return self._arrays[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        self.cumulative_sizes = []
        s = 0
        for d in self.datasets:
            s += len(d)
            self.cumulative_sizes.append(s)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        start = 0 if di == 0 else self.cumulative_sizes[di - 1]
        return self.datasets[di][idx - start]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None) -> List[Subset]:
    import numpy as np

    total = len(dataset)
    if abs(sum(lengths) - 1.0) < 1e-6 and all(isinstance(l, float) for l in lengths):
        lengths = [int(l * total) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    assert sum(lengths) == total
    perm = np.random.permutation(total)
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off : off + n].tolist()))
        off += n
    return out
