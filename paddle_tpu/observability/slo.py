"""SLO attainment and goodput accounting over the trace surface.

The metrics layer (PR 7) measures latency; this module judges it
(docs/OBSERVABILITY.md "Traffic replay & SLO attainment"): an
:class:`SLOConfig` names per-request targets (TTFT, mean inter-token
latency, queue wait) and :class:`SLOMonitor` turns the existing
:class:`~paddle_tpu.observability.tracing.TraceRecorder` data into

- **windowed attainment** — per window, the fraction of finished requests
  that met EVERY target (plus per-signal attainment read straight from the
  recorder's fixed-bucket histograms via the new
  ``Histogram.snapshot()``/``delta()`` reads — no recorder swap between
  windows), and per-TENANT attainment from the ``tenant`` tag the serving
  engine stamps on submit/admit;
- **goodput** — tokens/sec from requests that met every SLO, as distinct
  from raw throughput: a server in queueing collapse can post high
  tokens/sec while its goodput is ~0 because every token lands after the
  deadline the caller cared about. Goodput is what the autoscaler
  (inference/autoscale.py) and the ``serving_goodput_tokens_per_sec``
  bench line optimize.

Wiring: ``monitor.attach(tracer)`` installs the monitor as the tracer's
``slo`` sink — the stamp sites (submit/admit/first_token/finish/shed) feed
it per-request facts under the tracer's stamp lock, behind one
``is not None`` check each, so an un-monitored tracer pays nothing. All
monitor state sits behind its own lock (the driver/autoscaler thread reads
windows while replica threads stamp — PT-RACE discipline), and the
per-request staging table is bounded: terminal requests retire from it
immediately, which is what keeps a long replay O(in-flight).

Export: :func:`paddle_tpu.observability.collectors.slo_collector` renders
the monitor as ``pt_slo_*`` Prometheus families through the standard
registry/endpoint path.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Dict, Optional

__all__ = ["SLOConfig", "SLOMonitor"]


@dataclasses.dataclass
class SLOConfig:
    """Per-request SLO targets + the windowing/attainment contract.

    A request MEETS the SLO iff it finished cleanly (``finish`` — sheds,
    deadline evictions and failures never meet it) and each of its
    measured signals is at or under its target; a signal that was never
    measured for the request (e.g. inter-token latency on a 1-token
    response) is vacuously met. ``None`` disables a target entirely.

    ``target_attainment`` is the WINDOW contract the autoscaler and the
    replay gate judge against: the fraction of a window's finished
    requests that must meet the SLO. ``window_s`` is the attainment
    window length in the driving clock's seconds (virtual seconds under
    :class:`~paddle_tpu.observability.workload.VirtualClock` replay)."""

    ttft_ms: Optional[float] = 1000.0
    inter_token_ms: Optional[float] = None
    queue_wait_ms: Optional[float] = None
    target_attainment: float = 0.9
    window_s: float = 1.0


class _Window:
    __slots__ = ("finished", "met", "shed", "tokens", "good_tokens",
                 "submitted", "by_tenant")

    def __init__(self):
        self.submitted = 0
        self.finished = 0           # terminals of any kind (sheds included)
        self.met = 0
        self.shed = 0               # sheds among finished (never met)
        self.tokens = 0             # tokens from every terminal'd request
        self.good_tokens = 0        # tokens from SLO-meeting requests only
        self.by_tenant: Dict[str, list] = {}   # name -> [finished, met]


class SLOMonitor:
    """Windowed SLO attainment + goodput over one TraceRecorder.

    >>> monitor = SLOMonitor(SLOConfig(ttft_ms=500.0, window_s=1.0))
    >>> monitor.attach(tracer)          # tracer.slo = monitor
    >>> ... serve ...
    >>> monitor.roll_window(duration_s=1.0)   # per window boundary
    >>> monitor.report()["windows"][-1]["attainment"]

    ``roll_window`` finalizes the in-progress window into the (bounded)
    history and returns its summary; the driver calls it at each window
    boundary of ITS clock and passes the window duration explicitly —
    the monitor never reads a clock itself, so the same code serves
    virtual-clock replays and wall-clock production."""

    def __init__(self, config: SLOConfig,
                 tracer=None, max_windows: int = 512):
        self.config = config
        self.tracer = None
        self._lock = threading.Lock()
        # rid -> staged facts; retired at terminal (bounded by in-flight)
        self._staging: Dict[int, dict] = {}
        # sheds are staged here until the window ROLLS instead of booking
        # immediately: a fleet router that catches one replica's
        # RequestShed and places the request on the next candidate
        # re-opens the rid (note_reopen) in the same submit call — booking
        # the shed eagerly would count a successfully-rerouted request as
        # an SLO miss forever
        self._pending_sheds: Dict[int, dict] = {}
        self._cur = _Window()
        self.windows: deque = deque(maxlen=int(max_windows))
        self._windows_total = 0
        self.totals = {"submitted": 0, "finished": 0, "met": 0, "shed": 0,
                       "tokens": 0, "good_tokens": 0}
        self._hists = None
        self._hist_marks = None
        if tracer is not None:
            self.attach(tracer)

    def attach(self, tracer) -> "SLOMonitor":
        """Install as ``tracer.slo`` and snapshot the recorder's TTFT /
        inter-token / queue-wait histograms so the first window's
        per-signal deltas start from here."""
        self.tracer = tracer
        self._hists = {"ttft_ms": tracer._h_ttft,
                       "inter_token_ms": tracer._h_itl,
                       "queue_wait_ms": tracer._h_qwait}
        self._hist_marks = {k: h.snapshot() for k, h in self._hists.items()}
        tracer.slo = self
        return self

    # -- sink API (called by TraceRecorder under ITS stamp lock) -----------
    def note_submit(self, rid: int, tenant: Optional[str]) -> None:
        with self._lock:
            self._staging[rid] = {"tenant": tenant, "ttft": None,
                                  "qwait": None}
            self._cur.submitted += 1
            self.totals["submitted"] += 1

    def note_queue_wait(self, rid: int, wait_ms: float) -> None:
        with self._lock:
            st = self._staging.get(rid)
            if st is not None:
                st["qwait"] = float(wait_ms)

    def note_ttft(self, rid: int, ttft_ms: float) -> None:
        with self._lock:
            st = self._staging.get(rid)
            if st is not None:
                st["ttft"] = float(ttft_ms)

    def note_reopen(self, rid: int, tenant: Optional[str]) -> None:
        """The tracer re-opened a terminal'd rid (a fleet router caught
        one replica's shed and routed the request onward): cancel the
        pending shed — the request is live again and its REAL terminal is
        what gets booked. A reopen after the window already rolled (or of
        a long-gone rid) restores fresh staging instead."""
        with self._lock:
            st = self._pending_sheds.pop(rid, None)
            if st is None and rid not in self._staging:
                st = {"tenant": tenant, "ttft": None, "qwait": None}
            if st is not None:
                self._staging[rid] = st

    def note_terminal(self, rid: int, kind: str, n_out: int,
                      itl_ms: Optional[float]) -> None:
        """Book the request into the current window. A rid with no staged
        submit (e.g. a request re-opened after an earlier terminal already
        booked it) is ignored — one booking per lifecycle. Sheds are
        PENDED until the window rolls (see :meth:`note_reopen`)."""
        cfg = self.config
        with self._lock:
            st = self._staging.pop(rid, None)
            if st is None:
                return
            if kind == "shed":
                self._pending_sheds[rid] = st
                return
            met = kind == "finish"

            def within(value, target):
                # unmeasured signal = vacuously met (a 1-token response
                # has no inter-token latency to miss)
                return (target is None or value is None
                        or value <= target)

            met = (met and within(st["ttft"], cfg.ttft_ms)
                   and within(itl_ms, cfg.inter_token_ms)
                   and within(st["qwait"], cfg.queue_wait_ms))
            w = self._cur
            w.finished += 1
            w.tokens += int(n_out)
            self.totals["finished"] += 1
            self.totals["tokens"] += int(n_out)
            if met:
                w.met += 1
                w.good_tokens += int(n_out)
                self.totals["met"] += 1
                self.totals["good_tokens"] += int(n_out)
            ten = st.get("tenant")
            if ten is not None:
                row = w.by_tenant.setdefault(ten, [0, 0])
                row[0] += 1
                row[1] += 1 if met else 0

    # -- windows -----------------------------------------------------------
    def _signal_stats(self) -> Dict[str, dict]:
        """Per-signal window stats straight from the recorder histograms:
        the delta since the last roll gives this window's observations
        (``Histogram.snapshot``/``delta`` — no recorder swap), from which
        attainment-at-target and the window p50/p99 interpolate."""
        out: Dict[str, dict] = {}
        if self._hists is None:
            return out
        targets = {"ttft_ms": self.config.ttft_ms,
                   "inter_token_ms": self.config.inter_token_ms,
                   "queue_wait_ms": self.config.queue_wait_ms}
        for name, h in self._hists.items():
            row = h.delta(self._hist_marks[name])
            self._hist_marks[name] = h.snapshot()
            tgt = targets[name]
            frac = (h.row_fraction_le(row, tgt)
                    if tgt is not None else None)
            out[name] = {
                "count": h.row_count(row),
                "p50": h.row_quantile(row, 0.50),
                "p99": h.row_quantile(row, 0.99),
                "target": tgt,
                "attainment": None if frac is None else round(frac, 6),
            }
        return out

    def roll_window(self, duration_s: Optional[float] = None) -> dict:
        """Finalize the current window; returns its summary dict (also
        appended to :attr:`windows`). ``duration_s`` (the driving clock's
        window length) is the goodput denominator; without it the window
        reports token counts with null rates."""
        with self._lock:
            w, self._cur = self._cur, _Window()
            # finalize pending sheds: nothing re-opened them before the
            # window closed, so they are real caller-visible refusals
            for st in self._pending_sheds.values():
                w.finished += 1
                w.shed += 1
                self.totals["finished"] += 1
                self.totals["shed"] += 1
                ten = st.get("tenant")
                if ten is not None:
                    row = w.by_tenant.setdefault(ten, [0, 0])
                    row[0] += 1
            self._pending_sheds.clear()
            self._windows_total += 1
            idx = self._windows_total
        signals = self._signal_stats()     # instrument locks only
        dur = float(duration_s) if duration_s else None
        served = w.finished - w.shed
        rec = {
            "window": idx,
            "duration_s": dur,
            "submitted": w.submitted,
            "finished": w.finished,
            "met": w.met,
            "shed": w.shed,
            # an empty window has no evidence either way: attainment None
            "attainment": (round(w.met / w.finished, 6)
                           if w.finished else None),
            # attainment among requests that were actually SERVED (sheds
            # excluded) — what a forced brownout's exit decision must read:
            # brownout's own sheds cap the overall number, so judging
            # recovery on it would lock the degraded mode in forever
            "served_attainment": (round(w.met / served, 6)
                                  if served else None),
            "tokens": w.tokens,
            "good_tokens": w.good_tokens,
            "throughput_tokens_per_sec": (w.tokens / dur if dur else None),
            "goodput_tokens_per_sec": (w.good_tokens / dur
                                       if dur else None),
            "by_tenant": {ten: {"finished": f, "met": m,
                                "attainment": round(m / f, 6) if f else None}
                          for ten, (f, m) in sorted(w.by_tenant.items())},
            "signals": signals,
        }
        with self._lock:
            self.windows.append(rec)
        return rec

    def last_window(self) -> Optional[dict]:
        with self._lock:
            return self.windows[-1] if self.windows else None

    def attainment(self, last_n: Optional[int] = None) -> Optional[float]:
        """Request-level attainment aggregated over the last ``last_n``
        windows (all history when None) — the number the autoscaler and
        the replay gate judge against ``config.target_attainment``."""
        with self._lock:
            wins = list(self.windows)
        if last_n is not None:
            wins = wins[-int(last_n):]
        fin = sum(w["finished"] for w in wins)
        met = sum(w["met"] for w in wins)
        return (met / fin) if fin else None

    def report(self) -> dict:
        with self._lock:
            return {
                "config": dataclasses.asdict(self.config),
                "totals": dict(self.totals),
                "attainment": (self.totals["met"] / self.totals["finished"]
                               if self.totals["finished"] else None),
                # true monotonic count — self.windows is a bounded deque,
                # so len() plateaus at max_windows
                "windows_total": self._windows_total,
                "windows": list(self.windows),
            }
