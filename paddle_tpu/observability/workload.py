"""Open-loop traffic replay: seeded production-shaped load for the fleet.

Every bench in this repo drives the serving engine CLOSED-loop — submit a
wave, step until drained — which can never show queueing collapse: the
generator politely waits for the server. MLPerf-Inference-style OPEN-loop
load generation is the fix (docs/OBSERVABILITY.md "Traffic replay & SLO
attainment"): arrivals follow a fixed schedule drawn from an arrival
process, regardless of server progress, so a server falling behind grows a
real backlog and its TTFT/queue-wait tails finally look like production's.

Three pieces, all host-side and jax-free:

- :class:`WorkloadConfig` + :func:`generate_schedule` — a SEEDED,
  deterministic schedule generator: Poisson / diurnal (sinusoidally
  modulated, via thinning) / burst (square-wave rate multiplier) arrival
  processes, heavy-tailed lognormal prompt/output length draws, and a
  multi-tenant mix where each tenant's requests share a system prefix
  (exercising the radix prefix cache exactly like production system
  prompts do). Same seed ⇒ byte-identical schedule
  (:func:`encode_schedule`; ``tools/traffic_replay.py --selftest`` pins
  it) — a schedule is an artifact you can attach to a bug report.
- :class:`VirtualClock` — a discrete-event clock the replay driver (and a
  :class:`~paddle_tpu.observability.tracing.TraceRecorder` via its
  ``clock=`` parameter) advances one fixed ``dt`` per fleet step. One
  virtual second means the same thing on every machine, so SLO attainment
  measured against it is reproducible in CI; it models each replica
  stepping once per tick (the one-device-per-replica deployment the
  fleet is built toward). ``wall_clock=True`` replays against real time
  instead — the bench mode.
- :class:`ReplayDriver` — feeds the schedule to a
  :class:`~paddle_tpu.inference.fleet.FleetRouter` (or any object with
  ``submit``/``step``) WITHOUT waiting for completions: at each tick it
  submits every arrival whose time has come (a refusal — ``RequestShed``
  / ``EngineSaturated`` — is counted and dropped, never retried: the
  open-loop contract), steps the target, advances the clock, and at each
  SLO window boundary rolls the attached
  :class:`~paddle_tpu.observability.slo.SLOMonitor` window and ticks the
  attached autoscaler.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ReplayDriver", "ScheduledArrival", "TenantSpec", "VirtualClock",
           "WorkloadConfig", "decode_schedule", "encode_schedule",
           "generate_schedule", "schedule_digest"]


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant in the traffic mix.

    ``weight`` is the tenant's share of arrivals (normalized over the
    mix); ``prefix_len`` tokens of every prompt are the tenant's SHARED
    system prefix (drawn once per tenant from the workload seed), so a
    multi-tenant schedule exercises the radix prefix cache the way
    production system prompts do; ``priority`` maps straight onto
    ``Request.priority`` (LOW tenants are the ones fleet brownout sheds
    first)."""

    name: str
    weight: float = 1.0
    prefix_len: int = 0
    priority: int = 1            # Request.PRIORITY_NORMAL


@dataclasses.dataclass
class WorkloadConfig:
    """Knobs for :func:`generate_schedule`.

    Arrival process (``arrival``):

    - ``"poisson"`` — homogeneous Poisson at ``rate_rps``.
    - ``"diurnal"`` — inhomogeneous Poisson, rate modulated by
      ``1 + diurnal_depth * sin(2*pi*t/diurnal_period_s)`` (thinning).
    - ``"burst"`` — square wave: ``rate_rps`` baseline, multiplied by
      ``burst_multiplier`` inside every ``[k*burst_every_s,
      k*burst_every_s + burst_len_s)`` window — the schedule shape that
      exposes queueing collapse (ROADMAP item 3/5's
      ``serving_ttft_p99_under_burst_ms``).

    Lengths are clipped lognormals (heavy-tailed, like production): the
    ``*_mu``/``*_sigma`` parameters are the underlying normal's, lengths
    land in ``[*_min, *_max]``. Tenants default to one anonymous tenant
    with no shared prefix."""

    seed: int = 0
    duration_s: float = 10.0
    rate_rps: float = 4.0
    arrival: str = "poisson"
    diurnal_period_s: float = 10.0
    diurnal_depth: float = 0.8
    burst_every_s: float = 4.0
    burst_len_s: float = 1.0
    burst_multiplier: float = 4.0
    vocab_size: int = 256
    prompt_mu: float = 2.5
    prompt_sigma: float = 0.6
    prompt_min: int = 4
    prompt_max: int = 64
    output_mu: float = 2.0
    output_sigma: float = 0.7
    output_min: int = 2
    output_max: int = 32
    tenants: Tuple[TenantSpec, ...] = (TenantSpec("default"),)


@dataclasses.dataclass
class ScheduledArrival:
    """One scheduled request: arrival time (seconds from schedule start),
    tenant, the full prompt token ids (shared tenant prefix + fresh
    suffix), the decode budget, the sampling seed and the priority — a
    complete, replayable description (the same fields the request journal
    persists)."""

    t: float
    tenant: str
    prompt: Tuple[int, ...]
    max_new: int
    seed: int
    priority: int


def _rate_at(cfg: WorkloadConfig, t: float) -> float:
    if cfg.arrival == "poisson":
        return cfg.rate_rps
    if cfg.arrival == "diurnal":
        return cfg.rate_rps * (1.0 + cfg.diurnal_depth
                               * math.sin(2.0 * math.pi * t
                                          / cfg.diurnal_period_s))
    if cfg.arrival == "burst":
        in_burst = (t % cfg.burst_every_s) < cfg.burst_len_s
        return cfg.rate_rps * (cfg.burst_multiplier if in_burst else 1.0)
    raise ValueError(f"unknown arrival process {cfg.arrival!r} "
                     "(poisson | diurnal | burst)")


def _peak_rate(cfg: WorkloadConfig) -> float:
    if cfg.arrival == "diurnal":
        return cfg.rate_rps * (1.0 + abs(cfg.diurnal_depth))
    if cfg.arrival == "burst":
        return cfg.rate_rps * max(1.0, cfg.burst_multiplier)
    return cfg.rate_rps


def _clipped_lognormal(rng, mu: float, sigma: float, lo: int,
                       hi: int) -> int:
    return int(min(hi, max(lo, round(float(rng.lognormal(mu, sigma))))))


def generate_schedule(cfg: WorkloadConfig) -> List[ScheduledArrival]:
    """Draw the full arrival schedule. Deterministic: every random draw
    comes from ONE ``np.random.default_rng(cfg.seed)`` stream in a fixed
    order, so the same config produces the byte-identical schedule
    (:func:`encode_schedule`) on every platform numpy supports.

    Inhomogeneous processes use thinning: candidates are drawn at the
    peak rate and accepted with probability ``rate(t)/peak`` — exact for
    any bounded rate function, and the acceptance draw is consumed for
    EVERY candidate so the stream stays aligned."""
    if cfg.rate_rps <= 0 or cfg.duration_s <= 0:
        raise ValueError("rate_rps and duration_s must be positive")
    tenants = list(cfg.tenants) or [TenantSpec("default")]
    total_w = sum(max(0.0, t.weight) for t in tenants)
    if total_w <= 0:
        raise ValueError("tenant weights must sum to a positive value")
    cum_w = np.cumsum([max(0.0, t.weight) / total_w for t in tenants])
    rng = np.random.default_rng(int(cfg.seed))
    # per-tenant shared system prefixes, drawn FIRST (fixed order) so the
    # tenant mix cannot shift them between runs
    prefixes = {t.name: tuple(int(x) for x in rng.integers(
        0, cfg.vocab_size, (max(0, int(t.prefix_len)),)))
        for t in tenants}
    peak = _peak_rate(cfg)
    out: List[ScheduledArrival] = []
    t = 0.0
    k = 0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= cfg.duration_s:
            break
        accept = float(rng.random())          # consumed per candidate
        if accept * peak > _rate_at(cfg, t):
            continue
        tw = float(rng.random())
        # clamp: normalized weights can cumulate to 1 - 2^-53, and a draw
        # landing exactly there would index one past the end
        ten = tenants[min(int(np.searchsorted(cum_w, tw, side="right")),
                          len(tenants) - 1)]
        plen = _clipped_lognormal(rng, cfg.prompt_mu, cfg.prompt_sigma,
                                  cfg.prompt_min, cfg.prompt_max)
        olen = _clipped_lognormal(rng, cfg.output_mu, cfg.output_sigma,
                                  cfg.output_min, cfg.output_max)
        prefix = prefixes[ten.name]
        suffix_len = max(1, plen - len(prefix))
        suffix = tuple(int(x) for x in rng.integers(
            0, cfg.vocab_size, (suffix_len,)))
        k += 1
        out.append(ScheduledArrival(
            t=round(t, 9), tenant=ten.name, prompt=prefix + suffix,
            max_new=olen, seed=int(cfg.seed) * 1_000_003 + k,
            priority=ten.priority))
    return out


def encode_schedule(schedule: Sequence[ScheduledArrival]) -> bytes:
    """Canonical byte encoding (JSON lines, sorted keys, fixed float
    formatting via ``round`` at generation time) — the replayable artifact
    whose byte-identity across same-seed runs the selftest pins."""
    lines = []
    for a in schedule:
        lines.append(json.dumps(
            {"t": a.t, "tenant": a.tenant, "prompt": list(a.prompt),
             "max_new": a.max_new, "seed": a.seed, "priority": a.priority},
            sort_keys=True, separators=(",", ":")).encode("utf-8"))
    return b"\n".join(lines) + (b"\n" if lines else b"")


def decode_schedule(data: bytes) -> List[ScheduledArrival]:
    out = []
    for line in data.decode("utf-8").splitlines():
        if not line.strip():
            continue
        d = json.loads(line)
        out.append(ScheduledArrival(
            t=float(d["t"]), tenant=str(d["tenant"]),
            prompt=tuple(int(x) for x in d["prompt"]),
            max_new=int(d["max_new"]), seed=int(d["seed"]),
            priority=int(d["priority"])))
    return out


def schedule_digest(schedule: Sequence[ScheduledArrival]) -> str:
    return hashlib.blake2b(encode_schedule(schedule),
                           digest_size=16).hexdigest()


class VirtualClock:
    """Discrete-event clock: ``clock()`` reads the current virtual time in
    seconds, ``advance(dt)`` moves it. Passed as a
    :class:`TraceRecorder`'s ``clock=`` so TTFT/inter-token spans are
    measured in virtual seconds — machine-speed independent, hence CI
    stable. (Queue-wait as stamped by the engine uses wall monotonic time;
    virtual-clock SLOs should target TTFT, which subsumes queueing.)"""

    def __init__(self, t0: float = 0.0):
        self._t = float(t0)

    def __call__(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        self._t += float(dt)
        return self._t


class ReplayDriver:
    """Open-loop replay of a schedule against a fleet (or engine-like
    target).

    >>> clock = VirtualClock()
    >>> tracer = TraceRecorder(clock=clock)
    >>> fleet = FleetRouter(build, d, num_replicas=1, tracer=tracer)
    >>> drv = ReplayDriver(fleet, schedule, clock=clock, dt_s=0.05,
    ...                    monitor=monitor, autoscaler=scaler)
    >>> report = drv.run()

    Each tick: submit every arrival with ``t <= now`` (open-loop — the
    schedule never waits for the server; refusals are counted in
    ``stats["refused"]`` and dropped), step the target once, advance the
    clock by ``dt_s``. At every ``window_s`` boundary the SLO monitor's
    window is rolled and the autoscaler ticks (measurement then control —
    the closed loop of the observatory). With ``wall_clock=True`` the
    driver paces against real ``time.monotonic()`` instead and never
    sleeps (steps ARE the pacing; a tick with no due arrival still
    steps the target so in-flight work drains).

    After the last arrival the driver keeps stepping until the target
    reports no work (the drain tail is still measured — tail latching is
    the point of open-loop replay) or ``max_steps`` elapses."""

    def __init__(self, target, schedule: Sequence[ScheduledArrival],
                 clock: Optional[VirtualClock] = None, dt_s: float = 0.05,
                 monitor=None, autoscaler=None,
                 window_s: Optional[float] = None, wall_clock: bool = False,
                 max_steps: int = 200_000, request_cls=None):
        self.target = target
        self.schedule = sorted(schedule, key=lambda a: a.t)
        self.clock = clock if clock is not None else VirtualClock()
        self.dt_s = float(dt_s)
        self.monitor = monitor
        self.autoscaler = autoscaler
        self.window_s = float(window_s) if window_s is not None else (
            monitor.config.window_s if monitor is not None else None)
        self.wall_clock = bool(wall_clock)
        self.max_steps = int(max_steps)
        if request_cls is None:
            from ..inference.serving import Request

            request_cls = Request
        self._request_cls = request_cls
        self.requests: List = []
        self._last_roll_t = 0.0
        self.stats = {"submitted": 0, "refused": 0, "steps": 0,
                      "windows": 0}

    def _submit_due(self, now: float, idx: int) -> int:
        from ..inference.serving import EngineSaturated, RequestShed

        while idx < len(self.schedule) and self.schedule[idx].t <= now:
            a = self.schedule[idx]
            idx += 1
            req = self._request_cls(
                np.asarray(a.prompt, np.int32), max_new_tokens=a.max_new,
                seed=a.seed, priority=a.priority, tenant=a.tenant)
            try:
                self.target.submit(req)
            except (EngineSaturated, RequestShed):
                # open-loop: a refused arrival is load the server failed to
                # take, not load to re-offer — count it and move on (sheds
                # the router stamped are already in the tracer/monitor)
                self.stats["refused"] += 1
                continue
            self.stats["submitted"] += 1
            self.requests.append(req)
        return idx

    def _roll_window(self, now: float) -> None:
        """Roll at clock time ``now``: the window's rate denominator is
        the MEASURED time since the previous roll (under a wall clock,
        slow fleet steps make windows roll late — booking their tokens
        over the nominal ``window_s`` would overstate goodput). Virtual
        clocks roll exactly on the boundary, so measured == nominal
        there. A catch-up roll with zero elapsed time reports null
        rates."""
        self.stats["windows"] += 1
        dt = max(0.0, now - self._last_roll_t)
        self._last_roll_t = now
        if self.monitor is not None:
            self.monitor.roll_window(duration_s=dt if dt > 0 else None)
        if self.autoscaler is not None:
            self.autoscaler.tick()

    def run(self) -> dict:
        t0_wall = time.monotonic()
        idx = 0
        self._last_roll_t = 0.0
        next_window = (self.window_s if self.window_s is not None
                       else float("inf"))
        for _ in range(self.max_steps):
            now = (time.monotonic() - t0_wall if self.wall_clock
                   else self.clock())
            idx = self._submit_due(now, idx)
            if idx >= len(self.schedule) and not self.target.has_work():
                break
            if (self.wall_clock and not self.target.has_work()
                    and idx < len(self.schedule)):
                # idle gap before the next arrival: sleep instead of
                # hot-stepping an empty fleet (open-loop still holds —
                # nothing is due, so nothing is delayed)
                time.sleep(min(self.schedule[idx].t - now, 0.01))
                now = time.monotonic() - t0_wall
                while now >= next_window:
                    self._roll_window(now)
                    next_window += self.window_s
                continue
            self.target.step()
            self.stats["steps"] += 1
            if not self.wall_clock:
                self.clock.advance(self.dt_s)
                now = self.clock()
            else:
                now = time.monotonic() - t0_wall
            while now >= next_window:
                self._roll_window(now)
                next_window += self.window_s
        # close the partial final window so the tail is measured
        if self.window_s is not None and self.monitor is not None:
            self._roll_window(self.clock() if not self.wall_clock
                              else time.monotonic() - t0_wall)
        return self.report()

    def report(self) -> dict:
        rep = {"driver": dict(self.stats),
               "schedule": {"arrivals": len(self.schedule),
                            "digest": schedule_digest(self.schedule)}}
        if self.monitor is not None:
            rep["slo"] = self.monitor.report()
        if self.autoscaler is not None:
            rep["autoscaler"] = self.autoscaler.report()
        return rep
