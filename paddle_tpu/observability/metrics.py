"""Typed metrics registry with a Prometheus text exposition surface.

The TPU-native counterpart of the reference's monitoring hooks: every
telemetry dict the repo already keeps (``engine.stats``, the ``retry_call``
registry, guard/watchdog escalation counts, pool/radix occupancy,
``FleetRouter`` per-replica load) becomes a **collector** that is read at
SCRAPE time — pull-based, so instrumented code pays nothing between
scrapes and the registry holds no unbounded state:

- :class:`Counter` / :class:`Gauge` — one float per label set.
- :class:`Histogram` — FIXED bucket bounds (no reservoir, no unbounded
  sample list); percentiles are estimated from the cumulative bucket
  counts (:meth:`Histogram.quantile`), which is what the serving SLO
  summaries read (docs/OBSERVABILITY.md).
- :class:`MetricsRegistry` — owns instruments + collectors;
  :meth:`~MetricsRegistry.dump` renders the whole surface in Prometheus
  text format (one-shot scrape); ``tools/scrape_metrics.py`` and
  :class:`~paddle_tpu.observability.server.MetricsServer` serve it.

A collector is a zero-arg callable (or an object with ``collect()``)
returning an iterable of :class:`MetricFamily` — built fresh per scrape,
so adapters read live objects (``sup.engine`` after a rebuild, a fleet's
current replica set) instead of pinning dead ones.

Everything here is stdlib-only and host-side: recording NEVER touches
jax, device buffers, or the jitted step path.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricFamily",
           "MetricsRegistry", "parse_prometheus_text",
           "DEFAULT_LATENCY_BUCKETS_MS"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default bucket bounds for millisecond latency histograms — fixed and
#: log-spaced so the state is bounded regardless of traffic volume
DEFAULT_LATENCY_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                              250.0, 500.0, 1000.0, 2500.0, 5000.0,
                              10000.0, 30000.0)


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class MetricFamily:
    """One named metric with samples — the unit collectors emit and
    :func:`parse_prometheus_text` returns. ``samples`` are
    ``(suffix, labels_dict, value)``; the suffix is "" for plain
    counters/gauges and ``_bucket``/``_sum``/``_count`` for histograms."""

    def __init__(self, name: str, kind: str, help: str = ""):
        self.name = _check_name(name)
        if kind not in ("counter", "gauge", "histogram", "untyped"):
            raise ValueError(f"invalid metric kind {kind!r}")
        self.kind = kind
        self.help = help
        self.samples: List[Tuple[str, Dict[str, str], float]] = []

    def add(self, value: float, suffix: str = "", **labels) -> "MetricFamily":
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r}")
        self.samples.append((suffix, {k: str(v) for k, v in labels.items()},
                             float(value)))
        return self

    def render(self) -> List[str]:
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        for suffix, labels, value in self.samples:
            out.append(f"{self.name}{suffix}{_label_str(labels)} "
                       f"{_fmt(value)}")
        return out


class _Instrument:
    """Base: one value (or bucket vector) per label set; thread-safe under a
    shared registry lock (recording paths are host-side control plane)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 lock: Optional[threading.Lock] = None):
        self.name = _check_name(name)
        self.help = help
        self._lock = lock or threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def _key(self, labels: Dict[str, str]):
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r}")
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def family(self) -> MetricFamily:
        fam = MetricFamily(self.name, self.kind, self.help)
        with self._lock:
            items = list(self._values.items())
        for key, value in items:
            fam.add(value, **dict(key))
        if not items and self.kind in ("counter", "gauge"):
            fam.add(0.0)        # a registered metric always renders
        return fam


class Counter(_Instrument):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)


class Histogram(_Instrument):
    """Fixed-bucket histogram: cumulative counts per upper bound plus
    sum/count — bounded state no matter how many observations land, and
    enough to estimate percentiles (:meth:`quantile`, linear interpolation
    inside the winning bucket) for the SLO summary lines."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS,
                 lock: Optional[threading.Lock] = None):
        super().__init__(name, help, lock)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets: Tuple[float, ...] = tuple(bs)
        # per label set: [count per bucket..., +Inf count, sum]
        self._values: Dict[tuple, List[float]] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            row = self._values.get(key)
            if row is None:
                row = self._values[key] = [0.0] * (len(self.buckets) + 2)
            for i, b in enumerate(self.buckets):
                if v <= b:
                    row[i] += 1
                    break
            else:
                row[len(self.buckets)] += 1
            row[-1] += v

    def count(self, **labels) -> int:
        key = self._key(labels)
        with self._lock:
            row = list(self._values.get(key) or ())
        return int(sum(row[:-1])) if row else 0

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Estimate the q-quantile (0..1) from the bucket counts: walk the
        cumulative distribution to the winning bucket, interpolate linearly
        between its bounds. Observations past the last bound clamp to it
        (the standard Prometheus ``histogram_quantile`` posture). None when
        nothing was observed."""
        return self.row_quantile(self.snapshot(**labels), q)

    # -- windowed reads (docs/OBSERVABILITY.md "Traffic replay & SLO
    # attainment"): a scraper that wants PER-WINDOW percentiles/attainment
    # snapshots the row at each window boundary and works on the delta —
    # no recorder swap, no state reset, reads under the instrument lock
    def snapshot(self, **labels) -> Tuple[float, ...]:
        """Immutable copy of the row for one label set:
        ``(count per bucket..., +Inf count, sum)`` — all zeros when nothing
        was observed yet, so ``delta`` against a pre-traffic snapshot is
        always well-defined."""
        key = self._key(labels)
        with self._lock:
            row = self._values.get(key)
            return tuple(row) if row else (0.0,) * (len(self.buckets) + 2)

    def delta(self, since: Optional[Sequence[float]], **labels
              ) -> Tuple[float, ...]:
        """Current row minus an earlier :meth:`snapshot` — the WINDOW'S
        observations as a standalone row (``since=None`` means everything
        so far). Counts are monotonic, so the subtraction is exact."""
        cur = self.snapshot(**labels)
        if since is None:
            return cur
        return tuple(c - s for c, s in zip(cur, since))

    def row_count(self, row: Sequence[float]) -> int:
        return int(sum(row[:-1]))

    def row_quantile(self, row: Sequence[float], q: float
                     ) -> Optional[float]:
        """:meth:`quantile` over an explicit row (a snapshot or a window
        delta) instead of the live state."""
        if not row:
            return None
        total = sum(row[:-1])
        if total <= 0:
            return None
        rank = max(0.0, min(1.0, float(q))) * total
        cum = 0.0
        lo = 0.0
        for i, b in enumerate(self.buckets):
            prev = cum
            cum += row[i]
            if cum >= rank and row[i] > 0:
                frac = (rank - prev) / row[i]
                return lo + (b - lo) * min(1.0, max(0.0, frac))
            lo = b
        return self.buckets[-1]    # landed in the +Inf bucket: clamp

    def row_fraction_le(self, row: Sequence[float], value: float
                        ) -> Optional[float]:
        """Fraction of a row's observations at or below ``value`` (linear
        interpolation inside the straddling bucket) — the per-signal SLO
        attainment read. Observations in the +Inf bucket count as above
        every finite value; None when the row is empty."""
        total = sum(row[:-1])
        if total <= 0:
            return None
        v = float(value)
        cum = 0.0
        lo = 0.0
        for i, b in enumerate(self.buckets):
            if v >= b:
                cum += row[i]
            else:
                if v > lo and row[i] > 0:
                    cum += row[i] * (v - lo) / (b - lo)
                break
            lo = b
        return min(1.0, cum / total)

    def family(self) -> MetricFamily:
        fam = MetricFamily(self.name, self.kind, self.help)
        with self._lock:
            items = [(k, list(v)) for k, v in self._values.items()]
        for key, row in items:
            labels = dict(key)
            cum = 0.0
            for i, b in enumerate(self.buckets):
                cum += row[i]
                fam.add(cum, suffix="_bucket", le=_fmt(b), **labels)
            cum += row[len(self.buckets)]
            fam.add(cum, suffix="_bucket", le="+Inf", **labels)
            fam.add(row[-1], suffix="_sum", **labels)
            fam.add(cum, suffix="_count", **labels)
        if not items:
            # a registered histogram always renders (all-zero row) — same
            # posture as counters/gauges in _Instrument.family: dashboards
            # keyed on the family never see it vanish, and the scrape gate
            # can REQUIRE it before the first observation lands (e.g.
            # pt_migration_time_ms on a fleet that has not migrated yet)
            for b in self.buckets:
                fam.add(0.0, suffix="_bucket", le=_fmt(b))
            fam.add(0.0, suffix="_bucket", le="+Inf")
            fam.add(0.0, suffix="_sum")
            fam.add(0.0, suffix="_count")
        return fam


class MetricsRegistry:
    """Instrument factory + collector host + exposition renderer.

    >>> reg = MetricsRegistry()
    >>> c = reg.counter("pt_requests_total", "requests seen")
    >>> c.inc(replica="0")
    >>> reg.register_collector(lambda: [MetricFamily("pt_up", "gauge")
    ...                                 .add(1.0)])
    >>> text = reg.dump()        # Prometheus text format, one-shot scrape

    Re-requesting an instrument name returns the SAME instrument (the
    engine and a collector can share a counter); re-requesting it with a
    different type raises.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}
        self._collectors: List[Callable[[], Iterable[MetricFamily]]] = []

    # -- instrument factories ----------------------------------------------
    def _make(self, cls, name, help, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{inst.kind}, not {cls.kind}")
                return inst
            inst = self._instruments[name] = cls(name, help, **kw)
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._make(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._make(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS
                  ) -> Histogram:
        return self._make(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    # -- collectors --------------------------------------------------------
    def register_collector(self, collector) -> None:
        """``collector`` is a zero-arg callable or an object with
        ``collect()``, returning an iterable of :class:`MetricFamily`.
        Called at every scrape — read live state, never cache objects that
        can be rebuilt out from under you."""
        fn = getattr(collector, "collect", None)
        with self._lock:
            # the scrape thread snapshots under this lock (collect());
            # registration happens while serving traffic is live
            self._collectors.append(fn if callable(fn) else collector)

    def collect(self) -> List[MetricFamily]:
        """All families: own instruments first, then each collector's. A
        collector that raises is surfaced as a ``pt_collector_errors``
        sample instead of killing the scrape (a wedged adapter must not
        take the whole telemetry endpoint down with it)."""
        fams: List[MetricFamily] = []
        with self._lock:
            instruments = list(self._instruments.values())
            collectors = list(self._collectors)
        for inst in instruments:
            fams.append(inst.family())
        errors = 0
        for coll in collectors:
            try:
                fams.extend(coll())
            except Exception:
                errors += 1
        if errors:
            fams.append(MetricFamily(
                "pt_collector_errors", "gauge",
                "collectors that raised during this scrape").add(errors))
        # merge same-name families (e.g. per-replica engine families from a
        # fleet collector): Prometheus text allows ONE block per name
        merged: Dict[str, MetricFamily] = {}
        for fam in fams:
            have = merged.get(fam.name)
            if have is None:
                merged[fam.name] = fam
            else:
                have.samples.extend(fam.samples)
        return list(merged.values())

    def dump(self) -> str:
        """The whole registry in Prometheus text exposition format —
        the one-shot scrape ``tools/scrape_metrics.py`` and the
        ``MetricsServer`` ``/metrics`` endpoint serve."""
        lines: List[str] = []
        for fam in self.collect():
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, MetricFamily]:
    """Parse Prometheus text exposition back into families — the validator
    ``tools/scrape_metrics.py --selftest`` and the tests run over a scrape
    (name -> family; histogram suffixes fold into their base family)."""
    fams: Dict[str, MetricFamily] = {}
    types: Dict[str, str] = {}
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s+(\S+)\s*$")
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

    def unescape(s: str) -> str:
        # inverse of _escape: \\ -> \, \n -> newline, \" -> quote
        return (s.replace("\\\\", "\x00").replace("\\n", "\n")
                .replace('\\"', '"').replace("\x00", "\\"))
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            parts = rest.split()
            if len(parts) == 2:
                types[parts[0]] = parts[1]
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        if not m:
            raise ValueError(f"unparseable metric line: {raw!r}")
        name, _, labelblob, value = m.groups()
        base = name
        suffix = ""
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[: -len(suf)] in types:
                base, suffix = name[: -len(suf)], suf
                break
        fam = fams.get(base)
        if fam is None:
            fam = fams[base] = MetricFamily(base,
                                            types.get(base, "untyped"))
        labels = {k: unescape(v)
                  for k, v in label_re.findall(labelblob or "")}
        v = float("inf") if value == "+Inf" else (
            float("-inf") if value == "-Inf" else float(value))
        fam.samples.append((suffix, labels, v))
    return fams
