"""Scrapeable telemetry endpoint: a stdlib ``http.server`` thread serving
a :class:`~paddle_tpu.observability.metrics.MetricsRegistry` in Prometheus
text format.

Deliberately minimal — one daemon thread, no dependencies, port-0
friendly (tests and co-located replicas bind an ephemeral port and read
it back from :attr:`MetricsServer.port`). The scrape itself walks the
registry's collectors (pull-based), so serving traffic pays nothing until
someone actually asks.

Endpoints:

- ``GET /metrics`` — the registry dump (text/plain; version=0.0.4).
- ``GET /healthz`` — ``ok`` (liveness for the fleet's operator tooling).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import MetricsRegistry

__all__ = ["MetricsServer"]


class MetricsServer:
    """>>> server = MetricsServer(registry, port=0)   # ephemeral port
    >>> urllib.request.urlopen(server.url).read()     # one scrape
    >>> server.close()

    The server thread is a daemon: an engine process exiting never hangs
    on its telemetry endpoint.
    """

    def __init__(self, registry: MetricsRegistry, host: str = "127.0.0.1",
                 port: int = 0):
        self.registry = registry
        reg = registry

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 — BaseHTTPRequestHandler
                if self.path.split("?", 1)[0] == "/metrics":
                    try:
                        body = reg.dump().encode("utf-8")
                    except Exception as e:   # scrape must answer, not hang
                        self.send_response(500)
                        self.end_headers()
                        self.wfile.write(str(e).encode("utf-8", "replace"))
                        return
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/healthz":
                    self.send_response(200)
                    self.end_headers()
                    self.wfile.write(b"ok")
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="pt-metrics-server",
                                        daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self, timeout: Optional[float] = 2.0) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=timeout)
