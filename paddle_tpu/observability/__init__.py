"""paddle_tpu.observability — production telemetry for the serving stack.

Three pillars (docs/OBSERVABILITY.md; ROADMAP open item 5):

1. **Metrics** — :class:`MetricsRegistry` with typed
   :class:`Counter`/:class:`Gauge`/:class:`Histogram` (fixed buckets, no
   unbounded state) and a pull-based collector protocol; the repo's
   existing telemetry dicts (``engine.stats``, the ``retry_call``
   registry, guard/watchdog escalation, pool/radix occupancy,
   ``FleetRouter`` replica load) adapt in via
   :func:`engine_collector` / :func:`retry_collector` /
   :func:`guard_collector` / :func:`supervisor_collector` /
   :func:`fleet_collector`. :class:`MetricsServer` serves the whole
   registry in Prometheus text format from a stdlib ``http.server``
   thread; ``registry.dump()`` is the one-shot scrape.
2. **Tracing** — :class:`TraceRecorder` stamps host-side spans across the
   request lifecycle (submit → admit → prefill chunks → first token →
   decode → finish/evict/shed/failover), threaded through
   ``inference/serving.py``, ``recovery.py`` (spans survive crash-replay
   tagged ``recovered=true``, streamed tokens deduped against the journal
   high-water mark) and ``fleet.py`` (replica ids + failover edges);
   exports chrome-trace JSON readable in Perfetto.
3. **SLO summaries** — per-window p50/p99 time-to-first-token,
   inter-token latency, queue wait, shed/failover rates computed from the
   histograms (``TraceRecorder.slo_summary``); surfaced by ``bench.py``
   as ``serving_p50/p99_time_to_first_token_ms``.

Discipline: ALL recording is host-side, buffered, and off the jitted
step path — guarded by the ``observability_overhead_pct`` bench line
(≤5%, same posture as ``guard_overhead_pct``). This package imports no
jax and is safe to import anywhere.
"""

from .collectors import (checkpoint_collector, engine_collector,  # noqa: F401
                         fleet_collector, guard_collector,
                         procfleet_collector, retry_collector,
                         slo_collector, supervisor_collector,
                         tracer_collector)
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricFamily, MetricsRegistry,
                      parse_prometheus_text)
from .server import MetricsServer  # noqa: F401
from .slo import SLOConfig, SLOMonitor  # noqa: F401
from .tracing import TraceRecorder  # noqa: F401
from .workload import (ReplayDriver, ScheduledArrival,  # noqa: F401
                       TenantSpec, VirtualClock, WorkloadConfig,
                       decode_schedule, encode_schedule,
                       generate_schedule, schedule_digest)

__all__ = ["Counter", "Gauge", "Histogram", "MetricFamily",
           "MetricsRegistry", "MetricsServer", "ReplayDriver",
           "SLOConfig", "SLOMonitor", "ScheduledArrival", "TenantSpec",
           "TraceRecorder", "VirtualClock", "WorkloadConfig",
           "checkpoint_collector",
           "decode_schedule", "encode_schedule", "engine_collector",
           "fleet_collector", "generate_schedule", "guard_collector",
           "parse_prometheus_text", "procfleet_collector",
           "retry_collector", "schedule_digest", "slo_collector",
           "supervisor_collector", "tracer_collector"]
