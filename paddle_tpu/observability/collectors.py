"""Collector adapters: the repo's existing ad-hoc telemetry dicts exposed
as registry collectors (docs/OBSERVABILITY.md metric catalogue).

Each adapter is a zero-arg callable returning fresh
:class:`~paddle_tpu.observability.metrics.MetricFamily` objects built from
LIVE state at scrape time — pull-based, so the instrumented objects pay
nothing between scrapes, and adapters that wrap a rebuildable object (a
supervisor's engine, a fleet's replica set) always read the current one,
never a pre-rebuild corpse.

Adapters (register with ``MetricsRegistry.register_collector``):

- :func:`engine_collector` — ``ContinuousBatchingEngine``: stats dict,
  queue depth / busy slots, KV pool + radix-cache occupancy, brownout.
- :func:`retry_collector` — the ``retry_call`` module registry
  (calls/attempts/retries/giveups/latency + bounded per-``what``).
- :func:`guard_collector` — numeric-guard health events + an optional
  ``NumericWatchdog``'s skip/rollback escalation counts.
- :func:`supervisor_collector` — ``ServingSupervisor`` recovery stats +
  its CURRENT engine's families.
- :func:`fleet_collector` — ``FleetRouter``: router stats, per-replica
  state/load, and each alive replica's supervisor+engine families with a
  ``replica`` label.
- :func:`tracer_collector` — ``TraceRecorder`` health:
  ``pt_tracer_dropped_total`` / ``pt_tracer_gc_total`` — a saturated
  trace buffer silently under-reports TTFT tails, so saturation itself
  must be scrapeable.
- :func:`slo_collector` — ``SLOMonitor`` (observability/slo.py):
  windowed SLO attainment, per-tenant attainment and goodput as
  ``pt_slo_*`` families.
- :func:`checkpoint_collector` — the checkpoint lifecycle
  (distributed/resilience/lifecycle.py): published generation, publish
  totals/failures, and the train→serve phase gauge. Renders at
  zero/``idle`` with no publisher constructed, so the scrape gate
  REQUIREs the families unconditionally.
- :func:`procfleet_collector` — process-per-replica fleet transport
  (inference/procfleet): spawn/reap/heartbeat counters, workers-alive
  gauge, and — the remote-scrape topology (docs/OBSERVABILITY.md) — every
  live worker's OWN ``/metrics`` endpoint fetched at scrape time, its
  families re-labeled ``replica="<idx>"`` and merged into this registry's
  dump (``MetricsRegistry.collect`` already merges same-name families).
  Works on any router: a fleet without process replicas renders the
  ``pt_procfleet_*`` families at zero, so the scrape gate can REQUIRE
  them unconditionally.

Nothing here imports jax or touches device state.
"""

from __future__ import annotations

import contextlib
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, List, Optional

from .metrics import MetricFamily, parse_prometheus_text

__all__ = ["checkpoint_collector", "engine_collector", "fleet_collector",
           "guard_collector", "procfleet_collector", "retry_collector",
           "slo_collector", "supervisor_collector", "tracer_collector"]


def _stat_families(prefix: str, stats: dict, kinds: dict,
                   **labels) -> List[MetricFamily]:
    out = []
    for key, val in stats.items():
        if not isinstance(val, (int, float)):
            continue
        name = f"{prefix}_{key}"
        kind = kinds.get(key, "counter")
        out.append(MetricFamily(name, kind).add(float(val), **labels))
    return out


# stats-dict keys that are level readings, not monotonic totals
_ENGINE_GAUGE_KEYS = {"compile_cache_entries"}
# stats-dict keys NOT exported from engine.stats: "evictions" is a lagging
# copy of radix.evictions (synced only at admit/brownout time) and the
# collector already exports the live value as pt_radix_evictions_total —
# two families for one quantity that disagree mid-flight is worse than one.
# The spec proposed/accepted counters export under their REQUIRED
# pt_spec_* names below, not as a second pt_engine_* copy; spec_steps has
# no pt_spec_* twin and stays in the auto-exported pt_engine_* set (the
# verify-dispatch count is what shows spec degrading to 1-token
# dispatches). The mesh counters export under their REQUIRED
# pt_serving_* names below.
_ENGINE_SKIP_KEYS = {"evictions", "spec_proposed", "spec_accepted",
                     "mesh_collective_bytes", "mesh_decode_steps"}


def engine_collector(engine, **labels):
    """Families for one ``ContinuousBatchingEngine`` (pass ``labels`` such
    as ``replica="0"`` when scraping several engines into one registry)."""

    def collect() -> Iterable[MetricFamily]:
        fams = _stat_families(
            "pt_engine",
            {k: v for k, v in engine.stats.items()
             if k not in _ENGINE_SKIP_KEYS},
            {k: "gauge" for k in _ENGINE_GAUGE_KEYS}, **labels)
        fams.append(MetricFamily(
            "pt_engine_queue_depth", "gauge",
            "requests waiting for a slot").add(len(engine._queue), **labels))
        fams.append(MetricFamily(
            "pt_engine_busy_slots", "gauge").add(
            engine.active_slots(), **labels))
        fams.append(MetricFamily("pt_engine_max_batch", "gauge").add(
            engine.max_batch, **labels))
        fams.append(MetricFamily(
            "pt_engine_scheduled_tokens_total", "counter",
            "tokens scheduled across all requests").add(
            engine._sched_tokens, **labels))
        fams.append(MetricFamily("pt_engine_steps_total", "counter").add(
            engine._step_idx, **labels))
        rate = MetricFamily("pt_engine_decode_tokens_per_sec", "gauge",
                            "EMA of scheduled-tokens/s")
        rate.add(engine._ema_tok_s or 0.0, **labels)
        fams.append(rate)
        if engine.prefix_cache is not None:
            alloc, radix = engine._alloc, engine._radix
            fams.append(MetricFamily(
                "pt_pool_blocks_total", "gauge",
                "KV pool capacity in pages").add(alloc.num_blocks, **labels))
            fams.append(MetricFamily(
                "pt_pool_free_blocks", "gauge").add(alloc.free_blocks,
                                                    **labels))
            fams.append(MetricFamily(
                "pt_radix_cached_blocks", "gauge",
                "pages registered in the radix prefix cache").add(
                len(radix), **labels))
            fams.append(MetricFamily(
                "pt_radix_evictions_total", "counter").add(radix.evictions,
                                                           **labels))
        # _brownout_active exists on every engine (it just never flips
        # without a prefix cache) — emit unconditionally so dashboards
        # keyed on the gauge never see the family vanish
        fams.append(MetricFamily(
            "pt_engine_brownout_active", "gauge").add(
            1.0 if engine._brownout_active else 0.0, **labels))
        # speculative decode + int8 KV block format (docs/SERVING.md):
        # REQUIRED families (tools/scrape_metrics.py --selftest), rendered
        # at zero on non-spec / fp engines so dashboards never lose them
        prop = float(engine.stats.get("spec_proposed", 0))
        acc = float(engine.stats.get("spec_accepted", 0))
        fams.append(MetricFamily(
            "pt_spec_proposed_total", "counter",
            "draft tokens proposed by the speculative decoder").add(
            prop, **labels))
        fams.append(MetricFamily(
            "pt_spec_accepted_total", "counter",
            "draft tokens accepted by the in-graph verify").add(
            acc, **labels))
        fams.append(MetricFamily(
            "pt_spec_acceptance_rate", "gauge",
            "accepted / proposed draft tokens (lifetime)").add(
            acc / prop if prop > 0 else 0.0, **labels))
        fams.append(MetricFamily(
            "pt_kv_quant_blocks", "gauge",
            "pool pages held in the int8 KV block format").add(
            float(getattr(engine, "_kv_quant_blocks", 0)), **labels))
        # mesh-sharded serving (docs/SERVING.md "Sharded serving"):
        # REQUIRED families, rendered on unsharded engines too (tp=1,
        # zero collective bytes) so dashboards keyed on the gauge see
        # every replica of a mixed fleet
        mesh = getattr(engine, "mesh", None)
        fams.append(MetricFamily(
            "pt_serving_mesh_shape", "gauge",
            "tp width of the engine's serving mesh (1 == unsharded)").add(
            float(mesh.tp) if mesh is not None else 1.0, **labels))
        fams.append(MetricFamily(
            "pt_serving_collective_bytes_total", "counter",
            "wire bytes moved by serving collectives, per device group "
            "(traced census x dispatches)").add(
            float(engine.stats.get("mesh_collective_bytes", 0.0)),
            **labels))
        fams.append(MetricFamily(
            "pt_serving_mesh_decode_steps_total", "counter",
            "sharded decode/verify program dispatches").add(
            float(engine.stats.get("mesh_decode_steps", 0)), **labels))
        return fams

    return collect


def retry_collector():
    """The ``retry_call`` module-level stats registry
    (distributed/resilience/retry.py) — calls/attempts/retries/giveups,
    cumulative latency, and the bounded per-``what`` attempt breakdown."""

    def collect() -> Iterable[MetricFamily]:
        from ..distributed.resilience.retry import retry_stats

        rs = retry_stats()
        fams = [
            MetricFamily("pt_retry_calls_total", "counter").add(rs["calls"]),
            MetricFamily("pt_retry_attempts_total", "counter").add(
                rs["attempts"]),
            MetricFamily("pt_retry_retries_total", "counter").add(
                rs["retries"]),
            MetricFamily("pt_retry_giveups_total", "counter").add(
                rs["giveups"]),
            MetricFamily("pt_retry_latency_seconds_total", "counter").add(
                rs["latency_s"]),
        ]
        by = MetricFamily("pt_retry_attempts_by_what", "counter",
                          "attempts per operation label (capped at 64)")
        for what, n in rs.get("by_what", {}).items():
            by.add(n, what=str(what))
        if by.samples:
            fams.append(by)
        return fams

    return collect


def guard_collector(watchdog=None):
    """Numeric-guard escalation surface: the eager health-event
    accumulator (framework/numeric_guard.py) and, when a
    ``NumericWatchdog`` is passed, its skip/rollback budgets."""

    def collect() -> Iterable[MetricFamily]:
        from ..framework.numeric_guard import health_events, peek_health

        fams = [
            MetricFamily("pt_guard_health_events_total", "counter",
                         "eager health-word events recorded").add(
                len(health_events())),
            MetricFamily("pt_guard_health_word", "gauge",
                         "current un-consumed health word").add(
                peek_health()),
        ]
        if watchdog is not None:
            fams.append(MetricFamily(
                "pt_guard_rollbacks_total", "counter",
                "watchdog rollback escalations").add(watchdog.rollbacks))
            fams.append(MetricFamily(
                "pt_guard_window_skips", "gauge",
                "skips inside the current escalation window").add(
                len(watchdog._window_skips)))
        return fams

    return collect


# supervisor stats NOT auto-exported as pt_supervisor_*: the elastic
# mesh-degrade pair exports under its REQUIRED pt_serving_* names below
# (reshard total + degraded gauge — docs/RESILIENCE.md "Elastic serving
# mesh"), and a second pt_supervisor_* copy of each would just split
# dashboards across two names for one quantity.
_SUPERVISOR_SKIP_KEYS = {"mesh_reshards", "mesh_degraded"}


def supervisor_collector(sup, **labels):
    """``ServingSupervisor`` stats + its CURRENT engine's families (read
    through ``sup.engine`` at scrape time — a rebuild swaps the engine out
    from under any collector that captured it directly)."""

    def collect() -> Iterable[MetricFamily]:
        fams = _stat_families(
            "pt_supervisor",
            {k: v for k, v in sup.stats.items()
             if k not in _SUPERVISOR_SKIP_KEYS}, {}, **labels)
        stats = sup.stats
        fams.append(MetricFamily(
            "pt_serving_mesh_reshards_total", "counter",
            "elastic PT-SRV-008 mesh-degrade reshards absorbed").add(
            float(stats.get("mesh_reshards", 0)), **labels))
        fams.append(MetricFamily(
            "pt_serving_mesh_degraded", "gauge",
            "1 = this supervisor's engine is serving below its spawned "
            "mesh width (degraded)").add(
            float(stats.get("mesh_degraded", 0)), **labels))
        fams.extend(engine_collector(sup.engine, **labels)())
        return fams

    return collect


def fleet_collector(router):
    """``FleetRouter``: router-level stats, per-replica state/load gauges,
    and every serving replica's supervisor+engine families labeled
    ``replica="<idx>"`` (DEAD and RETIRED replicas keep their state gauge
    but report no load — a retired supervisor is closed)."""

    def collect() -> Iterable[MetricFamily]:
        from ..inference.fleet import _GONE, ReplicaState

        fams = _stat_families("pt_fleet", router.stats, {})
        fams.append(MetricFamily(
            "pt_fleet_brownout_active", "gauge").add(
            1.0 if router._brownout_active else 0.0))
        state = MetricFamily(
            "pt_fleet_replica_state", "gauge",
            "1=alive 0.5=draining 0=dead -1=retired (scaled in)")
        load = MetricFamily("pt_fleet_replica_load", "gauge",
                            "queued + slotted requests per replica")
        for rep in router.replicas:
            # tier label: "serving" on a flat fleet, prefill/decode under
            # a TieredRouter (docs/SERVING.md "Disaggregated tiers") — so
            # dashboards can split load/state per tier
            tier = getattr(rep, "tier", "serving")
            state.add({ReplicaState.ALIVE: 1.0,
                       ReplicaState.DRAINING: 0.5,
                       ReplicaState.RETIRED: -1.0}.get(rep.state, 0.0),
                      replica=str(rep.idx), tier=tier)
            if rep.state not in _GONE:
                load.add(rep.sup.load(), replica=str(rep.idx), tier=tier)
                fams.extend(supervisor_collector(
                    rep.sup, replica=str(rep.idx))())
        fams.append(state)
        fams.append(load)
        return fams

    return collect


def procfleet_collector(router, scrape_workers: bool = True,
                        timeout_s: float = 2.0):
    """Process-fleet transport telemetry + remote worker aggregation.

    ``pt_procfleet_spawned_total`` / ``pt_procfleet_reaped_total`` come
    from the router's stats (zero on a non-process fleet);
    ``pt_procfleet_heartbeats_total`` sums every proxy's heartbeat-probe
    count. The transport seam adds ``pt_transport_retries`` (retryable
    wire timeouts summed across replica proxies), ``pt_transport_hedges``
    (migrations raced onto a second decode replica) and
    ``pt_transport_breaker_state`` (per-replica gauge, 0=closed 1=open
    2=half_open) — all zero over an in-process fleet. With ``scrape_workers`` (default), each live worker's
    ``/metrics`` endpoint (``ProcFleetRouter.worker_metrics_urls``) is
    fetched under ``timeout_s``, parsed, re-labeled ``replica="<idx>"``
    and forwarded; a worker that cannot answer (dying, reaped mid-scrape)
    is skipped and counted in ``pt_procfleet_scrape_errors`` — one dead
    endpoint must not take the driver's scrape down."""

    def collect() -> Iterable[MetricFamily]:
        stats = getattr(router, "stats", {})
        fams = [
            MetricFamily("pt_procfleet_spawned_total", "counter",
                         "replica worker processes spawned").add(
                stats.get("proc_spawned", 0)),
            MetricFamily("pt_procfleet_reaped_total", "counter",
                         "replica worker processes reaped").add(
                stats.get("proc_reaped", 0)),
        ]
        hb = getattr(router, "heartbeat_total", None)
        fams.append(MetricFamily(
            "pt_procfleet_heartbeats_total", "counter",
            "driver-side heartbeat probes answered by workers").add(
            hb() if callable(hb) else 0))
        # transport-seam families (docs/SERVING.md "Transport seam") —
        # every read getattr-defaulted, so an IN-PROCESS fleet renders
        # them at zero (`scrape_metrics --selftest` runs exactly that)
        retries = 0
        breaker = MetricFamily(
            "pt_transport_breaker_state", "gauge",
            "per-replica circuit breaker (0=closed 1=open 2=half_open)")
        b_order = {"closed": 0, "open": 1, "half_open": 2}
        for rep in getattr(router, "replicas", ()):
            sup = getattr(rep, "sup", None)
            retries += int(getattr(sup, "transport_retries", 0) or 0)
            state_fn = getattr(sup, "breaker_state", None)
            state = state_fn() if callable(state_fn) else "closed"
            breaker.add(b_order.get(state, 0),
                        replica=str(getattr(rep, "idx", "?")))
        fams.append(MetricFamily(
            "pt_transport_retries", "counter",
            "retryable wire timeouts across replica transports "
            "(non-fatal: the probe retried or the migration hedged)").add(
            retries))
        fams.append(MetricFamily(
            "pt_transport_hedges", "counter",
            "timed-out KV migrations raced onto another decode replica"
            ).add(stats.get("migration_hedges", 0)))
        fams.append(breaker)
        urls = {}
        getter = getattr(router, "worker_metrics_urls", None)
        if callable(getter):
            urls = getter()
        fams.append(MetricFamily(
            "pt_procfleet_workers_alive", "gauge",
            "live worker processes exposing a /metrics endpoint").add(
            len(urls)))
        errors = 0
        if scrape_workers and urls:
            def fetch(item):
                idx, url = item
                with contextlib.closing(urllib.request.urlopen(
                        url, timeout=timeout_s)) as resp:
                    return idx, parse_prometheus_text(
                        resp.read().decode("utf-8"))

            # fetch workers CONCURRENTLY: the scrape blocks max(worker),
            # not sum(worker) — N dying endpoints during a rolling
            # restart must not stack N timeouts onto one registry dump
            with ThreadPoolExecutor(
                    max_workers=min(8, len(urls)),
                    thread_name_prefix="pt-procfleet-scrape") as pool:
                futures = [pool.submit(fetch, item)
                           for item in urls.items()]
                for fut in futures:
                    try:
                        idx, worker_fams = fut.result()
                    except Exception:   # dying worker: skip, count
                        errors += 1
                        continue
                    for fam in worker_fams.values():
                        out = MetricFamily(fam.name, fam.kind, fam.help)
                        for suffix, labels, value in fam.samples:
                            merged = dict(labels)
                            merged["replica"] = str(idx)
                            out.samples.append((suffix, merged, value))
                        fams.append(out)
        fams.append(MetricFamily(
            "pt_procfleet_scrape_errors", "gauge",
            "worker endpoints that failed this scrape").add(errors))
        return fams

    return collect


def tracer_collector(tracer, **labels):
    """``TraceRecorder`` health counters (read through the recorder's
    ``counters()`` — one stamp-lock acquisition per scrape):
    ``pt_tracer_dropped_total`` events refused by the bounded buffer and
    ``pt_tracer_gc_total`` terminal request records evicted past
    ``max_requests``. Either one moving means the recorder is saturated
    and TTFT tails are being under-reported — alert on it, don't trust
    the percentiles."""

    def collect() -> Iterable[MetricFamily]:
        c = tracer.counters()
        return [
            MetricFamily(
                "pt_tracer_dropped_total", "counter",
                "trace events dropped by the bounded buffer").add(
                c["dropped"], **labels),
            MetricFamily(
                "pt_tracer_gc_total", "counter",
                "terminal request records GC'd past max_requests").add(
                c["gc"], **labels),
            MetricFamily("pt_tracer_buffered_events", "gauge").add(
                c["events"], **labels),
            MetricFamily("pt_tracer_open_requests", "gauge").add(
                c["open"], **labels),
            MetricFamily("pt_tracer_resubmits_total", "counter").add(
                c["resubmits"], **labels),
        ]

    return collect


def checkpoint_collector(stats_fn=None):
    """Checkpoint-lifecycle families (docs/RESILIENCE.md "Checkpoint
    lifecycle"): ``pt_checkpoint_generation`` (the newest generation
    published to serving), ``pt_checkpoint_publish_total`` /
    ``pt_checkpoint_publish_failures`` (CheckpointPublisher outcomes) and
    ``pt_lifecycle_phase`` (one 0/1 gauge per phase of the
    train→checkpoint→shrink→resume→publish→serve arc; exactly one sample
    is 1). Reads the module-level stats in
    ``distributed.resilience.lifecycle`` — imported lazily at SCRAPE time
    so registering this collector keeps observability jax-free; pass
    ``stats_fn`` to scrape a different source (tests). With no publisher
    constructed yet every family renders at zero / phase ``idle``, so the
    scrape gate can REQUIRE them unconditionally."""

    def collect() -> Iterable[MetricFamily]:
        if stats_fn is not None:
            stats = stats_fn()
            phases = None
        else:
            from ..distributed.resilience.lifecycle import (LIFECYCLE_PHASES,
                                                            lifecycle_stats)

            stats = lifecycle_stats()
            phases = LIFECYCLE_PHASES
        if phases is None:
            phases = ("idle", "train", "checkpoint", "shrink", "resume",
                      "publish", "serve")
        fams = [
            MetricFamily(
                "pt_checkpoint_generation", "gauge",
                "newest checkpoint generation published to serving").add(
                stats.get("generation", 0)),
            MetricFamily(
                "pt_checkpoint_publish_total", "counter",
                "checkpoints handed to the serving fleet").add(
                stats.get("publish_total", 0)),
            MetricFamily(
                "pt_checkpoint_publish_failures", "counter",
                "publishes refused (corrupt manifest, stale generation, "
                "swap failure)").add(stats.get("publish_failures", 0)),
        ]
        phase = MetricFamily(
            "pt_lifecycle_phase", "gauge",
            "current phase of the train->serve lifecycle (1 = active)")
        current = stats.get("phase", "idle")
        for p in phases:
            phase.add(1.0 if p == current else 0.0, phase=p)
        fams.append(phase)
        return fams

    return collect


def slo_collector(monitor):
    """``SLOMonitor`` → ``pt_slo_*`` families: cumulative
    finished/met/good-token counters, the latest window's attainment
    (overall, per signal, per tenant) and goodput — the scrape-side face
    of the SLO observatory (docs/OBSERVABILITY.md)."""

    def collect() -> Iterable[MetricFamily]:
        rep = monitor.report()
        tot = rep["totals"]
        fams = [
            MetricFamily("pt_slo_requests_finished_total", "counter").add(
                tot["finished"]),
            MetricFamily(
                "pt_slo_requests_met_total", "counter",
                "finished requests that met every SLO target").add(
                tot["met"]),
            MetricFamily(
                "pt_slo_good_tokens_total", "counter",
                "tokens from SLO-meeting requests (goodput numerator)").add(
                tot["good_tokens"]),
            MetricFamily("pt_slo_tokens_total", "counter").add(
                tot["tokens"]),
            MetricFamily("pt_slo_windows_total", "counter").add(
                # the true monotonic count — rep["windows"] is a bounded
                # deque view that plateaus at the monitor's max_windows
                rep["windows_total"]),
            MetricFamily(
                "pt_slo_requests_shed_total", "counter",
                "sheds among finished (refused at submit — never met)"
            ).add(rep["totals"]["shed"]),
            MetricFamily(
                "pt_slo_target_attainment", "gauge",
                "the configured window attainment contract").add(
                monitor.config.target_attainment),
        ]
        att = MetricFamily("pt_slo_attainment", "gauge",
                           "last window's attainment by scope")
        goodput = MetricFamily("pt_slo_goodput_tokens_per_sec", "gauge")
        win = rep["windows"][-1] if rep["windows"] else None
        if win is not None:
            if win["attainment"] is not None:
                att.add(win["attainment"], scope="window")
            for name, sig in win["signals"].items():
                if sig.get("attainment") is not None:
                    att.add(sig["attainment"], scope=f"signal:{name}")
            for ten, row in win["by_tenant"].items():
                if row["attainment"] is not None:
                    att.add(row["attainment"], scope=f"tenant:{ten}")
            if win["goodput_tokens_per_sec"] is not None:
                goodput.add(win["goodput_tokens_per_sec"])
        if rep["attainment"] is not None:
            att.add(rep["attainment"], scope="total")
        if att.samples:
            fams.append(att)
        if goodput.samples:
            fams.append(goodput)
        return fams

    return collect
