"""Per-request trace spans across the serving lifecycle.

The reference's profiler layer composes host tracers into an event tree
with chrome-trace export (SURVEY.md §5: HostTracer + ChromeTracingLogger,
a state-scheduled ``Profiler``). This module reproduces that shape
TPU-natively for the SERVING path: every span is host-side and buffered —
nothing here touches the jitted step, device buffers, or jax at all. The
engine/supervisor/fleet stamp events only when a recorder is attached
(``tracer is None`` costs one attribute check per site).

Span taxonomy (docs/OBSERVABILITY.md state machine):

    submit ─► admit(queue_wait) ─► prefill_chunk* ─► first_token
          └► shed                                       │
                                                  decode_block*
                                                        │
                                  finish │ evict │ fail ◄┘
          (failover / migrate edges re-open a request on another replica)

Timeline semantics: spans are HOST DISPATCH windows (jax dispatch is
async — a decode block's span covers the host work that scheduled it, not
device occupancy; device-side truth stays with ``jax.profiler``). TTFT is
stamped when the first token is *scheduled*, matching what a streaming
caller can first observe through the engine's async materialization.

Crash/replay discipline (recovery.py): a re-admitted request keeps its
ORIGINAL submit timestamp and first-token stamp (first wins — TTFT spans
the crash, which is what the caller experienced); every span stamped after
:meth:`TraceRecorder.mark_recovered` carries ``recovered: true``; and
streamed-token accounting is deduped against the journal high-water mark —
catch-up regeneration below the mark adds zero tokens (the caller already
has them).

Export: :meth:`TraceRecorder.export_chrome` writes chrome-trace JSON
(``{"traceEvents": [...]}``) loadable in Perfetto / chrome://tracing —
pid = replica, tid = request id (one lane per request; tid 0 is the
engine lane). SLO summaries (p50/p99 TTFT, inter-token latency, queue
wait, shed/failover rates) are computed FROM the registry histograms
(fixed buckets — bounded state), not from raw span lists.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from .metrics import DEFAULT_LATENCY_BUCKETS_MS, MetricsRegistry

__all__ = ["TraceRecorder"]

#: terminal event names — every submitted request must reach exactly one
#: (unless it is re-opened by a failover/migration re-submit)
TERMINALS = ("finish", "evict", "shed", "fail")


class TraceRecorder:
    """Buffered host-side span recorder + SLO aggregator.

    >>> tracer = TraceRecorder()
    >>> eng = ContinuousBatchingEngine(model, ..., tracer=tracer)
    >>> ... serve ...
    >>> tracer.export_chrome("trace.json")     # open in Perfetto
    >>> tracer.slo_summary()                   # p50/p99 TTFT etc.

    ``registry``: a shared :class:`MetricsRegistry` to aggregate into
    (default: a private one). ``max_events`` bounds the chrome-trace
    buffer (oldest-first retention would reorder Perfetto lanes, so the
    buffer STOPS recording and counts drops instead — ``dropped``);
    per-request bookkeeping is bounded by ``max_requests`` with
    terminal-request eviction. ``mirror_host_events=True`` additionally
    feeds span durations into ``paddle_tpu.profiler``'s host-event table
    so ``Profiler.summary()``'s OperatorView shows serving spans beside
    model scopes.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 max_events: int = 200_000, max_requests: int = 100_000,
                 mirror_host_events: bool = False,
                 clock=time.perf_counter):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.max_events = int(max_events)
        self.max_requests = int(max_requests)
        self.mirror_host_events = bool(mirror_host_events)
        self._clock = clock
        self._t0 = clock()
        # ONE recorder is shared by every replica of a fleet — under
        # ``parallel_step`` the stamping sites run on concurrent replica
        # threads while the driver reads exports/summaries (PT-RACE-001,
        # tools/lint_concurrency.py). Re-entrant because public stamps
        # compose (finish -> tokens -> _terminal); host-side control
        # plane, so the lock costs nothing measurable per stamp.
        self._lock = threading.RLock()
        self.events: List[dict] = []
        self.dropped = 0
        self.gc_count = 0          # terminal rids evicted past max_requests
        # optional SLO sink (observability/slo.py SLOMonitor.attach): the
        # per-request attainment/goodput accounting that histograms cannot
        # carry (which REQUESTS met every target, and how many tokens they
        # streamed). Called under self._lock from the stamp sites, behind
        # one `is not None` check each — same discipline as the engine's
        # tracer attachment.
        self.slo = None
        # per-request bookkeeping (bounded: terminal rids are GC'd oldest
        # first past max_requests)
        self._submit_ts: Dict[int, float] = {}
        self._first_ts: Dict[int, float] = {}
        self._streamed: Dict[int, int] = {}    # dedup floor (journal hwm)
        self._tenant: Dict[int, str] = {}      # rid -> workload tenant tag
        self._recovered: set = set()           # rids past mark_recovered
        self._state: Dict[int, str] = {}       # "open" | terminal name
        self._order: List[int] = []            # rid insertion order for GC
        self.resubmits = 0
        reg = self.registry
        self._h_ttft = reg.histogram(
            "pt_serving_time_to_first_token_ms",
            "submit -> first scheduled token, ms",
            buckets=DEFAULT_LATENCY_BUCKETS_MS)
        self._h_itl = reg.histogram(
            "pt_serving_inter_token_ms",
            "mean inter-token latency per finished request, ms",
            buckets=DEFAULT_LATENCY_BUCKETS_MS)
        self._h_qwait = reg.histogram(
            "pt_serving_queue_wait_ms",
            "submit -> slot admission queue wait, ms",
            buckets=DEFAULT_LATENCY_BUCKETS_MS)
        self._c_submitted = reg.counter(
            "pt_serving_requests_submitted_total", "requests submitted")
        self._c_terminal = reg.counter(
            "pt_serving_requests_terminal_total",
            "terminal events by kind (finish/evict/shed/fail)")
        self._c_tokens = reg.counter(
            "pt_serving_tokens_streamed_total",
            "tokens newly streamed to callers (hwm-deduped)")
        self._c_failovers = reg.counter(
            "pt_serving_failovers_total", "requests failed over to another "
            "replica")
        # disaggregated-tier KV migration surface (inference/disagg.py —
        # docs/SERVING.md "Disaggregated tiers"): counters + a wall-time
        # histogram for the prefill→decode chain handoff. REQUIRED by
        # tools/scrape_metrics.py, so they register (and render at zero)
        # on every recorder, migrating fleet or not.
        self._c_migrations = reg.counter(
            "pt_migration_total",
            "finished-prefill KV chains migrated between serving tiers")
        self._c_migration_pages = reg.counter(
            "pt_migration_pages_total",
            "KV pages moved by tier migration")
        self._c_migration_failures = reg.counter(
            "pt_migration_failures_total",
            "migrations not spliced, by reason (corrupt/refused)")
        self._h_migration = reg.histogram(
            "pt_migration_time_ms",
            "export -> splice wall time per migrated chain, ms",
            buckets=DEFAULT_LATENCY_BUCKETS_MS)

    # -- low-level event plumbing ------------------------------------------
    def now(self) -> float:
        return self._clock()

    def _us(self, ts: float) -> float:
        return (ts - self._t0) * 1e6

    def _emit(self, ev: dict) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(ev)

    def _args(self, rid: Optional[int], tags: Optional[dict],
              extra: dict) -> dict:
        args = dict(tags) if tags else {}
        args.update(extra)
        if rid is not None and rid in self._recovered:
            args.setdefault("recovered", True)
        return args

    def instant(self, name: str, rid: Optional[int] = None,
                tags: Optional[dict] = None, **extra) -> None:
        tags = tags or {}
        with self._lock:
            self._emit({"name": name, "ph": "i", "ts": self._us(self.now()),
                        "pid": int(tags.get("replica", 0)),
                        "tid": int(rid or 0), "s": "t",
                        "args": self._args(rid, tags, extra)})

    def span(self, name: str, rid: Optional[int], t0: float,
             t1: Optional[float] = None, tags: Optional[dict] = None,
             **extra) -> None:
        t1 = self.now() if t1 is None else t1
        tags = tags or {}
        with self._lock:
            self._emit({"name": name, "ph": "X", "ts": self._us(t0),
                        "dur": max(0.0, (t1 - t0) * 1e6),
                        "pid": int(tags.get("replica", 0)),
                        "tid": int(rid or 0),
                        "args": self._args(rid, tags, extra)})
        if self.mirror_host_events:
            from ..profiler import _host_events

            _host_events.start(name, t0)
            _host_events.stop(name, t1)

    # -- request lifecycle -------------------------------------------------
    def _track(self, rid: int) -> None:
        if rid not in self._state:
            self._order.append(rid)
            self._gc()
        self._state[rid] = "open"

    def _gc(self) -> None:
        while len(self._order) > self.max_requests:
            for i, rid in enumerate(self._order):
                if self._state.get(rid) in TERMINALS:
                    self._order.pop(i)
                    for d in (self._submit_ts, self._first_ts,
                              self._streamed, self._state, self._tenant):
                        d.pop(rid, None)
                    self._recovered.discard(rid)
                    self.gc_count += 1
                    break
            else:
                return   # everything open — nothing safe to drop

    def submit(self, rid: int, prompt_tokens: int, max_new: int,
               tags: Optional[dict] = None) -> None:
        """Request entered an engine. Re-submission of a known rid (crash
        replay twin, fleet failover/migration) keeps the ORIGINAL submit
        timestamp — TTFT and queue wait stay caller-truthful — and
        re-opens a terminal'd request instead of double-counting it."""
        with self._lock:
            known = rid in self._state
            reopened = self._state.get(rid) in TERMINALS
            self._track(rid)
            tenant = (tags or {}).get("tenant")
            if tenant is not None:
                self._tenant[rid] = str(tenant)
            if not known:
                self._submit_ts[rid] = self.now()
                self._c_submitted.inc()
                if self.slo is not None:
                    self.slo.note_submit(rid, self._tenant.get(rid))
            else:
                self.resubmits += 1
                if reopened and self.slo is not None:
                    # a terminal'd rid coming back (fleet caught one
                    # replica's shed and routed onward): the pending shed
                    # is cancelled — the REAL terminal gets booked
                    self.slo.note_reopen(rid, self._tenant.get(rid))
            self.instant("submit" if not known else "resubmit", rid, tags,
                         prompt_tokens=int(prompt_tokens),
                         max_new=int(max_new), reopened=bool(reopened))

    def shed(self, rid: int, tags: Optional[dict] = None, **extra) -> None:
        with self._lock:
            if rid not in self._state:   # shed before any engine saw it
                self._track(rid)         # (fleet brownout): still tracked
                self._submit_ts[rid] = self.now()
                self._c_submitted.inc()
                if self.slo is not None:
                    self.slo.note_submit(rid, (tags or {}).get("tenant"))
            if self.slo is not None:
                self.slo.note_terminal(rid, "shed", 0, None)
            self._terminal(rid, "shed", tags, **extra)

    def admit(self, rid: int, queue_wait_s: float, hit_tokens: int = 0,
              miss_tokens: int = 0, tags: Optional[dict] = None) -> None:
        wait_ms = max(0.0, queue_wait_s * 1e3)
        with self._lock:
            if rid not in self._recovered:
                # a recovered/resumed re-admission's wait is operator cost,
                # not caller-visible queue wait — keep the SLO honest
                self._h_qwait.observe(wait_ms)
                if self.slo is not None:
                    self.slo.note_queue_wait(rid, wait_ms)
            self.instant("admit", rid, tags,
                         queue_wait_ms=round(wait_ms, 3),
                         hit_tokens=int(hit_tokens),
                         miss_tokens=int(miss_tokens))

    def prefill_chunk(self, rid: int, t0: float, tokens: int,
                      t1: Optional[float] = None,
                      tags: Optional[dict] = None) -> None:
        self.span("prefill_chunk", rid, t0, t1, tags, tokens=int(tokens))

    def first_token(self, rid: int, tags: Optional[dict] = None) -> None:
        """First scheduled token. First stamp wins: a crash-replay twin
        re-reaching its first token does NOT reset TTFT (the caller saw
        the original) — it records a tagged replay event instead."""
        with self._lock:
            if rid in self._first_ts:
                self.instant("first_token_replay", rid, tags)
                return
            ts = self.now()
            self._first_ts[rid] = ts
            sub = self._submit_ts.get(rid)
            ttft_ms = None
            if sub is not None:
                ttft_ms = (ts - sub) * 1e3
                self._h_ttft.observe(ttft_ms)
                if self.slo is not None:
                    self.slo.note_ttft(rid, ttft_ms)
            self.instant("first_token", rid, tags,
                         **({"ttft_ms": round(ttft_ms, 3)}
                            if ttft_ms is not None else {}))

    def tokens(self, rid: int, total: int,
               tags: Optional[dict] = None) -> None:
        """Book streamed-token progress; ``total`` is the request's
        cumulative scheduled-token count. Deduped against the journal
        high-water mark: during crash-replay catch-up the twin regenerates
        tokens the caller already has — those add nothing here."""
        with self._lock:
            prev = self._streamed.get(rid, 0)
            if total <= prev:
                return
            self._streamed[rid] = int(total)
            self._c_tokens.inc(total - prev)

    def decode_block(self, t0: float, n_steps: int, slots: int,
                     t1: Optional[float] = None,
                     tags: Optional[dict] = None,
                     tokens: Optional[int] = None) -> None:
        """Engine-lane span for one fused decode dispatch (tid 0 — block
        work is batched across requests, so it has no single rid).
        ``tokens`` carries the block's REAL emitted-token count: under
        speculative decoding a dispatch emits a variable 1..K+1 tokens per
        row, so TTFT/inter-token SLO math must read token progress off the
        span, never infer it from n_steps x slots."""
        extra = {} if tokens is None else {"tokens": int(tokens)}
        self.span("decode_block", None, t0, t1, tags,
                  n_steps=int(n_steps), slots=int(slots), **extra)

    def decode_block_batch(self, t0: float, n_steps: int, slots: int,
                           items, t1: Optional[float] = None,
                           tags: Optional[dict] = None,
                           tokens: Optional[int] = None) -> None:
        """One decode block's full stamp set — the block span plus every
        row's token progress — under a SINGLE lock acquisition (the
        big-batch step path; per-slot locking is O(slots) contention per
        block)."""
        with self._lock:
            self.decode_block(t0, n_steps, slots, t1, tags, tokens=tokens)
            if items:
                for rid, total in items:
                    self.tokens(rid, total, tags)

    def first_tokens(self, items, tags: Optional[dict] = None) -> None:
        """Batched first-token stamps for an admission wave: per rid the
        first-token instant (+TTFT) and the token progress, all under one
        lock acquisition. ``items``: ``(rid, total)`` pairs."""
        with self._lock:
            for rid, total in items:
                self.first_token(rid, tags)
                self.tokens(rid, total, tags)

    def finish(self, rid: int, n_out: int, failed: bool = False,
               error: Optional[str] = None, kind: Optional[str] = None,
               tags: Optional[dict] = None) -> None:
        """Terminal stamp. ``kind`` defaults to finish / evict (deadline)
        / fail, inferred from ``failed``+``error``. Also closes the SLO
        math: mean inter-token latency over the request's stream."""
        if kind is None:
            kind = ("evict" if failed and error and "deadline" in error
                    else "fail" if failed else "finish")
        with self._lock:
            first = self._first_ts.get(rid)
            itl_ms = None
            if kind == "finish" and first is not None and n_out > 1:
                itl_ms = (self.now() - first) / (n_out - 1) * 1e3
                self._h_itl.observe(itl_ms)
            self.tokens(rid, int(n_out), tags)
            if self.slo is not None:
                self.slo.note_terminal(rid, kind, int(n_out), itl_ms)
            self._terminal(rid, kind, tags, n_out=int(n_out),
                           **({"error": str(error)[:200]} if error else {}))

    def _terminal(self, rid: int, kind: str, tags: Optional[dict],
                  **extra) -> None:
        with self._lock:
            if rid not in self._state:
                self._track(rid)
            self._state[rid] = kind
            self._c_terminal.inc(kind=kind)
            self.instant(kind, rid, tags, **extra)

    # -- recovery / fleet edges -------------------------------------------
    def mark_recovered(self, rid: int, hwm: int,
                       tags: Optional[dict] = None) -> None:
        """A supervisor re-admitted ``rid`` via ``submit(resume=True)``
        (crash replay, failover, or drain migration). With ``hwm`` > 0
        tokens already delivered, raise the streamed-token dedup floor
        and tag everything after as recovered (and exclude the re-admit's
        queue wait from the SLO histogram — it is operator cost). A
        ``hwm == 0`` resume (e.g. a still-QUEUED request migrated by a
        rolling drain) has nothing to dedup and its wait on the new
        replica is real caller-visible queue wait — it stays untagged and
        fully counted."""
        with self._lock:
            self._track(rid)
            if rid not in self._submit_ts:
                self._submit_ts[rid] = self.now()   # restart: best known
            if hwm > 0:
                self._recovered.add(rid)
                self._streamed[rid] = max(self._streamed.get(rid, 0),
                                          int(hwm))
            self.instant("recovered", rid, tags, hwm=int(hwm),
                         recovered=hwm > 0)

    def failover(self, rid: int, from_replica: int, to_replica: int,
                 tags: Optional[dict] = None) -> None:
        self._c_failovers.inc()
        self.instant("failover", rid, tags, from_replica=int(from_replica),
                     to_replica=int(to_replica))

    def migrate(self, rid: int, from_replica: int, to_replica: int,
                pages: int, nbytes: int, t0: float,
                t1: Optional[float] = None,
                tags: Optional[dict] = None) -> None:
        """One finished-prefill KV chain handed from the prefill tier to a
        decode replica (inference/disagg.py): a span on the request's lane
        covering export -> splice, plus the ``pt_migration_*`` counters.
        The request stays OPEN — migration is an edge, not a terminal."""
        t1 = self.now() if t1 is None else t1
        with self._lock:
            self._c_migrations.inc()
            self._c_migration_pages.inc(int(pages))
            self._h_migration.observe(max(0.0, (t1 - t0) * 1e3))
            self.span("migrate", rid, t0, t1, tags,
                      from_replica=int(from_replica),
                      to_replica=int(to_replica), pages=int(pages),
                      bytes=int(nbytes))

    def migration_failure(self, rid: int, reason: str,
                          tags: Optional[dict] = None) -> None:
        """A chain that did not splice: ``corrupt`` (PT-SRV-007 crc/digest
        rejection — decode side re-runs prefill) or ``refused`` (pool
        shortfall — retried elsewhere / fallen back to re-prefill)."""
        with self._lock:
            self._c_migration_failures.inc(reason=str(reason))
            self.instant("migrate_failure", rid, tags, reason=str(reason))

    def recovery(self, t0: float, code: str, replayed: int,
                 t1: Optional[float] = None,
                 tags: Optional[dict] = None) -> None:
        self.span("recovery", None, t0, t1, tags, code=code,
                  replayed=int(replayed))

    def publish(self, t0: float, step: int, generation: int, shards: int,
                ok: bool = True, t1: Optional[float] = None,
                tags: Optional[dict] = None) -> None:
        """A checkpoint handed from training to serving (CheckpointPublisher,
        docs/RESILIENCE.md lifecycle): manifest verify -> in-place weight
        load -> rolling fleet swap, one span covering the whole handoff."""
        self.span("publish", None, t0, t1, tags, step=int(step),
                  generation=int(generation), shards=int(shards),
                  ok=bool(ok))

    def resume(self, t0: float, step: int, world: int,
               t1: Optional[float] = None,
               tags: Optional[dict] = None) -> None:
        """An elastic resume: checkpoint reloaded (reshard-on-load) onto
        the surviving mesh at the recorded step."""
        self.span("resume", None, t0, t1, tags, step=int(step),
                  world=int(world))

    # -- introspection / export -------------------------------------------
    def counters(self) -> dict:
        """Recorder health counters, read under the stamp lock — the
        ``tracer_collector`` source for ``pt_tracer_dropped_total`` /
        ``pt_tracer_gc_total`` (a saturated buffer or a GC'd request set
        silently under-reports TTFT tails; this makes saturation itself a
        scrapeable signal)."""
        with self._lock:
            return {"events": len(self.events), "dropped": self.dropped,
                    "gc": self.gc_count, "resubmits": self.resubmits,
                    "open": sum(1 for st in self._state.values()
                                if st == "open")}

    def is_open(self, rid: int) -> bool:
        """True while ``rid`` is submitted but has no terminal span yet —
        callers that might race the engine's own terminal stamp (e.g. the
        supervisor's replay-divergence path, where the twin may already
        have finished through ``_mark_done``) guard on this to preserve
        the one-terminal-per-lifecycle invariant."""
        with self._lock:
            return self._state.get(rid) == "open"

    def incomplete(self) -> List[int]:
        """Submitted rids with no terminal span yet — empty once a served
        wave has fully drained (the lifecycle-completeness invariant)."""
        with self._lock:
            return [rid for rid, st in self._state.items() if st == "open"]

    def lifecycle(self, rid: int) -> List[str]:
        """Ordered event names for one request — what the tests assert the
        submit -> admit -> first_token -> finish chain on."""
        with self._lock:
            return [e["name"] for e in self.events
                    if e.get("tid") == rid and rid != 0]

    def export_chrome(self, path: Optional[str] = None) -> dict:
        """Chrome-trace JSON (Perfetto / chrome://tracing loadable):
        ``{"traceEvents": [...]}`` with request lanes (tid = rid) and the
        engine lane (tid 0), pid = replica."""
        with self._lock:
            events = list(self.events)
            dropped = self.dropped
        meta = []
        pids = sorted({e.get("pid", 0) for e in events})
        for pid in pids:
            meta.append({"name": "process_name", "ph": "M", "ts": 0,
                         "pid": pid, "tid": 0,
                         "args": {"name": f"replica{pid}"}})
            meta.append({"name": "thread_name", "ph": "M", "ts": 0,
                         "pid": pid, "tid": 0,
                         "args": {"name": "engine"}})
        doc = {"traceEvents": meta + events,
               "displayTimeUnit": "ms",
               "otherData": {"dropped_events": dropped}}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    def slo_summary(self) -> dict:
        """SLO rollup computed from the (fixed-bucket) histograms:
        p50/p99 TTFT, mean-inter-token-latency percentiles, queue-wait
        percentiles, shed/failover rates. The bench surfaces
        ``serving_p50/p99_time_to_first_token_ms`` from here."""
        def q(h, p):
            v = h.quantile(p)
            return None if v is None else round(v, 3)

        submitted = self._c_submitted.value()
        shed = self._c_terminal.value(kind="shed")
        out = {
            "p50_time_to_first_token_ms": q(self._h_ttft, 0.50),
            "p99_time_to_first_token_ms": q(self._h_ttft, 0.99),
            "p50_inter_token_ms": q(self._h_itl, 0.50),
            "p99_inter_token_ms": q(self._h_itl, 0.99),
            "p50_queue_wait_ms": q(self._h_qwait, 0.50),
            "p99_queue_wait_ms": q(self._h_qwait, 0.99),
            "submitted": int(submitted),
            "tokens_streamed": int(self._c_tokens.value()),
            "shed_rate": (shed / submitted) if submitted else 0.0,
            "failover_rate": (self._c_failovers.value() / submitted
                              if submitted else 0.0),
        }
        return out
