"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's capability
surface, built from scratch on JAX/XLA/Pallas/pjit.

Top-level namespace mirrors ``paddle``: tensor ops, nn, optimizer, autograd, amp, io,
jit, static, distributed, incubate, profiler, metric, vision.
"""

from __future__ import annotations

from .version import full_version as __version__  # noqa: E402

from . import flags as _flags_mod
from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16,
    bool_ as bool,  # noqa: A001
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.tensor import Parameter, Tensor  # noqa: F401
from .core.autograd_engine import enable_grad, no_grad, set_grad_enabled  # noqa: F401
from .core.autograd_engine import grad  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .framework import ParamAttr, load, save, seed  # noqa: F401
from .framework.random import get_rng_state, set_rng_state  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .tensor import einsum  # noqa: F401

from . import amp  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import dataset  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import fft  # noqa: F401,E402
from . import framework  # noqa: F401,E402
from . import geometric  # noqa: F401,E402
from . import hapi  # noqa: F401,E402
from . import hub  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import nn  # noqa: F401,E402
from . import observability  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import signal  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import version  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from .hapi.model import Model  # noqa: F401,E402
from .jit.api import to_static  # noqa: F401,E402


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone trainable parameter (reference: python/paddle/tensor/
    creation.py create_parameter)."""
    from .nn import initializer as I

    init = default_initializer or (I.Constant(0.0) if is_bias
                                   else I.XavierUniform())
    data = init(shape, dtype)
    return Parameter(data, dtype=dtype, name=name)


def create_tensor(dtype="float32", name=None, persistable=False):
    import jax.numpy as _jnp

    return Tensor(_jnp.zeros((), _dtype_mod.convert_dtype(dtype)), name=name)


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


def is_compiled_with_distribute() -> bool:
    return True


def in_dynamic_mode() -> bool:
    from .core import static_graph

    return not static_graph.static_mode_enabled()


def in_static_mode() -> bool:
    from .core import static_graph

    return static_graph.static_mode_enabled()


def enable_static():
    from .core import static_graph

    static_graph.enable_static_mode()


def disable_static(place=None):
    from .core import static_graph

    static_graph.disable_static_mode()


def set_device(device):
    return device


def get_device():
    import jax

    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def device_count():
    import jax

    return jax.device_count()


def set_printoptions(**kwargs):
    import numpy as np

    np.set_printoptions(**{k: v for k, v in kwargs.items() if k in ("precision", "threshold", "edgeitems", "linewidth")})


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """FLOPs of one forward pass at ``input_size`` (reference:
    hapi/dynamic_flops.py walks layers with per-type formulas).

    TPU-native: the forward is jit-compiled and XLA's own cost model is
    asked (``compiled.cost_analysis()['flops']``) — every op the compiler
    actually emits is counted, including fused ones, with no per-layer
    formula table to maintain. ``custom_ops`` is accepted for API parity
    but unused (XLA already costs custom ops it compiles)."""
    import jax
    import jax.numpy as jnp

    from .core import autograd_engine
    from .core.tensor import Tensor
    from .jit.api import _collect_state, _Swap

    _, tensors = _collect_state(net)
    params = [t._data for t in tensors]
    x = jnp.zeros(tuple(input_size), jnp.float32)

    def fwd(ps, xx):
        with autograd_engine.no_grad(), _Swap(tensors, ps):
            out = net(Tensor(xx))
        return out._data if isinstance(out, Tensor) else out

    ca = jax.jit(fwd).lower(params, x).compile().cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    total = int(ca.get("flops", 0) or 0)
    if print_detail:
        print(f"Total Flops: {total}  (XLA cost model, input {tuple(input_size)})")
    return total


CPUPlace = type("CPUPlace", (), {})
CUDAPlace = type("CUDAPlace", (), {"__init__": lambda self, i=0: None})
TPUPlace = type("TPUPlace", (), {"__init__": lambda self, i=0: None})

DataParallel = None  # bound by paddle_tpu.distributed at import


def _late_bind():
    global DataParallel
    from .distributed.parallel import DataParallel as _DP

    DataParallel = _DP


try:
    _late_bind()
except Exception:  # distributed optional at import time
    pass
