"""In-process interleaved A/B of flash backward block configurations.

Kernel-level fwd+bwd attention at the training shapes (GQA 16/4, d 128),
scan-chained so there is no per-call dispatch floor. Variants mutate
ops.flash_attention.BWD_ROW_CAP before tracing (read at trace time):

  rows1024 : folded dQ/dKV rows capped at 1024 (bq 256 at group 4)
  rows512  : cap 512 (bq 128)
  rows2048 : cap 2048 (bq 512, bk halved to 256 by the VMEM guard)

Usage: python benchmarks/flash_block_ab.py [seq] [rounds]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import importlib

# ops/__init__ rebinds the `flash_attention` attribute to the FUNCTION, which
# shadows the submodule for plain `import ... as` — resolve via sys.modules
fa = importlib.import_module("paddle_tpu.ops.flash_attention")

SEQ = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
ROUNDS = int(sys.argv[2]) if len(sys.argv) > 2 else 3
B = 2 if SEQ <= 4096 else 1
HQ, HKV, D = 16, 4, 128
ITERS = 8


def main():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, SEQ, HQ, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, SEQ, HKV, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, SEQ, HKV, D)), jnp.bfloat16)

    def make(cap):
        def chain(q0, k0, v0):
            fa.BWD_ROW_CAP[0] = cap          # baked at trace time

            def body(c, _):
                qq, kk, vv = c

                def loss(a, b, cdv):
                    return jnp.sum(
                        fa.flash_attention(a, b, cdv, causal=True)
                        .astype(jnp.float32) ** 2)

                l, (dq, dk, dv) = jax.value_and_grad(
                    loss, argnums=(0, 1, 2))(qq, kk, vv)
                eps = jnp.bfloat16(1e-12)
                return (qq + eps * dq.astype(qq.dtype),
                        kk + eps * dk.astype(kk.dtype),
                        vv + eps * dv.astype(vv.dtype)), l

            (_, _, _), ls = jax.lax.scan(body, (q0, k0, v0), None,
                                         length=ITERS)
            return ls.sum()

        return jax.jit(chain)

    variants = {"rows1024": make(1024), "rows512": make(512),
                "rows2048": make(2048)}
    # causal fwd+bwd model flops: fwd 2 matmuls + bwd 5 (dq:3 shared s/dp
    # counted once... use 3.5x fwd convention) — report RELATIVE ms only plus
    # an absolute TF/s using the 3.5x-fwd convention
    fwd_flops = 2 * 2 * B * HQ * SEQ * SEQ * D / 2  # causal half
    tot = 3.5 * fwd_flops

    best = {}
    for name, fn in variants.items():
        t0 = time.perf_counter()
        jax.device_get(fn(q, k, v).reshape(1))
        print(f"# {name}: compiled+warm {time.perf_counter()-t0:.1f}s",
              flush=True)
        best[name] = float("inf")

    for r in range(ROUNDS):
        for name, fn in variants.items():
            t0 = time.perf_counter()
            jax.device_get(fn(q, k, v).reshape(1))
            dt = (time.perf_counter() - t0) / ITERS
            best[name] = min(best[name], dt)
            print(f"round {r} {name:9s}: {dt*1e3:7.2f} ms  "
                  f"{tot/dt/1e12:5.1f} TF/s", flush=True)

    print(f"\n== best-of-{ROUNDS} seq {SEQ} (b{B} h{HQ}/{HKV} d{D}) ==")
    for name, dt in best.items():
        print(f"{name:9s}: {dt*1e3:7.2f} ms  {tot/dt/1e12:5.1f} TF/s")


if __name__ == "__main__":
    main()
