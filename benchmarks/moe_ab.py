"""In-process interleaved A/B of MoE dispatch modes on the bench shape
(MoE llama 8 experts top-2, b8 seq2048, bf16).

Variants share one param set (pure fwd+bwd — no optimizer state):
  - scatter : capacity-bounded segment-sum dispatch (round-4 state)
  - ragged  : dropless jax.lax.ragged_dot grouped matmuls (round 5)
  - einsum  : GShard dense one-hot dispatch (reference formulation)

Same methodology as remat_ab.py: jitted lax.scan chain over fresh batches,
params as arguments, grads kept live via a probe, interleaved rounds,
best-of-N. Usage: python benchmarks/moe_ab.py [rounds]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.jit.api import _collect_state, _Swap
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

ROUNDS = int(sys.argv[1]) if len(sys.argv) > 1 else 3
BATCH, SEQ, ITERS = 8, 2048, 4


def main():
    dev = jax.devices()[0]
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=4,
        max_position_embeddings=2048, dtype="bfloat16", num_experts=8,
        moe_topk=2)
    model = LlamaForCausalLM(cfg)
    _, tensors = _collect_state(model)
    params = [t._data for t in tensors]

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (ITERS, BATCH, SEQ)),
                      jnp.int32)

    def make_step(mode):
        def step(ps, batch_ids):
            def loss_of(ps_):
                with _Swap(tensors, ps_):
                    return model.loss_fn(batch_ids, batch_ids)

            l, g = jax.value_and_grad(loss_of)(ps)
            probe = sum(gg.ravel()[0].astype(jnp.float32) for gg in g)
            ps = [p_ + 0.0 * gg.astype(p_.dtype) for p_, gg in zip(ps, g)]
            return ps, l.astype(jnp.float32) + 0.0 * probe

        def chain(ps, ids_stack):
            cfg.moe_dispatch = mode          # baked at trace time
            for layer in model.model.layers:
                layer.mlp.dispatch_mode = mode
            _, losses = jax.lax.scan(step, list(ps), ids_stack)
            return losses.sum()

        return jax.jit(chain)

    variants = {m: make_step(m) for m in ("scatter", "pgmm", "ragged")}

    n_total = sum(int(np.prod(p.shape)) for p in model.parameters())
    n_exp = sum(int(np.prod(p.shape)) for name, p in model.named_parameters()
                if ".experts." in name)
    n_act = n_total - n_exp * (1.0 - cfg.moe_topk / cfg.num_experts)
    fpt = 6.0 * n_act + 6.0 * cfg.num_hidden_layers * cfg.hidden_size * SEQ
    peak = 197e12 if "v5 lite" in dev.device_kind.lower() else 459e12

    best = {}
    for name, fn in variants.items():
        try:
            t0 = time.perf_counter()
            jax.device_get(fn(params, ids))
            print(f"# {name}: compiled+warm in {time.perf_counter()-t0:.1f}s",
                  flush=True)
            best[name] = float("inf")
        except Exception as e:
            print(f"# {name}: FAILED {e!r}", flush=True)

    for r in range(ROUNDS):
        for name, fn in variants.items():
            if name not in best:
                continue
            t0 = time.perf_counter()
            jax.device_get(fn(params, ids))
            dt = (time.perf_counter() - t0) / ITERS
            best[name] = min(best[name], dt)
            tok = BATCH * SEQ / dt
            print(f"round {r} {name:8s}: {dt*1e3:7.1f} ms/step "
                  f"{tok:9.0f} tok/s  activated-mfu {tok*fpt/peak:.3f}",
                  flush=True)

    print("\n== best-of-%d (fwd+bwd only) ==" % ROUNDS)
    for name, dt in best.items():
        tok = BATCH * SEQ / dt
        print(f"{name:8s}: {dt*1e3:7.1f} ms/step {tok:9.0f} tok/s  "
              f"activated-mfu {tok*fpt/peak:.3f}")


if __name__ == "__main__":
    main()
