"""In-process interleaved A/B of remat operating points on the north-star
llama shape (853M, seq 4096, GQA 16/4).

Variants (one shared param set — pure fwd+bwd, no optimizer state, so all
variants fit HBM together and interleave honestly):
  - noremat      : recompute=False              (the headline regime)
  - remat_flash  : recompute=True, policy saves flash out+lse (round-4 state)
  - remat_qkv    : recompute=True, policy additionally saves rope'd q/k/v
                   (kills the qkv-proj + rope + norm1 recompute)

Each timed sample is a jitted lax.scan chain over `ITERS` fresh batches whose
carry folds the loss AND one element of every grad (so no dW matmul can be
DCE'd); one device_get fences the chain — no per-step dispatch floor in the
numbers. Rounds are interleaved across variants so chip-state drift hits all
sides equally; report best-of-N per variant.

Usage: python benchmarks/remat_ab.py [batch] [rounds]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.jit.api import _collect_state, _Swap
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 4
ROUNDS = int(sys.argv[2]) if len(sys.argv) > 2 else 3
SEQ = 4096
ITERS = 4


def main():
    dev = jax.devices()[0]
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=16, num_attention_heads=16, num_key_value_heads=4,
        max_position_embeddings=4096, dtype="bfloat16", recompute=True)
    model = LlamaForCausalLM(cfg)
    _, tensors = _collect_state(model)
    params = [t._data for t in tensors]
    n_params = cfg.num_params()

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (ITERS, BATCH, SEQ)),
                      jnp.int32)

    def make_step(recompute, policy):
        def step(ps, batch_ids):
            def loss_of(ps_):
                with _Swap(tensors, ps_):
                    return model.loss_fn(batch_ids, batch_ids)

            l, g = jax.value_and_grad(loss_of)(ps)
            # keep every dW live (one element each — a DCE'd backward matmul
            # would otherwise make remat look free); params must flow in as
            # ARGUMENTS (closing over them would bake 1.7GB of literals into
            # the HLO and stall the remote compiler)
            probe = sum(gg.ravel()[0].astype(jnp.float32) for gg in g)
            ps = [p_ + 0.0 * gg.astype(p_.dtype) for p_, gg in zip(ps, g)]
            return ps, l.astype(jnp.float32) + 0.0 * probe

        def chain(ps, ids_stack):
            # trace-time switch: config mutated before each variant's first
            # call, read inside the traced model
            cfg.recompute = recompute
            cfg.remat_policy = policy
            _, losses = jax.lax.scan(step, list(ps), ids_stack)
            return losses.sum()

        return jax.jit(chain)

    variants = {
        "noremat": make_step(False, "flash"),
        "remat_flash": make_step(True, "flash"),
        "remat_qkv": make_step(True, "flash_qkv"),
    }

    peak = 197e12 if "v5 lite" in dev.device_kind.lower() else 459e12
    flops_per_token = 6.0 * n_params + 6.0 * 16 * 2048 * SEQ

    # compile + one warm pass each (mutating cfg between traces is safe: the
    # policy is baked in at trace time)
    best = {}
    for name, fn in variants.items():
        try:
            t0 = time.perf_counter()
            jax.device_get(fn(params, ids))
            print(f"# {name}: compiled+warm in {time.perf_counter()-t0:.1f}s",
                  flush=True)
            best[name] = float("inf")
        except Exception as e:
            print(f"# {name}: FAILED {e!r}", flush=True)

    for r in range(ROUNDS):
        for name, fn in variants.items():
            if name not in best:
                continue
            t0 = time.perf_counter()
            jax.device_get(fn(params, ids))
            dt = (time.perf_counter() - t0) / ITERS
            best[name] = min(best[name], dt)
            tok = BATCH * SEQ / dt
            print(f"round {r} {name:12s}: {dt*1e3:7.1f} ms/step  "
                  f"{tok:9.0f} tok/s  mfu {tok*flops_per_token/peak:.3f}",
                  flush=True)

    print("\n== best-of-%d (fwd+bwd only, batch %d) ==" % (ROUNDS, BATCH))
    for name, dt in best.items():
        tok = BATCH * SEQ / dt
        print(f"{name:12s}: {dt*1e3:7.1f} ms/step  {tok:9.0f} tok/s  "
              f"mfu {tok*flops_per_token/peak:.3f}")
    if "noremat" in best and "remat_qkv" in best:
        print(f"remat_qkv tax vs noremat: "
              f"{(best['remat_qkv']/best['noremat']-1)*100:.1f}%")
    if "noremat" in best and "remat_flash" in best:
        print(f"remat_flash tax vs noremat: "
              f"{(best['remat_flash']/best['noremat']-1)*100:.1f}%")


if __name__ == "__main__":
    main()
