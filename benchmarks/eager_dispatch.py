"""Eager per-op dispatch overhead microbenchmark (VERDICT r3 next #8).

Parity anchor: the reference gates op-level perf in CI
(tools/ci_op_benchmark.sh over benchmark/api scripts). Here the measured
quantity is the FRAMEWORK overhead per eager op — everything apply_fn adds
on top of jax's own eager dispatch: tape recording, AMP classification,
static-graph interception checks, Tensor wrap/unwrap.

Methodology: time N chained `paddle.add` calls on a small [8, 8] operand
(device work ~0) in four regimes, then subtract the raw-jnp baseline.
Numbers are host-CPU-bound; run on an idle machine. The CI gate
(tests/test_ci_gates.py::test_eager_dispatch_overhead_bounded) asserts a
GENEROUS multiple of the raw-jnp time so real regressions (accidental
per-op retraces, O(n) tape scans) fail fast while shared-CI jitter passes.

Run: python benchmarks/eager_dispatch.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(n_ops: int = 2000):
    # NOTE: no platform pinning here — the test suite imports this under its
    # own CPU-pinned config; standalone runs pin in __main__ below
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core import autograd_engine

    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    y = paddle.to_tensor(np.ones((8, 8), np.float32))
    xa, ya = x._data, y._data

    def timed(fn, reps=3):
        fn()  # warm (compile the add)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best / n_ops * 1e6  # us per op

    def raw_jnp():
        a = xa
        for _ in range(n_ops):
            a = jnp.add(a, ya)
        a.block_until_ready()

    def eager_no_grad():
        with autograd_engine.no_grad():
            a = x
            for _ in range(n_ops):
                a = paddle.add(a, y)
            a._data.block_until_ready()

    def eager_tape():
        xg = paddle.to_tensor(np.ones((8, 8), np.float32),
                              stop_gradient=False)
        a = xg
        for _ in range(n_ops):
            a = paddle.add(a, y)
        a._data.block_until_ready()

    def eager_amp():
        with autograd_engine.no_grad(), paddle.amp.auto_cast():
            a = x
            for _ in range(n_ops):
                a = paddle.add(a, y)
            a._data.block_until_ready()

    out = {
        "raw_jnp_us": timed(raw_jnp),
        "eager_no_grad_us": timed(eager_no_grad),
        "eager_tape_us": timed(eager_tape),
        "eager_amp_us": timed(eager_amp),
    }
    base = out["raw_jnp_us"]
    for k in ("eager_no_grad_us", "eager_tape_us", "eager_amp_us"):
        out[k.replace("_us", "_x_raw")] = out[k] / base
    return out


if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")  # host-dispatch measurement
    res = measure()
    for k, v in res.items():
        print(f"{k:24s} {v:8.2f}")
