"""North-star MFU decomposition on the real chip (one process, interleaved).

Times, at the north-star shape (853M, seq 4096, GQA 4/16, bf16):
  - loss-only forward
  - value_and_grad with remat (flash policy) and without
  - full engine step (adds clip + AdamW)
  - 16 chained flash-attention layers fwd+bwd at the training shape,
    inside ONE jit (lax.scan) — in-situ kernel throughput, no dispatch floor
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

V5E_PEAK = 197e12


def fence(x):
    jax.device_get(jax.tree_util.tree_leaves(x)[0].sum()
                   if hasattr(jax.tree_util.tree_leaves(x)[0], "sum")
                   else x)


def bench(f, *args, iters=6):
    o = f(*args)
    fence(o)
    t0 = time.perf_counter()
    for _ in range(iters):
        o = f(*args)
    fence(o)
    return (time.perf_counter() - t0) / iters


def main():
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    batch, seq = 4, 4096
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=16, num_attention_heads=16,
        num_key_value_heads=4, max_position_embeddings=4096,
        dtype="bfloat16", recompute=True)
    n = cfg.num_params()
    fpt = 6.0 * n + 6.0 * cfg.num_hidden_layers * cfg.hidden_size * seq
    tok = batch * seq

    model = LlamaForCausalLM(cfg)
    eng = Engine(model, mesh=None, lr=1e-4, clip_norm=1.0)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    params = [t._data for t in eng._param_tensors]

    def loss_fn(ps, ids):
        from paddle_tpu.jit.api import _Swap
        from paddle_tpu.core import autograd_engine

        with autograd_engine.no_grad(), _Swap(eng._param_tensors, ps):
            return model.loss_fn(ids, ids)

    t_fwd = bench(jax.jit(loss_fn), params, ids)
    print(f"fwd-only:        {t_fwd*1e3:7.1f} ms  "
          f"(model-fwd mfu {tok*(fpt/3)/t_fwd/V5E_PEAK:.3f})")

    t_step = bench(lambda i: eng.step(i, i), ids)
    print(f"full step:       {t_step*1e3:7.1f} ms  (mfu {tok*fpt/t_step/V5E_PEAK:.3f})")

    # engine-level remat on/off comparison at batch 2 (no-remat fits there)
    del eng
    import gc as _gc
    _gc.collect()
    ids2 = ids[:2]
    for name, rec in (("remat", True), ("no-remat", False), ("flash_mlp", "fm")):
        kw = dict(recompute=True, remat_policy="flash_mlp") if rec == "fm" \
            else dict(recompute=rec)
        cfg2 = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=4096,
            dtype="bfloat16", **kw)
        model2 = LlamaForCausalLM(cfg2)
        eng2 = Engine(model2, mesh=None, lr=1e-4, clip_norm=1.0)
        t = bench(lambda i: eng2.step(i, i), ids2)
        print(f"b2 step {name:9}: {t*1e3:7.1f} ms  "
              f"(mfu {2*seq*fpt/t/V5E_PEAK:.3f})")
        del eng2, model2
        _gc.collect()

    # in-situ flash attention: 16 chained layers fwd+bwd in one jit
    from paddle_tpu.ops.flash_attention import flash_attention

    hd = cfg.head_dim
    q = jax.random.normal(jax.random.PRNGKey(0),
                          (batch, seq, cfg.num_attention_heads, hd),
                          jnp.bfloat16)
    kv = jax.random.normal(jax.random.PRNGKey(1),
                           (batch, seq, cfg.num_key_value_heads, hd),
                           jnp.bfloat16)

    def attn_chain(q, kv):
        def body(c, _):
            o = flash_attention(c, kv, kv, causal=True)
            return o, None
        o, _ = jax.lax.scan(body, q, None, length=cfg.num_hidden_layers)
        return (o.astype(jnp.float32) ** 2).sum()

    g = jax.jit(jax.grad(attn_chain, argnums=(0, 1)))
    t_attn = bench(g, q, kv)
    afl = 3.5 * cfg.num_hidden_layers * 4 * batch * cfg.num_attention_heads \
        * seq * seq * hd / 2
    print(f"16-layer flash fwd+bwd: {t_attn*1e3:7.1f} ms "
          f"({afl/t_attn/1e12:.1f} TF/s, "
          f"{100*afl/V5E_PEAK/t_attn:.1f}% of peak)")
    # share of the training step spent in attention at this rate
    attn_model_flops = tok * 6.0 * cfg.num_hidden_layers * cfg.hidden_size * seq
    print(f"attention share of step @ this rate: "
          f"{100 * (attn_model_flops * 3.5 / 3 / (afl/t_attn)) / t_step:.1f}%")


if __name__ == "__main__":
    main()
