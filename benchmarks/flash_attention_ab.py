"""A/B: in-repo Pallas flash fwd+bwd vs jax library kernel vs XLA recompute."""
import time, functools, os
import jax, jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.flash_attention import (
    flash_attention, _jax_tuned_flash, _xla_reference, _flash, _tuned_block)

def bench(f, *args, iters=20):
    o = jax.block_until_ready(f(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        o = f(*args)
    jax.block_until_ready(o)
    return (time.perf_counter() - t0) / iters

def attn_flops(b, sq, skv, hq, d, causal):
    f = 4 * b * hq * sq * skv * d
    return f // 2 if causal else f

def run(name, b, s, hq, hkv, d, dtype=jnp.bfloat16):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, s, hq, d), dtype)
    k = jax.random.normal(kk, (b, s, hkv, d), dtype)
    v = jax.random.normal(kv, (b, s, hkv, d), dtype)
    fl = attn_flops(b, s, s, hq, d, True)

    def loss_inrepo(q, k, v):
        return (flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2).sum()
    def loss_xla(q, k, v):
        return (_xla_reference(q, k, v, True, d ** -0.5).astype(jnp.float32) ** 2).sum()

    g_inrepo = jax.jit(jax.grad(loss_inrepo, argnums=(0, 1, 2)))
    g_xla = jax.jit(jax.grad(loss_xla, argnums=(0, 1, 2)))
    fwd_inrepo = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))

    # correctness vs xla ref (fp32 inputs to tighten tolerance)
    qf, kf, vf = q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    o1 = np.asarray(jax.jit(lambda q,k,v: flash_attention(q,k,v,causal=True))(qf, kf, vf))
    o2 = np.asarray(_xla_reference(qf, kf, vf, True, d ** -0.5))
    err = np.abs(o1 - o2).max()
    gg1 = jax.jit(jax.grad(lambda q,k,v: (flash_attention(q,k,v,causal=True)**2).sum(), argnums=(0,1,2)))(qf, kf, vf)
    gg2 = jax.jit(jax.grad(lambda q,k,v: (_xla_reference(q,k,v,True,d**-0.5)**2).sum(), argnums=(0,1,2)))(qf, kf, vf)
    gerr = max(np.abs(np.asarray(a)-np.asarray(b2)).max() for a, b2 in zip(gg1, gg2))

    t_fwd = bench(fwd_inrepo, q, k, v)
    t_bwd = bench(g_inrepo, q, k, v)
    t_xla_bwd = bench(g_xla, q, k, v)
    line = (f"{name}: fwd {fl/t_fwd/1e12:.1f} TF/s ({t_fwd*1e3:.2f}ms) | "
            f"fwd+bwd {3.5*fl/t_bwd/1e12:.1f} TF/s ({t_bwd*1e3:.2f}ms) | "
            f"xla-recompute bwd {t_xla_bwd*1e3:.2f}ms | speedup {t_xla_bwd/t_bwd:.2f}x | "
            f"err {err:.2e} gerr {gerr:.2e}")
    print(line, flush=True)

    if hq == hkv:
        os.environ["PADDLE_TPU_FLASH_IMPL"] = "jaxlib"
        try:
            g_lib = jax.jit(jax.grad(lambda q,k,v: (flash_attention(q,k,v,causal=True).astype(jnp.float32)**2).sum(), argnums=(0,1,2)))
            f_lib = jax.jit(lambda q,k,v: flash_attention(q,k,v,causal=True))
            t_lf = bench(f_lib, q, k, v)
            t_lb = bench(g_lib, q, k, v)
            print(f"  jaxlib: fwd {fl/t_lf/1e12:.1f} TF/s ({t_lf*1e3:.2f}ms) | fwd+bwd {3.5*fl/t_lb/1e12:.1f} TF/s ({t_lb*1e3:.2f}ms) | inrepo/lib bwd ratio {t_lb/t_bwd:.2f}", flush=True)
        finally:
            del os.environ["PADDLE_TPU_FLASH_IMPL"]

print("backend:", jax.default_backend(), jax.devices())
run("MHA b4 s2048 h16 d128", 4, 2048, 16, 16, 128)
run("GQA b1 s4096 h32/8 d128", 1, 4096, 32, 8, 128)
run("GQA b2 s4096 h16/4 d128", 2, 4096, 16, 4, 128)
