"""In-process A/B of the north-star llama config: plain CE vs fused chunked CE
vs fused CE + flash_mlp remat. Sequential in ONE process (axon chip throughput
varies wildly across processes; see docs). Each leg frees the previous model.
"""

import gc
import json
import time

import numpy as np


def run(tag, **over):
    import jax

    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=16, num_attention_heads=16,
        num_key_value_heads=4, max_position_embeddings=4096,
        dtype="bfloat16", recompute=True, **over)
    batch, seq, iters = 4, 4096, 8
    model = LlamaForCausalLM(cfg)
    eng = Engine(model, mesh=None, lr=1e-4, clip_norm=1.0)
    rng = np.random.default_rng(0)
    batches = [rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
               for _ in range(iters)]
    loss = eng.step(batches[0], batches[0]); jax.device_get(loss)
    loss = eng.step(batches[0], batches[0]); jax.device_get(loss)
    t0 = time.perf_counter()
    for ids in batches:
        loss = eng.step(ids, ids)
    jax.device_get(loss)
    dt = time.perf_counter() - t0
    tok = batch * seq * iters / dt
    n = cfg.num_params()
    fpt = 6.0 * n + 6.0 * cfg.num_hidden_layers * cfg.hidden_size * seq
    mfu = tok * fpt / 459e12
    print(json.dumps({"tag": tag, "tokens_per_sec": round(tok, 1),
                      "mfu": round(mfu, 4), "loss": round(float(loss), 3)}),
          flush=True)
    del eng, model
    gc.collect()
    return mfu


if __name__ == "__main__":
    import sys

    legs = sys.argv[1:] or ["plain", "fused", "fused_mlp"]
    for leg in legs:
        try:
            if leg == "plain":
                run("plain_ce", fused_ce=False)
            elif leg == "fused":
                run("fused_ce", fused_ce=True)
            elif leg == "fused_mlp":
                run("fused_ce+flash_mlp", fused_ce=True,
                    remat_policy="flash_mlp")
            elif leg == "fused_c512":
                run("fused_ce_chunk512", fused_ce=True, fused_ce_chunk=512)
            elif leg == "fused_c2048":
                run("fused_ce_chunk2048", fused_ce=True, fused_ce_chunk=2048)
        except Exception as e:
            print(json.dumps({"tag": leg, "error": repr(e)}), flush=True)
            gc.collect()
