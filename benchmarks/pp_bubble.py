"""Pipeline bubble measurement (VERDICT r1 #7: 'prove it or build it').

Times the jitted scan+ppermute pipeline (fwd+bwd) on the 8-virtual-device CPU
mesh at pp=4 across microbatch counts, fits the per-tick cost, and checks the
measured step time against the schedule model:

    GPipe ticks = M + p - 1          (stage-sized work per tick)
    VPP ticks   = v*M + p - 1        (chunk-sized work = 1/v stage per tick)

If the measured times match the model, the pipeline's only overhead IS the
fill/drain bubble — no hidden serialization — and the bubble fraction table
in docs/PP_BUBBLE.md follows analytically.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 python benchmarks/pp_bubble.py
"""

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def run(p=4, v=1, hidden=1024, layers=8, mb_size=16, Ms=(4, 8, 16, 32),
        iters=10, schedule="auto", remat=False):
    from paddle_tpu.distributed.auto_parallel.pipeline import pipeline_call

    mesh = Mesh(np.array(jax.devices()[:p]), ("pp",))
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(layers, hidden, hidden)) * 0.05,
                    jnp.float32)
    w = jax.device_put(w, NamedSharding(mesh, P("pp")))

    def block_fn(wl, h):
        return jnp.tanh(h @ wl[0])

    results = {}
    for M in Ms:
        x = jnp.asarray(rng.normal(size=(M * mb_size, hidden)), jnp.float32)

        def loss(w, x):
            out = pipeline_call(block_fn, [w], x, mesh=mesh, n_micro=M,
                                interleave=v, schedule=schedule, remat=remat)
            return (out.astype(jnp.float32) ** 2).mean()

        g = jax.jit(jax.grad(loss))
        jax.block_until_ready(g(w, x))
        t0 = time.perf_counter()
        for _ in range(iters):
            gv = g(w, x)
        jax.block_until_ready(gv)
        dt = (time.perf_counter() - t0) / iters
        # per-microbatch time normalizes away the growing batch
        results[M] = dt / M
        tag = schedule + ("+rm" if remat else "")
        print(f"p={p} v={v} {tag:>7} M={M:3d}: {dt*1e3:8.2f} ms/step  "
              f"{dt/M*1e3:6.2f} ms/microbatch", flush=True)

    # model check: time/M proportional to (vM + p - 1) / (vM)
    M0, M1 = Ms[0], Ms[-1]
    meas_ratio = results[M0] / results[M1]
    model_ratio = ((v * M0 + p - 1) / (v * M0)) / ((v * M1 + p - 1) / (v * M1))
    print(f"p={p} v={v}: measured per-mb ratio M={M0}/M={M1} = {meas_ratio:.3f}, "
          f"schedule model = {model_ratio:.3f}", flush=True)
    return results


if __name__ == "__main__":
    # ZB vs same-v schedule at the VERDICT's comparison points (M=p, M=2p)
    if "--zb" in sys.argv:
        for v in (1, 2):
            run(p=4, v=v, Ms=(4, 8), schedule="auto")
            run(p=4, v=v, Ms=(4, 8), schedule="zb")
    elif "--zb-remat" in sys.argv:
        # memory-constrained regime: both schedules under remat semantics
        for v in (1, 2):
            run(p=4, v=v, Ms=(4, 8), schedule="auto", remat=True)
            run(p=4, v=v, Ms=(4, 8), schedule="zb", remat=True)
    else:
        run(p=4, v=1)
        run(p=4, v=2)
