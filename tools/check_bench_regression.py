"""Bench-regression gate (reference: tools/ci_op_benchmark.sh +
check_op_benchmark_result.py — relative old-vs-new perf comparison).

Compares a fresh bench output (JSON lines from bench.py) against the last
recorded driver result (BENCH_r*.json in the repo root, or an explicit
baseline file). Fails when the primary metric's vs_baseline drops more than
--tolerance (default 5%).

Usage:
    python bench.py > /tmp/bench_now.txt
    python tools/check_bench_regression.py /tmp/bench_now.txt
"""

from __future__ import annotations

import glob
import json
import os
import sys

PRIMARY = "llama_pretrain_tokens_per_sec_per_chip"

# secondary guards, compared only when BOTH sides recorded them (so adding a
# new metric never fails the gate retroactively): name -> (direction,
# tolerance, floor). "lower" = smaller is better; the baseline is clamped to
# at least `floor` before the relative comparison, so metrics that sit near
# zero when healthy (guard_overhead_pct can even be negative noise) don't
# turn the relative gate into a hair trigger.
# - serving_p99_step_latency_ms: measured with request deadlines enabled —
#   pins the resilience hooks (deadline scan, queue bookkeeping) as
#   overhead-neutral on the serving hot path; 2x tolerance guards against
#   accidental O(n)/sync work, not CI jitter.
# - guard_overhead_pct: guarded vs unguarded fused train step
#   (docs/NUMERIC_GUARD.md) — fails only past max(baseline, 5%) * 2, i.e.
#   the health word grew a real host sync or per-tensor transfer.
# - serving_prefix_hit_rate: fraction of prompt tokens served from the
#   radix prefix cache on the repeated-system-prompt workload
#   (docs/SERVING.md) — a drop past 20% means matching/registration broke
#   (e.g. blocks evicted while reusable, or insert stopped firing).
# - serving_prefill_tokens_per_sec: warm-cache prefill throughput — guards
#   the admission path (chunk programs, radix walk, COW) against host-side
#   or recompile regressions; "higher is better", 30% tolerance rides out
#   CI jitter on a sub-second wave.
# - serving_recovery_time_s: supervisor rebuild+replay after a mid-decode
#   engine kill (docs/SERVING.md) — dominated by recompiles on the fresh
#   engine; the 2s floor keeps tiny-model CI noise from hair-triggering,
#   while a real regression (replay doing quadratic journal work, rebuild
#   re-running whole prompts it already delivered) fails past 2x.
# - serving_shed_rate: fraction of an overload wave (half infeasible
#   deadlines) refused at submit — if feasibility shedding breaks the rate
#   collapses toward 0 ("higher" direction catches it).
# - fleet_tokens_per_sec: 3-replica FleetRouter useful tok/s on a mixed
#   wave (docs/SERVING.md fleet section) — replicas share one device, so
#   this guards the fleet-layer overhead (routing, per-replica journals,
#   twin splicing) against growing a per-step host sync or O(requests)
#   scan; 30% tolerance rides out CI jitter.
# - fleet_failover_time_s: journal load + re-admit + replay-to-hwm after a
#   mid-wave replica kill — same posture as serving_recovery_time_s (2s
#   floor, recompile-dominated), fails past 2x when failover starts
#   re-running work it already delivered.
# - serving_p50/p99_time_to_first_token_ms: submit -> first scheduled
#   token over warm serving waves, queue wait included
#   (docs/OBSERVABILITY.md SLO summaries) — 50/100ms floors keep
#   tiny-model CI noise from hair-triggering; past 2x of
#   max(baseline, floor) the admission/prefill path grew real latency.
# - observability_overhead_pct: fully-instrumented (tracing + metrics +
#   live endpoint) vs bare engine on the identical warm wave — same
#   posture as guard_overhead_pct (5% floor): recording must stay
#   host-side, buffered, and off the step path.
# - serving_large_batch_tokens_per_sec: fused mega-step engine at 128
#   slots on a 2x-oversubscribed mixed wave (docs/SERVING.md big-batch
#   section) — the r06+ slot-count-scaling line; 30% tolerance.
# - serving_step_host_share_pct: host-side share of the 128-slot wave
#   (admit + decode dispatch + prefill bookkeeping / wall). Catches host
#   work creeping back onto the fused step path — an O(max_batch) scan or
#   a per-step table upload shows up here first. 5% floor (CPU tiny reads
#   are noisy), fails past 2x of max(baseline, floor).
# - observability_overhead_big_batch_pct: instrumented-vs-bare at 128
#   slots — guards the BATCHED per-step stamps (one recorder lock per
#   decode block); a per-slot lock acquisition regression shows here.
# - serving_slo_attainment_pct: % of finished requests meeting the TTFT
#   target under the open-loop burst replay (docs/OBSERVABILITY.md
#   "Traffic replay & SLO attainment") — a collapse means the serving
#   path grew real latency or started shedding wholesale; 30% relative
#   tolerance rides out CPU wall-clock noise.
# - serving_goodput_tokens_per_sec: tokens/s from SLO-meeting requests
#   only (goodput, not raw throughput — a server in queueing collapse
#   posts throughput with ~0 goodput); "higher", 50% tolerance (wall-
#   clock attainment is the noisiest line in the suite).
# - serving_ttft_p99_under_burst_ms: the queueing tail the open-loop
#   arrivals exist to expose (ROADMAP items 3/5) — 250ms floor + 2x,
#   same posture as the closed-loop TTFT lines.
# - serving_disagg_ttft_p99_under_burst_ms: the same burst schedule served
#   by a 1-prefill+1-decode TieredRouter (docs/SERVING.md "Disaggregated
#   tiers") — the tail the tier split exists to protect: long prompts
#   prefill on their own replica, decode never stalls behind them. Same
#   250ms floor + 2x posture as the unified line.
# - serving_kv_migration_time_s: mean export→splice wall time per migrated
#   chain (codec serialize + crc + scatter + resume-at-position admission).
#   0.5s floor (tiny CPU chains are sub-ms and jittery); past 2x the
#   handoff grew real work — e.g. re-running prefill instead of splicing.
# - serving_migration_under_loss_p99_s: p99 export→splice per migrated
#   chain with a seeded MIGRATE_IN drop + CRC-valid bitflip on the wire
#   and hedged recovery on (docs/SERVING.md "Transport seam",
#   bench_serving_migration_under_loss). The tail is DOMINATED by the
#   5s hedge timeout the dropped frame must wait out, so the floor sits
#   above it (8s) — CPU weather cannot flap the line; past 2x beyond
#   that, hedging stopped bounding the loss path (e.g. the hedge loser
#   wedged the winner, or retries serialized).
SECONDARY = {
    "serving_p99_step_latency_ms": ("lower", 1.0, 0.0),
    "guard_overhead_pct": ("lower", 1.0, 5.0),
    "serving_prefix_hit_rate": ("higher", 0.2, 0.0),
    "serving_prefill_tokens_per_sec": ("higher", 0.3, 0.0),
    "serving_recovery_time_s": ("lower", 1.0, 2.0),
    # elastic mesh degrade (docs/RESILIENCE.md "Elastic serving mesh"):
    # harvest + rebuild at the surviving width + replay-to-hwm after a
    # device.loss fault — same posture as serving_recovery_time_s (2s
    # floor, the reshard is recompile-dominated on the narrower engine);
    # past 2x the degrade path grew real work (e.g. harvesting per
    # replayed request instead of once, or replay re-running delivered
    # prompts)
    "serving_mesh_degrade_time_s": ("lower", 1.0, 2.0),
    "serving_shed_rate": ("higher", 0.5, 0.0),
    "fleet_tokens_per_sec": ("higher", 0.3, 0.0),
    "fleet_failover_time_s": ("lower", 1.0, 2.0),
    # process-per-replica scale-out (inference/procfleet): 2 worker
    # processes vs 1 on the same wave; wide tolerance — the ratio rides
    # host-core availability (CPU weather), the guard only catches a
    # collapse back toward serialized stepping
    "fleet_proc_tokens_per_sec": ("higher", 0.5, 0.0),
    # mesh-sharded serving (docs/SERVING.md "Sharded serving"): the tp2
    # engine line guards collective + shard_map dispatch overhead on CPU
    # hosts (vs_baseline is the ratio vs the unsharded engine, not a
    # speedup claim); the proc arm's mesh=2 scale-out ratio rides
    # host-core weather like its unsharded sibling
    "serving_sharded_tokens_per_sec": ("higher", 0.5, 0.0),
    "fleet_proc_sharded_tokens_per_sec": ("higher", 0.5, 0.0),
    "serving_p50_time_to_first_token_ms": ("lower", 1.0, 50.0),
    "serving_p99_time_to_first_token_ms": ("lower", 1.0, 100.0),
    "observability_overhead_pct": ("lower", 1.0, 5.0),
    "serving_large_batch_tokens_per_sec": ("higher", 0.3, 0.0),
    "serving_step_host_share_pct": ("lower", 1.0, 5.0),
    "observability_overhead_big_batch_pct": ("lower", 1.0, 5.0),
    "serving_slo_attainment_pct": ("higher", 0.3, 0.0),
    "serving_goodput_tokens_per_sec": ("higher", 0.5, 0.0),
    "serving_ttft_p99_under_burst_ms": ("lower", 1.0, 250.0),
    "serving_disagg_ttft_p99_under_burst_ms": ("lower", 1.0, 250.0),
    "serving_kv_migration_time_s": ("lower", 1.0, 0.5),
    "serving_migration_under_loss_p99_s": ("lower", 1.0, 8.0),
    # speculative decode + int8 KV (docs/SERVING.md "Speculative decode" /
    # "int8 KV cache", bench_speculative): spec tok/s is a throughput line
    # like its siblings; the acceptance rate guards the drafter (a rate
    # collapse silently degrades spec to 1-token dispatches with verify
    # overhead); the int8 headroom is near-deterministic geometry (pool
    # bytes ratio) — a drop means the block format grew overhead
    "serving_spec_tokens_per_sec": ("higher", 0.5, 0.0),
    "serving_spec_acceptance_rate": ("higher", 0.3, 0.0),
    "serving_int8_kv_slots_headroom": ("higher", 0.2, 0.0),
    # checkpoint publish-to-serving (docs/RESILIENCE.md "Lifecycle",
    # bench_checkpoint_publish): digest-verify + in-place weight load +
    # rolling hot-swap of a warm fleet — same posture as
    # serving_recovery_time_s (2s floor, the swap is recompile-dominated
    # on fresh engines); past 2x the publish path grew real work, e.g.
    # re-verifying shards per replica or serializing the restarts
    "checkpoint_publish_time_s": ("lower", 1.0, 2.0),
}


def parse_lines(path):
    out = {}
    for line in open(path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" in d:
            out[d["metric"]] = d
    return out


def last_recorded(root):
    files = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    if not files:
        return None, None
    path = files[-1]
    try:
        d = json.load(open(path))
    except json.JSONDecodeError as e:
        print(f"FAIL: baseline {os.path.basename(path)} is not valid JSON "
              f"({e})")
        sys.exit(1)
    if not isinstance(d, dict):
        print(f"FAIL: baseline {os.path.basename(path)} is not a JSON "
              f"object (got {type(d).__name__})")
        sys.exit(1)
    # driver records either the raw line or a {"parsed": {...}} wrapper
    return d.get("parsed", d), path


def require(d, key, where):
    """Readable gate failure instead of a KeyError/TypeError deep in the
    comparison when a recorded BENCH file is missing (or nulls out) a metric
    key — missing and null are rejected identically on both sides."""
    if not isinstance(d, dict) or d.get(key) is None:
        print(f"FAIL: {where} is missing metric key '{key}' — "
              f"re-record the benchmark (bench.py emits it)")
        sys.exit(1)
    return d[key]


def main():
    if len(sys.argv) < 2:
        print(__doc__)
        return 2
    tol = 0.05
    for i, a in enumerate(sys.argv):
        if a == "--tolerance":
            tol = float(sys.argv[i + 1])
    now = parse_lines(sys.argv[1])
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base, base_path = last_recorded(root)
    if base is None:
        print("no recorded BENCH_r*.json baseline — gate passes vacuously")
        return 0
    cur = now.get(PRIMARY)
    if cur is None:
        print(f"FAIL: fresh output has no '{PRIMARY}' line")
        return 1
    where = os.path.basename(base_path)
    prev_vs = require(base, "vs_baseline", f"baseline {where}")
    cur_vs = require(cur, "vs_baseline", "fresh output")
    # the measured CONFIG lives in the unit string ("tokens/s (<config>, ...")
    # — comparing across a config change (e.g. the round-2 switch to the
    # honest seq-4096 GQA shape) is not a regression signal
    def config_of(d):
        u = d.get("unit") or ""  # explicit null unit reads as no config
        return u.split("(", 1)[-1].split(",", 1)[0] if "(" in u else u

    if config_of(base) != config_of(cur):
        print(f"config changed ({config_of(base)!r} -> {config_of(cur)!r}) — "
              "gate passes vacuously; next recorded BENCH becomes the baseline")
        return 0
    if cur_vs < prev_vs * (1.0 - tol):
        print(f"FAIL: {PRIMARY} vs_baseline {cur_vs:.4f} < "
              f"{prev_vs:.4f} * (1 - {tol}) — perf regression")
        return 1
    rc = check_secondary(base, now, root)
    if rc:
        return rc
    print(f"OK: {PRIMARY} vs_baseline {cur_vs:.4f} (baseline {prev_vs:.4f})")
    return 0


def recorded_secondary(root, base):
    """Baselines for SECONDARY metrics, from either shape a driver may
    record: a ``{"secondary": {name: record}}`` dict nested in the primary
    baseline, or a flat per-metric object in any ``BENCH_r*.json`` (newest
    file wins). Unparseable or foreign files are skipped — the primary
    gate's own validation already covers the newest file."""
    out = {}
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            d = json.load(open(path))
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(d, dict):
            d = d.get("parsed", d)
        if isinstance(d, dict) and d.get("metric") in SECONDARY:
            out[d["metric"]] = d
    nested = base.get("secondary") if isinstance(base, dict) else None
    if isinstance(nested, dict):
        out.update({k: v for k, v in nested.items() if isinstance(v, dict)})
    return out


def check_secondary(base, now, root):
    """Guard-rail metrics (SECONDARY), compared only when both a recorded
    baseline and the fresh output carry them — a metric that predates the
    baseline passes vacuously."""
    recorded = recorded_secondary(root, base)
    for name, (direction, tol, floor) in SECONDARY.items():
        prev = recorded.get(name)
        cur = now.get(name)
        if not isinstance(prev, dict) or not isinstance(cur, dict):
            continue
        pv, cv = prev.get("value"), cur.get("value")
        if pv is None or cv is None:
            continue
        ref = max(pv, floor) if direction == "lower" else pv
        worse = (cv > ref * (1.0 + tol) if direction == "lower"
                 else cv < ref * (1.0 - tol))
        if worse:
            print(f"FAIL: secondary {name} {cv:.4g} vs baseline {pv:.4g} "
                  f"(tolerance {tol:.0%} over max(baseline, {floor:g}), "
                  f"{direction} is better)")
            return 1
        print(f"ok: secondary {name} {cv:.4g} (baseline {pv:.4g})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
