"""Shared selftest harness for the tools/ CI gates.

Every gate in this directory (lint_graph, fault_drill, scrape_metrics,
lint_concurrency) speaks the same protocol: run a matrix of cases, print
one ``[ok]``/``[FAIL]`` line per case, print a single pinned summary line,
and exit non-zero iff anything failed — tests/test_ci_gates.py asserts on
the summary strings. This module is that protocol, extracted so the
fourth gate is a consumer, not a fourth copy.

Usage::

    import _selftest
    ROOT = _selftest.bootstrap()          # repo on sys.path, CPU jax env

    h = _selftest.Harness("SCRAPE")
    h.case("inject shape_mismatch", ok, "detected PT-SHAPE-001")
    h.fail_now("metric families missing")         # assertion-style abort
    return h.finish("SELFTEST OK: ...", "SELFTEST FAIL: ...")
"""

from __future__ import annotations

import os
import sys

__all__ = ["repo_root", "bootstrap", "Harness"]


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def bootstrap(jax_cpu: bool = True) -> str:
    """Put the repo root on ``sys.path`` and default the gates' shared
    environment (CPU jax). Returns the root."""
    root = repo_root()
    if root not in sys.path:
        sys.path.insert(0, root)
    if jax_cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    return root


class Harness:
    """Case counter + the two exit styles the gates use: matrix summaries
    (``case``/``finish``) and assertion-style aborts (``fail_now``)."""

    def __init__(self, gate: str = "SELFTEST"):
        self.gate = gate
        self.cases = 0
        self.failures = 0

    def case(self, label: str, ok: bool, info: str = "") -> bool:
        """One matrix entry: prints ``[ok|FAIL] <label>: <info>``."""
        print(f"[{'ok' if ok else 'FAIL'}] {label}: {info}")
        self.cases += 1
        if not ok:
            self.failures += 1
        return ok

    def note(self, msg: str) -> None:
        print(msg)

    def fail_now(self, msg: str) -> "NoReturn":    # noqa: F821
        """Abort the whole gate with a named first failure (exit 1)."""
        print(f"{self.gate} FAIL: {msg}")
        sys.exit(1)

    def finish(self, ok_msg: str, fail_msg: str) -> int:
        """Print the pinned summary line and return the exit code. The
        messages may use ``{failures}`` / ``{cases}`` placeholders."""
        fmt = dict(failures=self.failures, cases=self.cases)
        if self.failures:
            print(fail_msg.format(**fmt))
            return 1
        print(ok_msg.format(**fmt))
        return 0
