"""Replay a captured bad batch in isolation.

``BadBatchRecorder`` (framework/numeric_guard.py) dumps the offending batch
+ step + rng seed + health word to ``<ckpt_dir>/badbatch/step_<n>/`` the
moment the guarded train step flags it. This tool re-runs that exact batch
through a freshly built (guarded) engine and reports whether the anomaly
reproduces — separating data-dependent anomalies (a poisoned batch NaNs any
parameter state) from state-dependent ones (only that optimizer state at
that step spikes).

Usage:
    # rebuild the engine via your builder, optionally restoring the
    # checkpoint ring entry closest to the captured step
    python tools/replay_batch.py CKPT/badbatch/step_00000005 \
        --builder mypkg.train:build_engine [--ckpt CKPT]

    # self-test: poison a batch, capture it, replay it, expect reproduction
    python tools/replay_batch.py --selftest

The builder is ``module.path:callable`` returning an Engine (built with
``guard=GuardPolicy(...)`` so the replay computes the health word). Exit 0
iff the replay reproduces a non-zero health word sharing at least one bit
with the capture.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def load_capture(capture_dir):
    import numpy as np

    with open(os.path.join(capture_dir, "meta.json")) as f:
        meta = json.load(f)
    with np.load(os.path.join(capture_dir, "batch.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    return meta, arrays


def resolve_builder(spec):
    mod, _, fn = spec.partition(":")
    if not fn:
        raise SystemExit(f"--builder must be module.path:callable, got {spec!r}")
    return getattr(importlib.import_module(mod), fn)


def restore_ring_state(engine, ckpt_dir, step):
    """Load the newest ring entry at or before ``step`` into the engine (the
    state the guarded step actually saw), tolerating a ring that has since
    been rolled back or GC'd. Returns the restored step or None."""
    from paddle_tpu.distributed.resilience import ResilientTrainer

    trainer = ResilientTrainer(lambda alive: engine, ckpt_dir, save_every=10**9)
    candidates = [s for s in trainer._recorded_steps() if s <= step]
    if not candidates:
        return None
    from paddle_tpu.distributed.checkpoint import load_state_dict

    sd = engine.state_dict()
    load_state_dict(sd, trainer._step_dir(candidates[-1]))
    engine.set_state_dict(sd)
    return candidates[-1]


def replay(capture_dir, builder, ckpt_dir=None):
    from paddle_tpu.framework.numeric_guard import describe_health

    meta, arrays = load_capture(capture_dir)
    engine = builder()
    if getattr(engine, "guard", None) is None:
        raise SystemExit("builder returned an Engine without guard= — the "
                         "replay needs the health word")
    restored = None
    if ckpt_dir:
        restored = restore_ring_state(engine, ckpt_dir, meta["step"])
    keys = meta.get("arrays") or sorted(arrays)
    engine.step(*[arrays[k] for k in keys])
    word = int(engine.last_health)
    print(f"capture:  step {meta['step']} health {meta['health_word']} "
          f"({'|'.join(meta['bits'])}, {', '.join(meta['codes'])})")
    print(f"replayed: health {word} ({describe_health(word)})"
          + (f" from ring step {restored}" if restored is not None else
             " from fresh init"))
    reproduced = bool(word and (word & meta["health_word"] or word))
    print("REPRODUCED" if reproduced else
          "NOT REPRODUCED (state-dependent anomaly — replay with --ckpt "
          "pointing at the run's ring)")
    return 0 if reproduced else 1


def selftest():
    """Poison a batch, let the guarded step flag it, capture, replay."""
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.framework.numeric_guard import (BadBatchRecorder,
                                                    GuardPolicy)
    from paddle_tpu.nn.layer.layers import Layer

    D = 8

    class Toy(Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(D, D)

        def loss_fn(self, x, y):
            out = self.fc(Tensor(x))
            diff = out._data - y
            return (diff * diff).mean()

    def build():
        paddle.seed(0)
        return Engine(Toy(), None, lr=0.05, clip_norm=None,
                      guard=GuardPolicy(action="skip_step", warmup_steps=2))

    rng = np.random.default_rng(7)
    x = rng.standard_normal((8, D)).astype(np.float32)
    y = rng.standard_normal((8, D)).astype(np.float32)
    x[0, 0] = np.nan                        # the poisoned sample

    eng = build()
    eng.step(x, y)
    word = int(eng.last_health)
    if not word:
        print("SELFTEST FAIL: poisoned batch not flagged")
        return 1
    with tempfile.TemporaryDirectory() as tmp:
        rec = BadBatchRecorder(os.path.join(tmp, "badbatch"))
        d = rec.record(1, word, {"input_ids": x, "labels": y}, rng_seed=7)
        rc = replay(d, build)
    if rc == 0:
        print("SELFTEST OK: captured anomaly reproduced in isolation")
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("capture", nargs="?", help="badbatch/step_<n> directory")
    ap.add_argument("--builder", help="module.path:callable -> guarded Engine")
    ap.add_argument("--ckpt", help="checkpoint ring root (restores the entry "
                                   "nearest the captured step)")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if not args.capture or not args.builder:
        print(__doc__)
        return 2
    return replay(args.capture, resolve_builder(args.builder), args.ckpt)


if __name__ == "__main__":
    sys.exit(main())
