"""End-to-end Llama-2-7B v5p-32 proof WITHOUT v5p hardware (VERDICT r3 #2).

Three artifacts, recorded in docs/LLAMA7B_V5P.md:
  1. auto_tuner mesh selection for Llama-2-7B (hidden 4096, 32 layers, MHA,
     seq 4096) on a 16-chip v5p-32 slice (16 chips x 2 TensorCores), with
     the HBM-fit arithmetic per candidate.
  2. AOT lowering of the FULL hybrid Engine train step (fwd + fused CE loss +
     bwd + global-norm clip + AdamW, remat, real 7B shapes) over a 16-device
     virtual mesh with the selected shardings — proving the 7B program
     traces, shards, and lowers exactly as it would on hardware. Lowering
     needs shapes and shardings only, so params are zero-initialized (the
     StableHLO is identical for any parameter values).
  3. roofline projection of tokens/s/chip + MFU from the tuner's cost model.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=16 python tools/llama7b_proof.py

Reference anchor: test/auto_parallel/hybrid_strategy/semi_auto_llama.py:33
(the reference's 7B-class hybrid-parallel llama test).
"""

import os
import sys
import time

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=16")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def pick_mesh(n_devices=16, global_batch=64):
    from paddle_tpu.distributed.auto_tuner import AutoTuner, TuneConfig

    cfg = TuneConfig(
        n_devices=n_devices,
        num_layers=32, hidden_size=4096, num_heads=32,
        seq_len=4096, global_batch=global_batch,
        vocab_size=32000, ffn_mult=11008 / 4096,
        hbm_gb=95.0, flops_per_chip=459e12,  # v5p
        remat=True, max_pp=8, max_tp=8)
    tuner = AutoTuner(cfg)
    cands = tuner.candidates()
    print(f"feasible candidates: {len(cands)}; top 5 by roofline cost:")
    for c in cands[:5]:
        d = c.details
        print(f"  {c}  t_compute={d['t_compute']*1e3:.1f}ms "
              f"t_comm={d['t_comm']*1e3:.1f}ms bubble={d['bubble']:.3f}")
    best = cands[0]
    n_params = tuner._param_count()
    print(f"\nselected: {best}")
    print(f"params: {n_params/1e9:.2f}B")
    shard = best.axes["fsdp"] * best.axes["tp"] * best.axes["pp"]
    state_gb = n_params * 14 / shard / 1e9
    print(f"HBM fit: params(bf16 2B) + grads(4B) + AdamW m+v(8B) = 14 B/param"
          f" / {shard} shards = {state_gb:.1f} GB/chip of 95 GB")
    tok_s_chip = global_batch * 4096 / best.cost / n_devices
    mfu = tok_s_chip * 6 * n_params / 459e12
    # the roofline is an upper bound (perfect MXU utilization); scale by the
    # MEASURED single-chip matmul efficiency from the v5e north-star line
    # (0.65-0.67 model-MFU, BENCH_r03/r04) for a realistic projection
    eff = 0.65
    print(f"roofline UPPER BOUND: step {best.cost*1e3:.0f} ms -> "
          f"{tok_s_chip:.0f} tok/s/chip, MFU {mfu:.3f}")
    print(f"realistic projection (x{eff} measured single-chip efficiency): "
          f"{tok_s_chip*eff:.0f} tok/s/chip, MFU {mfu*eff:.3f} "
          f"(north-star target >= 0.40)")
    return best, n_params


def lower_7b(best, fast_init=True):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine, axis_rules, make_mesh
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    axes = {k: v for k, v in best.axes.items()}
    mesh = make_mesh(axes)
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=32,
        max_position_embeddings=4096, dtype="bfloat16", recompute=True)

    saved = {}
    if fast_init:
        # zero-init: StableHLO depends on shapes/shardings only; random init
        # of 6.7B params on one CPU core would take ~20 min for nothing
        import paddle_tpu.nn.initializer as ini

        for cls in (ini.Normal, ini.XavierNormal, ini.XavierUniform,
                    ini.KaimingNormal, ini.Uniform):
            saved[cls] = cls.__call__
            cls.__call__ = lambda self, shape, dtype, *a, **k: (
                jax.numpy.zeros(tuple(shape), dtype))
    t0 = time.time()
    try:
        with axis_rules(mesh):
            model = LlamaForCausalLM(cfg)
    finally:
        for cls, fn in saved.items():
            cls.__call__ = fn
    print(f"7B model materialized (zeros) in {time.time()-t0:.1f}s; "
          f"{cfg.num_params()/1e9:.2f}B params")

    def lower_with(mesh, n_micro=None):
        eng = Engine(model, mesh, lr=3e-4, clip_norm=1.0, n_micro=n_micro,
                     abstract_state=True)
        # batch: dp*fsdp shards the batch dim; feed the GLOBAL batch
        ids = jax.ShapeDtypeStruct((64, 4096), jax.numpy.int32)
        t0 = time.time()
        if eng._jit_step is None:
            eng._jit_step = eng._build_step()
        lowered = eng._jit_step.lower(eng.params, eng.m, eng.v,
                                      eng.step_count, ids, ids)
        txt = lowered.as_text()
        dt = time.time() - t0
        counts = {x: txt.count(x) for x in
                  ("all_reduce", "all_gather", "reduce_scatter",
                   "collective_permute", "all_to_all")}
        # NOTE: GSPMD inserts fsdp gathers/tp reductions at COMPILE time;
        # the StableHLO here shows sharding annotations + the explicit
        # collectives (psum grad reductions, pipeline ppermutes)
        print(f"AOT lowering OK in {dt:.1f}s: StableHLO {len(txt)/1e6:.1f} MB,"
              f" mesh {dict(mesh.shape)}, explicit collectives {counts}")
        return lowered

    if "--hybrid" in sys.argv:
        # the full hybrid machinery at 7B shapes: fsdp x tp x pp with
        # microbatched pipeline (the reference's 3D-hybrid shape,
        # semi_auto_llama.py). Separate process from the tuner-selected
        # lowering (each 7B trace holds tens of GB of host RAM), and 8
        # virtual devices, not 16 — resharding 7B arrays across 16
        # single-core CPU "devices" trips XLA's 40s collective-rendezvous
        # timeout; dp is the trivial batch axis and is already proven by
        # the dp8xfsdp2 lowering above.
        print("\nhybrid fsdp2xtp2xpp2 (n_micro=4) lowering:")
        return lower_with(
            make_mesh({"dp": 1, "fsdp": 2, "sep": 1, "tp": 2, "pp": 2}),
            n_micro=4)
    return lower_with(make_mesh(dict(best.axes)),
                      n_micro=best.n_micro if best.axes["pp"] > 1 else None)


if __name__ == "__main__":
    best, n_params = pick_mesh()
    lower_7b(best)
    print("\n7B v5p-32 proof complete.")
