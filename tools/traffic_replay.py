"""Traffic replay gate (docs/OBSERVABILITY.md "Traffic replay & SLO
attainment").

Modes:

- ``--dump schedule.jsonl [--seed N --rate R --duration S --arrival
  poisson|diurnal|burst]`` — generate a seeded open-loop arrival schedule
  (observability/workload.py) and write its canonical byte encoding plus
  print the digest: the replayable artifact (same seed ⇒ byte-identical
  file).
- ``--run [--autoscale/--no-autoscale]`` — in-process demo: replay a
  seeded burst schedule against a tiny fleet on a virtual clock with the
  SLO monitor + autoscaler attached, print the report JSON, and exit 0
  iff the SLO contract held (recovered attainment, or brownout engaged
  at max replicas) — the same judgment the selftest pins.
- ``--selftest`` — CI gate (tests/test_ci_gates.py, beside lint_graph /
  fault_drill / scrape_metrics):

  1. schedule determinism: same seed ⇒ byte-identical encoding, a
     different seed differs;
  2. replay report schema: a tiny 1→3-replica fleet under a seeded burst
     schedule produces a report with the windows/attainment/goodput/
     autoscaler structure intact, the autoscaler takes at least one
     scale action, and the exit judgment passes (attainment recovered
     over the post-control half of the run, or brownout engaged at max
     replicas);
  3. control arm: the SAME schedule with the autoscaler disabled leaves
     attainment below target and flips the exit judgment to 1 — the
     measured difference between the arms is the autoscaler's worth.

Exit code 0 on success, 1 naming the first failed check.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile

import _selftest

ROOT = _selftest.bootstrap()
_H = _selftest.Harness("TRAFFIC REPLAY")

#: the selftest's seeded burst workload: ~3x a single tiny replica's
#: virtual-clock service rate inside bursts, comfortably under three
#: replicas' — so the control arm collapses and the scaled fleet recovers
_SELFTEST_SEED = 17


def _selftest_workload():
    from paddle_tpu.observability import TenantSpec, WorkloadConfig

    return WorkloadConfig(
        seed=_SELFTEST_SEED, duration_s=10.0, rate_rps=5.0,
        arrival="burst", burst_every_s=4.0, burst_len_s=2.0,
        burst_multiplier=8.0, vocab_size=64,
        prompt_mu=2.2, prompt_sigma=0.4, prompt_min=4, prompt_max=16,
        output_mu=1.8, output_sigma=0.4, output_min=4, output_max=12,
        tenants=(TenantSpec("chat", weight=2.0, prefix_len=8),
                 TenantSpec("batch", weight=1.0, prefix_len=0,
                            priority=2)))


def _slo_config():
    from paddle_tpu.observability import SLOConfig

    # virtual-clock targets: dt_s=0.05 per fleet step, so 500 ms of TTFT
    # is ~10 steps of queue+prefill — generous for an unloaded replica,
    # hopeless once the backlog is a few waves deep
    return SLOConfig(ttft_ms=500.0, inter_token_ms=None,
                     queue_wait_ms=None, target_attainment=0.7,
                     window_s=1.0)


def run_replay(fleet_dir: str, autoscale_on: bool, max_replicas: int = 3,
               model=None) -> dict:
    """One full observatory run: seeded burst schedule → open-loop replay
    on a virtual clock → windowed attainment → autoscaler control.
    Deterministic on CPU (single-threaded fleet, virtual timestamps)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.autoscale import (AutoscaleConfig,
                                                SLOAutoscaler)
    from paddle_tpu.inference.fleet import FleetConfig, FleetRouter
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import (ReplayDriver, SLOMonitor,
                                          TraceRecorder, VirtualClock,
                                          generate_schedule)

    if model is None:
        paddle.seed(11)
        model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))

    def build():
        return ContinuousBatchingEngine(
            model, max_batch=2, max_len=32, page_size=8, block_size=2,
            prefix_cache=True)

    clock = VirtualClock()
    tracer = TraceRecorder(clock=clock)
    monitor = SLOMonitor(_slo_config(), tracer=tracer)
    fleet = FleetRouter(build, fleet_dir, num_replicas=1, tracer=tracer,
                        config=FleetConfig(brownout_depth=10 ** 9))
    scaler = SLOAutoscaler(
        fleet, monitor,
        AutoscaleConfig(min_replicas=1, max_replicas=max_replicas,
                        up_after=2, down_after=4, cooldown_windows=1),
        tracer=tracer, enabled=autoscale_on)
    schedule = generate_schedule(_selftest_workload())
    driver = ReplayDriver(fleet, schedule, clock=clock, dt_s=0.05,
                          monitor=monitor, autoscaler=scaler,
                          max_steps=5000)
    try:
        report = driver.run()
    finally:
        fleet.close()
    return report


def validate_report(report: dict) -> None:
    """Schema check: the report a dashboard/driver consumes must carry the
    full observatory structure with sane types."""
    for key in ("driver", "schedule", "slo", "autoscaler"):
        if key not in report:
            _H.fail_now(f"report missing section {key!r}")
    drv = report["driver"]
    for key in ("submitted", "refused", "steps", "windows"):
        if not isinstance(drv.get(key), int):
            _H.fail_now(f"driver.{key} not an int: {drv.get(key)!r}")
    if not isinstance(report["schedule"].get("digest"), str):
        _H.fail_now("schedule.digest missing")
    slo = report["slo"]
    wins = slo.get("windows")
    if not isinstance(wins, list) or not wins:
        _H.fail_now("slo.windows empty")
    for w in wins:
        for key in ("window", "finished", "met", "tokens", "good_tokens"):
            if not isinstance(w.get(key), int):
                _H.fail_now(f"window.{key} not an int: {w.get(key)!r}")
        att = w.get("attainment")
        if att is not None and not (0.0 <= att <= 1.0):
            _H.fail_now(f"window attainment out of range: {att!r}")
        if w["met"] > w["finished"]:
            _H.fail_now("window met > finished")
        if w["good_tokens"] > w["tokens"]:
            _H.fail_now("window good_tokens > tokens")
        sig = w.get("signals", {})
        if "ttft_ms" not in sig:
            _H.fail_now("window signals missing ttft_ms")
    tot = slo.get("totals", {})
    if tot.get("finished", 0) <= 0:
        _H.fail_now("no finished requests in SLO totals")
    asc = report["autoscaler"]
    if not isinstance(asc.get("stats"), dict):
        _H.fail_now("autoscaler.stats missing")
    json.dumps(report)        # must round-trip as plain JSON


def second_half_attainment(report: dict):
    """Attainment over the later half of the run's windows — the
    post-control read the exit judgment uses (the autoscaler cannot fix
    windows that elapsed before it had evidence to act on)."""
    wins = [w for w in report["slo"]["windows"]
            if w["attainment"] is not None]
    if not wins:
        return None
    half = wins[len(wins) // 2:]
    fin = sum(w["finished"] for w in half)
    met = sum(w["met"] for w in half)
    return (met / fin) if fin else None


def report_exit(report: dict) -> int:
    """The SLO contract judgment: 0 when the post-control attainment meets
    the configured target OR the controller engaged brownout at max
    replicas (the last lever — degraded deliberately, not collapsed
    silently); 1 otherwise."""
    target = report["slo"]["config"]["target_attainment"]
    att = second_half_attainment(report)
    if att is not None and att >= target:
        return 0
    asc = report.get("autoscaler") or {}
    if asc.get("stats", {}).get("brownouts", 0) >= 1:
        return 0
    return 1


def selftest() -> int:
    from paddle_tpu.observability import (WorkloadConfig, encode_schedule,
                                          generate_schedule)

    cfg = _selftest_workload()
    enc1 = encode_schedule(generate_schedule(cfg))
    enc2 = encode_schedule(generate_schedule(cfg))
    other = dataclasses.replace(cfg, seed=cfg.seed + 1)
    enc3 = encode_schedule(generate_schedule(other))
    _H.case("schedule determinism", enc1 == enc2 and enc1 != enc3,
            f"{len(enc1)} bytes, same seed identical, "
            "different seed differs")

    with tempfile.TemporaryDirectory() as tmp:
        on = run_replay(os.path.join(tmp, "on"), autoscale_on=True)
        validate_report(on)
        stats = on["autoscaler"]["stats"]
        acted = stats["scale_ups"] + stats["brownouts"] >= 1
        att_on = second_half_attainment(on)
        rc_on = report_exit(on)
        _H.case(
            "autoscaler arm", acted and rc_on == 0,
            f"scale_ups={stats['scale_ups']} brownouts={stats['brownouts']} "
            f"second-half attainment={att_on} exit={rc_on}")

        off = run_replay(os.path.join(tmp, "off"), autoscale_on=False)
        validate_report(off)
        att_off = second_half_attainment(off)
        target = off["slo"]["config"]["target_attainment"]
        rc_off = report_exit(off)
        _H.case(
            "control arm (autoscaler off)",
            rc_off == 1 and att_off is not None and att_off < target,
            f"second-half attainment={att_off} < target={target} "
            f"exit={rc_off}")
        _H.case(
            "same-seed replay reproduces the schedule",
            on["schedule"]["digest"] == off["schedule"]["digest"],
            on["schedule"]["digest"])
    return _H.finish(
        "TRAFFIC REPLAY SELFTEST OK: {cases} checks — schedule "
        "byte-identity, report schema, autoscaler recovery, control-arm "
        "attainment flip",
        "TRAFFIC REPLAY SELFTEST FAIL: {failures}/{cases} checks failed")


def main(argv) -> int:
    if "--selftest" in argv:
        return selftest()

    def opt(name, default=None, cast=str):
        for i, a in enumerate(argv):
            if a == name and i + 1 < len(argv):
                return cast(argv[i + 1])
        return default

    if "--dump" in argv:
        from paddle_tpu.observability import (WorkloadConfig,
                                              encode_schedule,
                                              generate_schedule,
                                              schedule_digest)

        cfg = WorkloadConfig(
            seed=opt("--seed", 0, int),
            duration_s=opt("--duration", 10.0, float),
            rate_rps=opt("--rate", 4.0, float),
            arrival=opt("--arrival", "poisson"))
        sched = generate_schedule(cfg)
        path = opt("--dump")
        with open(path, "wb") as f:
            f.write(encode_schedule(sched))
        print(f"OK: {len(sched)} arrivals -> {path} "
              f"(digest {schedule_digest(sched)})")
        return 0
    if "--run" in argv:
        with tempfile.TemporaryDirectory() as tmp:
            report = run_replay(tmp,
                                autoscale_on="--no-autoscale" not in argv)
        print(json.dumps(report, indent=1))
        rc = report_exit(report)
        print(f"{'OK' if rc == 0 else 'FAIL'}: second-half attainment "
              f"{second_half_attainment(report)} vs target "
              f"{report['slo']['config']['target_attainment']}")
        return rc
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
