"""Collective-communication gate (PT-COMM — docs/STATIC_ANALYSIS.md):
trace every registered mesh-sharded program under a symbolic
``AbstractMesh`` (NO XLA compile, no devices — pure ``make_jaxpr``
through ``static.analysis.trace_to_program``; a compile counter enforces
this and the gate fails if anything compiled) and audit its collective
census against the reviewed baseline (tools/collective_baseline.json).

What PT-COST is for device-program cost, this is for the WIRE: the
registry covers the train-step collective contract at each recorded
MULTICHIP_r01–r05 mesh shape, the ring-attention and MoE dispatch/
combine spmd-rule programs traced at two mesh widths (the mesh-scaling
law), and the single-device serving programs (mega-step, prefill chunk,
spec verify — reusing audit_program_cost's recorders) under an explicit
``unsharded: true`` contract that ROADMAP item 1's sharding PR must
flip together with its sharding change. The audit catches, before any
multi-chip run:

- PT-COMM-001  a large operand entering shard_map fully replicated
               while the mesh shards its siblings
- PT-COMM-002  a loop-invariant collective inside a scan/while body
               (the same bytes re-gathered every step)
- PT-COMM-003  comm bytes growing superlinearly with mesh size across
               a traced width pair
- PT-COMM-004  all_gather feeding a reduce over the gathered dim where
               a reduce_scatter contract moves (n-1)/n of the bytes
- PT-COMM-005  contract drift / unbaselined program / broken unsharded
               contract

Exit 0 iff every error-severity finding is fixed or covered by a
reviewed waiver WITH a justification (the PT-RACE baseline discipline).

Usage:
    JAX_PLATFORMS=cpu python tools/audit_collectives.py     # full gate
    python tools/audit_collectives.py --program mesh_train_step@r01
    python tools/audit_collectives.py --write-baseline      # refresh
    python tools/audit_collectives.py --inject loop_regather
    python tools/audit_collectives.py --selftest            # all 5 classes
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

import _selftest

ROOT = _selftest.bootstrap()

BASELINE_PATH = os.path.join(ROOT, "tools", "collective_baseline.json")

import jax  # noqa: E402
import numpy as np  # noqa: E402

DEFECTS = ("replicated_param", "loop_regather", "superlinear_comm",
           "gather_reduce", "contract_drift", "serving_unsharded")

EXPECTED_CODE = {
    "replicated_param": "PT-COMM-001",
    "loop_regather": "PT-COMM-002",
    "superlinear_comm": "PT-COMM-003",
    "gather_reduce": "PT-COMM-004",
    "contract_drift": "PT-COMM-005",
    "serving_unsharded": "PT-COMM-005",
}

#: the recorded MULTICHIP_r01–r05 dryrun mesh shapes (size-1 axes kept
#: for the record; the contract program drops them)
MULTICHIP_MESHES = {
    "r01": {"dp": 1, "fsdp": 1, "sep": 2, "tp": 2, "pp": 2},   # primary
    "r02": {"dp": 2, "fsdp": 2, "sep": 1, "tp": 1, "pp": 2},   # hybrid
    "r03": {"dp": 4, "fsdp": 1, "sep": 1, "tp": 1, "pp": 2},   # zero-bubble
    "r04": {"ep": 4, "fsdp": 2},                               # MoE
    "r05": {"dp": 2, "tp": 4},                                 # tp4
}

#: mesh widths each scaling family is traced at (PT-COMM-003 law)
SCALING_WIDTHS = (2, 4)

#: per-process count of XLA compiles — must stay 0 for the whole gate
_COMPILES = []


def install_compile_guard():
    """Count backend compiles so 'zero XLA compiles' is enforced, not
    asserted in a docstring. jax-internal hook — if the symbol moves on
    a future jax, the guard degrades to 'untracked' rather than lying."""
    try:
        from jax._src import compiler as _jc
    except Exception:
        return False
    orig = _jc.backend_compile

    def counting(*a, **kw):
        _COMPILES.append(1)
        return orig(*a, **kw)
    _jc.backend_compile = counting
    return True


def _spec(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


# ---------------------------------------------------------------------------
# registry — each recorder returns (Program, CommPathSpec)
# ---------------------------------------------------------------------------

def record_mesh_train_step(key: str):
    """The train-step collective contract at one recorded MULTICHIP mesh
    shape (distributed.auto_parallel.comm_programs.train_step_comm)."""
    from paddle_tpu.distributed.auto_parallel import train_step_comm
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.comm import CommPathSpec

    fn, structs, names, axes = train_step_comm(MULTICHIP_MESHES[key])
    prog = trace_to_program(fn, *structs, input_names=names)
    spec = CommPathSpec(
        f"mesh_train_step@{key}", mesh=axes,
        notes=f"MULTICHIP_{key} dryrun shape {MULTICHIP_MESHES[key]} — "
              "Megatron/FSDP/Ulysses/MoE/pp contract step")
    return prog, spec


def record_tp_train(width: int):
    """The tensor-parallel train step at a tp width (the r05 family) —
    one leg of the mesh-scaling law."""
    from paddle_tpu.distributed.auto_parallel import train_step_comm
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.comm import CommPathSpec

    fn, structs, names, axes = train_step_comm({"dp": 2, "tp": width})
    prog = trace_to_program(fn, *structs, input_names=names)
    spec = CommPathSpec(f"tp_train@{width}", mesh=axes, width=2 * width,
                        notes="dp2 x tp-width Megatron step (r05 family)")
    return prog, spec


def record_flash_ring(width: int):
    """Ring (flash) attention under a sep-axis mesh — the SURVEY
    flash-attention spmd-rule program (ops/ring_attention.py, zigzag
    layout: 2(n-1) ppermutes of the local KV chunk)."""
    from paddle_tpu.ops.ring_attention import ring_attention
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.comm import CommPathSpec, abstract_mesh

    mesh = abstract_mesh({"sep": width})
    sh = _spec((2, 32, 2, 8), "bfloat16")      # [B, S, H, D], S % 2n == 0
    prog = trace_to_program(
        lambda q, k, v: ring_attention(q, k, v, mesh, axis_name="sep"),
        sh, sh, sh, input_names=["q", "k", "v"])
    spec = CommPathSpec(f"flash_ring@{width}", mesh={"sep": width},
                        width=width,
                        notes="zigzag ring attention, causal, bf16")
    return prog, spec


def record_moe_combine(width: int):
    """MoE token dispatch/combine under an ep-axis mesh — the SURVEY
    moe_combine spmd-rule program (two all_to_alls through
    distributed.utils.moe_utils)."""
    from paddle_tpu.distributed.auto_parallel import moe_combine_comm
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.comm import CommPathSpec

    fn, structs, names, axes = moe_combine_comm(width)
    prog = trace_to_program(fn, *structs, input_names=names)
    spec = CommPathSpec(f"moe_combine@{width}", mesh=axes, width=width,
                        notes="global_scatter -> expert FFN -> "
                              "global_gather")
    return prog, spec


@contextlib.contextmanager
def _compile_free_setup():
    """Build the serving recorders' concrete state (weights, KV pools,
    tables) on numpy stand-ins: the auditor only ever reads shapes and
    dtypes off those buffers — their values are dead — and eager jax
    array creation would cost one tiny XLA compile per init op, which
    the zero-compile guard (rightly) fails. Every stub delegates to the
    real function the moment a tracer is involved, so the tracing the
    recorders do under this context is untouched; numpy results inside
    a trace are ordinary constants. Dtypes are canonicalized to jax's
    x32 defaults so the traced programs are bit-identical."""
    import jax.numpy as jnp

    def canon(a):
        fix = {np.dtype(np.int64): np.int32,
               np.dtype(np.float64): np.float32,
               np.dtype(np.uint64): np.uint32}.get(a.dtype)
        return a.astype(fix) if fix else a

    def traced(*vals):
        return any(isinstance(v, jax.core.Tracer) for v in vals)

    targets = {
        (jax.random, "key"), (jax.random, "PRNGKey"),
        (jax.random, "split"), (jax.random, "normal"),
        (jax.random, "uniform"), (jnp, "zeros"), (jnp, "ones"),
        (jnp, "full"), (jnp, "arange"),
    }
    saved = {(mod, name): getattr(mod, name) for mod, name in targets}

    def stub(mod, name, fake):
        orig = saved[(mod, name)]

        def f(*args, **kw):
            if traced(*args, *kw.values()):
                return orig(*args, **kw)
            return fake(*args, **kw)
        setattr(mod, name, f)

    stub(jax.random, "key", lambda seed: np.zeros(2, np.uint32))
    stub(jax.random, "PRNGKey", lambda seed: np.zeros(2, np.uint32))
    stub(jax.random, "split",
         lambda key, num=2: np.zeros((num, 2), np.uint32))
    stub(jax.random, "normal",
         lambda key, shape=(), dtype=np.float32: np.zeros(shape, dtype))
    stub(jax.random, "uniform",
         lambda key, shape=(), dtype=np.float32, minval=0.0, maxval=1.0:
         np.zeros(shape, dtype))
    stub(jnp, "zeros",
         lambda shape, dtype=np.float32, **kw: np.zeros(shape, dtype))
    stub(jnp, "ones",
         lambda shape, dtype=np.float32, **kw: np.ones(shape, dtype))
    stub(jnp, "full",
         lambda shape, v, dtype=None, **kw: canon(np.full(shape, v, dtype)))
    stub(jnp, "arange", lambda *a, **kw: canon(np.arange(*a, **kw)))
    try:
        yield
    finally:
        for (mod, name), orig in saved.items():
            setattr(mod, name, orig)


def record_unsharded(which: str):
    """The single-device serving programs under the EXPLICIT unsharded
    contract. Since the sharding PR flipped the registry to
    :func:`record_sharded`, this recorder exists for the
    ``serving_unsharded`` defect arm: it is exactly what a serving
    program looks like after silently LOSING its sharding, and auditing
    it against the sharded baseline must flip the gate (PT-COMM-005
    ``lost-sharding``)."""
    import audit_program_cost as apc
    from paddle_tpu.static.comm import CommPathSpec

    rec = {"mega_step@8": lambda: apc.record_mega_step(8),
           "spec_verify@8": lambda: apc.record_spec_verify(8),
           "prefill_chunk": apc.record_prefill_chunk}[which]
    with _compile_free_setup():
        prog, cost_spec = rec()
    spec = CommPathSpec(which, unsharded=True,
                        notes="single-device serving program "
                              f"({cost_spec.notes}) — unsharded contract")
    return prog, spec


def record_sharded(which: str, tp: int = 2):
    """The mesh-sharded serving programs, re-recorded from
    audit_program_cost's registry over an ABSTRACT tp mesh (no devices,
    no compiles — docs/SERVING.md "Sharded serving"). Column-parallel
    identity contract: every collective is an all_gather of disjoint
    output shards, so the census must stay psum-free."""
    import audit_program_cost as apc
    from paddle_tpu.static.comm import CommPathSpec

    rec = {"mega_step@8": lambda: apc.record_mega_step(8, mesh=tp),
           "spec_verify@8": lambda: apc.record_spec_verify(8, mesh=tp),
           "prefill_chunk": lambda: apc.record_prefill_chunk(mesh=tp)}[which]
    with _compile_free_setup():
        prog, cost_spec = rec()
    spec = CommPathSpec(which, mesh={"tp": tp}, width=tp,
                        notes=f"tp{tp}-sharded serving program "
                              f"({cost_spec.notes}) — column-parallel, "
                              "all_gather-only by construction")
    return prog, spec


def record_all(only=None):
    out = {}
    for key in MULTICHIP_MESHES:
        out[f"mesh_train_step@{key}"] = lambda k=key: record_mesh_train_step(k)
    for w in SCALING_WIDTHS:
        out[f"tp_train@{w}"] = lambda s=w: record_tp_train(s)
        out[f"flash_ring@{w}"] = lambda s=w: record_flash_ring(s)
        out[f"moe_combine@{w}"] = lambda s=w: record_moe_combine(s)
    for name in ("mega_step@8", "spec_verify@8", "prefill_chunk"):
        out[name] = lambda n=name: record_sharded(n)
    if only:
        if only not in out:
            raise SystemExit(f"unknown program {only!r} "
                             f"(choose: {sorted(out)})")
        out = {only: out[only]}
    return {name: rec() for name, rec in out.items()}


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str = BASELINE_PATH):
    """Returns (programs: {name: manifest dict}, waivers: {id: just}).
    Waiver entries without a justification are rejected — the file is a
    review record, not a mute button (PT-RACE discipline)."""
    if not os.path.exists(path):
        return {}, {}
    with open(path) as f:
        doc = json.load(f)
    waivers = {}
    for entry in doc.get("waivers", ()):
        fid = entry.get("id")
        just = (entry.get("justification") or "").strip()
        if not fid or not just:
            raise SystemExit(
                f"baseline waiver {entry!r} is missing an id or a "
                "justification — every suppression must say why")
        waivers[fid] = just
    return doc.get("programs", {}), waivers


def write_baseline(manifests, waivers, path: str = BASELINE_PATH):
    # `degrade_widths` is a REVIEWED annotation, not a traced fact —
    # CommManifest.to_dict() cannot produce it, so a refresh must carry
    # it over from the prior baseline or the elastic-degrade exemption
    # (docs/RESILIENCE.md "Elastic serving mesh") silently disappears
    prior, _ = load_baseline(path)
    programs = {}
    for k, m in sorted(manifests.items()):
        rec = m.to_dict()
        widths = (prior.get(k) or {}).get("degrade_widths")
        if widths:
            rec["degrade_widths"] = [int(w) for w in widths]
        programs[k] = rec
    doc = {
        "_comment": [
            "PT-COMM manifests + reviewed waivers",
            "(docs/STATIC_ANALYSIS.md, tools/audit_collectives.py).",
            "Counts and wire bytes are CONTRACTS: collectives may only",
            "change through a reviewed refresh. The serving programs",
            "record their tp-sharded collective census (column-parallel,",
            "all_gather-only); a program that silently reverts to",
            "unsharded gates as PT-COMM-005 lost-sharding. Every waiver",
            "needs a justification; stale waivers are reported.",
            "Serving entries may record `degrade_widths`: the narrower",
            "tp widths the elastic PT-SRV-008 reshard path legitimately",
            "serves at — a still-sharded manifest at a recorded degrade",
            "width passes the count/drift/bytes gates (its census scales",
            "with the width); losing sharding entirely still gates as",
            "lost-sharding. Preserved across --write-baseline refreshes.",
        ],
        "programs": programs,
        "waivers": [{"id": fid, "justification": waivers[fid]}
                    for fid in sorted(waivers)],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"baseline written: {path} ({len(manifests)} program(s), "
          f"{len(waivers)} waiver(s))")


# ---------------------------------------------------------------------------
# audit driver (shared by the real gate and the selftest fixtures)
# ---------------------------------------------------------------------------

def audit(programs, base_programs, waivers, skip_contract=False,
          report_stale=True):
    """Audit ``programs`` ({name: (Program, CommPathSpec)}). Returns
    (exit_code, manifests, gate_findings)."""
    from paddle_tpu.static.comm import (check_comm_contract,
                                        check_gather_reduce,
                                        check_loop_invariant_collectives,
                                        check_mesh_scaling,
                                        check_replication,
                                        compute_comm_manifest)

    manifests, specs, findings = {}, {}, []
    for name, (prog, spec) in programs.items():
        man = compute_comm_manifest(prog, name=name, spec=spec)
        manifests[name], specs[name] = man, spec
        findings += check_replication(prog, name)
        findings += check_loop_invariant_collectives(prog, name)
        findings += check_gather_reduce(prog, name)
        if not skip_contract:
            findings += check_comm_contract(man, base_programs.get(name))
    # mesh-scaling law over every family traced at >=2 widths
    groups = {}
    for name, man in manifests.items():
        if man.width and "@" in name:
            groups.setdefault(name.split("@")[0], []).append(man)
    for fam, group in sorted(groups.items()):
        if len(group) >= 2:
            findings += check_mesh_scaling(group)
    gate, suppressed = [], []
    for d in findings:
        fid = getattr(d, "finding_id", None)
        (suppressed if fid in waivers else gate).append(d)
    for name, man in sorted(manifests.items()):
        scal = (man.scaling or {}).get("verdict", "-")
        counts = " ".join(f"{k}:{v}" for k, v in sorted(
            man.collectives.items())) or "none"
        contract = "unsharded" if man.unsharded else (
            "mesh " + "x".join(f"{k}{v}" for k, v in sorted(man.mesh.items()))
            if man.mesh else "unmeshed")
        print(f"[manifest] {name}: {contract}, "
              f"{man.collective_eqns} collective eqn(s) [{counts}], "
              f"{man.comm_bytes:.3g} wire B, "
              f"loop-inv {man.loop_invariant_eqns}, scaling {scal}")
    for d in gate:
        print(f"{d.format()}\n    id: {getattr(d, 'finding_id', '')}")
    for d in suppressed:
        fid = getattr(d, "finding_id", "")
        print(f"[waived] {fid}: {waivers[fid]}")
    if report_stale:
        all_ids = {getattr(d, "finding_id", None) for d in findings}
        for fid in sorted(set(waivers) - all_ids):
            print(f"[stale waiver — remove it] {fid}")
    status = "FINDINGS AT GATE SEVERITY" if gate else "CLEAN"
    print(f"COLLECTIVE COMM AUDIT {'FAIL' if gate else 'OK'}: "
          f"{len(manifests)} program(s), {len(findings)} finding(s), "
          f"{len(suppressed)} waived, {len(gate)} at gate severity — "
          f"{status}")
    return (1 if gate else 0), manifests, gate


# ---------------------------------------------------------------------------
# seeded-defect fixtures (synthetic, tiny — no model builds, no compiles)
# ---------------------------------------------------------------------------

def _fixture(width=2, replicated=False, loop_regather=False,
             quadratic=False, gather_reduce=False, extra_psum=False):
    """One tiny shard_map'd step over an ``x``-axis mesh: a sharded
    weight, a small replicated activation, one row-parallel psum — each
    defect class is one knob away."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.framework.jax_compat import shard_map
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.comm import CommPathSpec, abstract_mesh

    r_shape = (512, 512) if replicated else (8, 8)   # 1 MiB vs 256 B
    perm = [(i, (i + 1) % width) for i in range(width)]

    def step(w, x, r):
        h = x @ w.T                          # [8, 8] partial over x
        h = lax.psum(h, "x")                 # the one contracted psum
        if extra_psum:
            h = lax.psum(h, "x")             # contract drift
        if gather_reduce:
            g = lax.all_gather(x, "x", axis=0, tiled=True)
            h = h + g.sum()                  # reduce over the gathered dim
        if loop_regather:
            def sbody(c, _):                 # w is a scan CONST: the same
                g = lax.all_gather(w, "x", axis=0, tiled=True)  # bytes
                return c + g.sum(), None     # re-gathered every step
            h2, _ = lax.scan(sbody, jnp.float32(0), jnp.arange(4))
            h = h + h2
        if quadratic:
            # an O(width^2) collective count on a width-scaled payload:
            # the "gather the world then ring it around" accident
            xt = jnp.tile(x, (width, 1))
            for _ in range(width * width):
                xt = lax.ppermute(xt, "x", perm)
            h = h + xt.sum()
        return h.sum() + r[0, 0] * jnp.float32(0)

    mesh = abstract_mesh({"x": width})
    fn = shard_map(step, mesh=mesh,
                   in_specs=(P("x", None), P(None, None), P(None, None)),
                   out_specs=P(), check_vma=False)
    prog = trace_to_program(
        fn, _spec((8 * width, 16), np.float32), _spec((8, 16), np.float32),
        _spec(r_shape, np.float32), input_names=["w", "x", "r"])
    spec = CommPathSpec(f"fixture@{width}", mesh={"x": width}, width=width)
    return prog, spec


def _fixture_pair(**kw):
    return {f"fixture@{w}": _fixture(width=w, **kw) for w in (2, 4)}


def _fixture_baseline():
    from paddle_tpu.static.comm import compute_comm_manifest

    base = {}
    for name, (prog, spec) in _fixture_pair().items():
        base[name] = compute_comm_manifest(prog, name=name,
                                           spec=spec).to_dict()
    return base


def inject(defect, base_programs):
    """Programs for one seeded defect class, audited against the CLEAN
    fixture baseline."""
    if defect == "replicated_param":
        return _fixture_pair(replicated=True)
    if defect == "loop_regather":
        return _fixture_pair(loop_regather=True)
    if defect == "superlinear_comm":
        return _fixture_pair(quadratic=True)
    if defect == "gather_reduce":
        return _fixture_pair(gather_reduce=True)
    if defect == "contract_drift":
        return _fixture_pair(extra_psum=True)
    if defect == "serving_unsharded":
        # a serving program that silently LOST its sharding: the engine
        # dispatches the single-device program while the baseline records
        # the tp-sharded all_gather census (audit against _serving_base())
        return {"mega_step@8": record_unsharded("mega_step@8")}
    raise SystemExit(f"unknown defect {defect!r} (choose: {DEFECTS})")


def _serving_base():
    """The REAL sharded mega-step census, recorded as the baseline the
    ``serving_unsharded`` defect arm is audited against — the one defect
    class that needs a production program, not a synthetic fixture."""
    from paddle_tpu.static.comm import compute_comm_manifest

    prog, spec = record_sharded("mega_step@8")
    man = compute_comm_manifest(prog, name="mega_step@8", spec=spec)
    return {"mega_step@8": man.to_dict()}


def selftest():
    """The clean fixture must audit clean against its own baseline; every
    seeded defect class must flip the exit code with its expected code;
    an unbaselined program and the waiver discipline are pinned
    (harness: tools/_selftest.py — asserted in tests/test_ci_gates.py)."""
    h = _selftest.Harness("COMM")
    base = _fixture_baseline()
    rc, _, gate = audit(_fixture_pair(), base, waivers={})
    h.case("clean fixture", rc == 0, f"rc={rc}, {len(gate)} gate finding(s)")
    for defect in DEFECTS:
        want = EXPECTED_CODE[defect]
        b = dict(base, **_serving_base()) \
            if defect == "serving_unsharded" else base
        rc, _, gate = audit(inject(defect, b), b, waivers={})
        hit = [d for d in gate if d.code == want]
        if rc == 1 and hit:
            h.case(f"inject {defect}", True,
                   f"detected {want} — {hit[0].message[:70]}")
        else:
            h.case(f"inject {defect}", False,
                   f"rc={rc}, wanted {want}, gate codes: "
                   f"{sorted({d.code for d in gate})}")
    rc, _, gate = audit(_fixture_pair(), {}, waivers={})
    h.case("unbaselined program flips the gate",
           rc == 1 and any(d.code == "PT-COMM-005" for d in gate),
           f"rc={rc}")
    # waiver discipline end-to-end: a waiver with a justification
    # un-flips exactly its finding; nothing else
    progs = inject("replicated_param", base)
    rc_bad, _, gate = audit(progs, base, waivers={})
    fids = {getattr(d, "finding_id", "") for d in gate}
    rc_ok, _, _ = audit(progs, base,
                        waivers={fid: "selftest" for fid in fids})
    h.case("waiver un-flips the gate", rc_bad == 1 and rc_ok == 0,
           f"rc {rc_bad} -> {rc_ok} with {len(fids)} waiver(s)")
    return h.finish(
        f"COMM SELFTEST OK: {len(DEFECTS)} defect classes detected, "
        "clean fixture audits clean, waiver discipline pinned",
        "COMM SELFTEST FAIL: {failures} expectation(s) violated")


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--program", default=None,
                    help="audit one registered program (default: all)")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show everything; the "
                         "unbaselined-program finding still fires)")
    ap.add_argument("--inject", choices=DEFECTS, default=None,
                    help="audit the synthetic fixture seeded with one "
                         "defect class (must flip the exit code)")
    ap.add_argument("--selftest", action="store_true",
                    help="verify every defect class flips the gate")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record the current manifests as the baseline "
                         "(review the diff!)")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    guarded = install_compile_guard()

    if args.selftest:
        rc = selftest()
    elif args.inject:
        base = _fixture_baseline()
        if args.inject == "serving_unsharded":
            base = dict(base, **_serving_base())
        rc, _, _ = audit(inject(args.inject, base), base, waivers={})
    else:
        base_programs, waivers = ({}, {}) if args.no_baseline \
            else load_baseline(args.baseline)
        programs = record_all(only=args.program)
        rc, manifests, gate = audit(programs, base_programs, waivers,
                                    skip_contract=args.write_baseline,
                                    report_stale=args.program is None)
        if args.write_baseline:
            if args.program:
                raise SystemExit("--write-baseline needs the full set")
            write_baseline(manifests, waivers, args.baseline)

    compiles = len(_COMPILES) if guarded else "untracked"
    print(f"xla_compiles={compiles} elapsed={time.monotonic() - t0:.1f}s")
    if guarded and _COMPILES:
        print("COLLECTIVE COMM AUDIT FAIL: the gate triggered an XLA "
              "compile — the auditor must stay pure tracing")
        return 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
