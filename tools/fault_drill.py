"""Fault drill: prove every recovery path by injecting its fault.

Each drill runs a small end-to-end scenario twice: with its recovery path
enabled (the injected fault must be absorbed) and with it disabled (the
same fault must flip the exit code). ``--selftest`` runs the whole seeded
matrix — heartbeat loss, store stall, checkpoint shard corruption, serving
engine saturation, serving deadline, prefix-cache block-pool exhaustion,
128-slot fused big-batch saturation (docs/SERVING.md), speculative-decode
divergence (verification disabled — accept-all), the numeric
classes (NaN gradient, loss spike,
poisoned batch — docs/NUMERIC_GUARD.md), a composed multi-site chaos plan
(three subsystems faulted concurrently off ONE seed), and the full
checkpoint-lifecycle arc (train → async checkpoint → elastic shrink →
resume → publish-to-serving, docs/RESILIENCE.md) — and exits
0 iff every fault class recovers when enabled AND fails when its recovery
is off. For the numeric drills "recovery off" means GuardPolicy(action=
"warn"): detection stays on but the anomalous update is applied — exactly
the run an unguarded job would have. Recovery is proven by tests, not
prayer (docs/RESILIENCE.md).

Usage:
    python tools/fault_drill.py --selftest
    python tools/fault_drill.py --drill heartbeat            # expect exit 0
    python tools/fault_drill.py --drill heartbeat --no-recover   # expect != 0

Faults come from seeded, step-indexed FaultPlans
(paddle_tpu/distributed/resilience/faults.py), so every run injects the
same faults at the same events.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

# pure-Python store daemon so server-side faults (store.daemon stalls) are
# real, not simulated; CPU jax with 8 host devices for the elastic meshes
os.environ["PT_DISABLE_NATIVE"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")

import _selftest  # noqa: E402

ROOT = _selftest.bootstrap()


# ---------------------------------------------------------------------------
# shared fixtures
# ---------------------------------------------------------------------------

def _toy_model(d=8):
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.nn.layer.layers import Layer

    class Toy(Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(d, d)

        def loss_fn(self, x, y):
            out = self.fc(Tensor(x))
            diff = out._data - y
            return (diff * diff).mean()

    return Toy()


_SERVING = {}


def _serving_model():
    """One tiny llama shared by the serving drills (build once)."""
    if "model" not in _SERVING:
        import paddle_tpu as paddle
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(11)
        cfg = LlamaConfig.tiny(num_hidden_layers=1)
        _SERVING["model"] = (cfg, LlamaForCausalLM(cfg))
    return _SERVING["model"]


# ---------------------------------------------------------------------------
# drill: heartbeat loss -> elastic save/reshard/resume
# ---------------------------------------------------------------------------

def drill_heartbeat(recover: bool):
    """2-node elastic run loses its peer mid-run. Recovery = detect the
    stale heartbeat, checkpoint, rebuild the mesh over the survivor,
    resume at the recorded step; the final loss must match an uninterrupted
    run (deterministic per-step data => replay-exact trajectory)."""
    import numpy as np
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.distributed.communication.store import TCPStore
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.resilience import (FaultPlan, FaultSpec,
                                                   ResilientTrainer)

    D, B, STEPS = 8, 8, 8

    def data_fn(step):
        rng = np.random.default_rng(1000 + step)
        return (rng.standard_normal((B, D)).astype(np.float32),
                rng.standard_normal((B, D)).astype(np.float32))

    def build(alive):
        n = 8 if len(alive) >= 2 else 4
        mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
        paddle.seed(0)
        return Engine(_toy_model(D), mesh, lr=0.05, clip_norm=None)

    with tempfile.TemporaryDirectory() as tmp:
        # uninterrupted reference trajectory (2-node mesh, no faults)
        ref = ResilientTrainer(lambda alive: build(["a", "b"]),
                               os.path.join(tmp, "ref"), elastic=None,
                               save_every=100, async_save=False
                               ).fit(data_fn, STEPS)
        ref_final = ref["losses"][STEPS]

        store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1,
                         timeout=20.0)
        store_b = TCPStore("127.0.0.1", store.port, world_size=1,
                           timeout=20.0)
        plan = FaultPlan(seed=7, specs=[
            FaultSpec("elastic.heartbeat", "kill", at=3, count=-1,
                      match="nodeB")])
        mgr_b = ElasticManager(store_b, "drill", "nodeB",
                               expected=["nodeA", "nodeB"],
                               heartbeat_interval=0.1, ttl=0.45)
        mgr_a = ElasticManager(store, "drill", "nodeA",
                               expected=["nodeA", "nodeB"],
                               heartbeat_interval=0.1, ttl=0.45) \
            if recover else None
        b_stop = threading.Event()

        def node_b_loop():
            i = 0
            while not b_stop.is_set():
                if mgr_b._thread is None or not mgr_b._thread.is_alive():
                    return              # heartbeat killed -> node is dead
                if i >= 3:
                    # deterministic backstop: whatever the thread-scheduling
                    # weather, node B is dead by step 3 — its lease counter
                    # stops advancing and it leaves the per-step barriers,
                    # so A's recovery path MUST engage (wall-clock-only
                    # death made this drill flake under heavy CI load)
                    mgr_b.stop()
                    return
                try:
                    store_b.barrier(f"g2s{i}", world_size=2, timeout=3.0)
                except Exception:
                    return
                i += 1

        def coop_data_fn(step):
            # the job's per-step cross-node sync: a dead peer turns this
            # into a timeout — exactly how peer loss surfaces in real runs
            ws = len(mgr_a.expected) if mgr_a is not None else 2
            if ws > 1:
                store.barrier(f"g2s{step}", world_size=ws, timeout=1.5)
            time.sleep(0.05)
            return data_fn(step)

        plan.install()
        try:
            mgr_b.start()
            if mgr_a is not None:
                mgr_a.start()
            b_thread = threading.Thread(target=node_b_loop, daemon=True)
            b_thread.start()
            trainer = ResilientTrainer(build, os.path.join(tmp, "job"),
                                       elastic=mgr_a, save_every=2)
            try:
                out = trainer.fit(coop_data_fn, STEPS)
            except Exception as e:
                return False, f"run died without recovery: {type(e).__name__}: {e}"
            finally:
                b_stop.set()
                if mgr_a is not None:
                    mgr_a.stop()
                mgr_b.stop()
        finally:
            plan.uninstall()
            store_b.close()
            store.close()
        if out["restarts"] < 1:
            return False, "peer loss never detected (no restart)"
        final = out["losses"][STEPS]
        if not np.allclose(final, ref_final, rtol=1e-3):
            return (False, f"post-resume trajectory diverged: {final} vs "
                    f"uninterrupted {ref_final}")
        return True, (f"peer lost, resumed at step {out['resumed_at']}, "
                      f"final loss {final:.6f} == uninterrupted {ref_final:.6f}")


# ---------------------------------------------------------------------------
# drill: store stall -> retry/timeout/backoff
# ---------------------------------------------------------------------------

def drill_store_stall(recover: bool):
    """The store daemon stalls one op past the client's op deadline.
    Recovery = socket timeout -> reconnect -> retry (PT-RETRY policy);
    without retry the first stalled op raises."""
    from paddle_tpu.distributed.communication.store import TCPStore
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec

    plan = FaultPlan(seed=3, specs=[
        FaultSpec("store.daemon", "stall", at=2, count=1, arg=1.2)])
    prev = os.environ.get("PT_RETRY_DISABLE")
    if not recover:
        os.environ["PT_RETRY_DISABLE"] = "1"
    store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1,
                     timeout=10.0, op_timeout=0.4)
    try:
        with plan:
            for i in range(6):
                store.set(f"k{i}", str(i).encode())
                got = store.get(f"k{i}", wait=False)
                if got != str(i).encode():
                    return False, f"k{i}: got {got!r}"
        stalled = [e for e in plan.log if e[2] == "stall"]
        if not stalled:
            return False, "fault never fired"
        return True, f"rode through daemon stall at {stalled[0][1]!r}"
    except Exception as e:
        return False, f"store op failed: {type(e).__name__}: {e}"
    finally:
        store.close()
        if prev is None:
            os.environ.pop("PT_RETRY_DISABLE", None)
        else:
            os.environ["PT_RETRY_DISABLE"] = prev


# ---------------------------------------------------------------------------
# drill: checkpoint shard corruption -> checksum detect + replica recover
# ---------------------------------------------------------------------------

def drill_shard_corruption(recover: bool):
    """A shard is truncated on disk after its digests were recorded.
    Recovery = load-time verification raises CheckpointCorruptionError
    *naming the shard*, and a replica copy restores the data. With
    verification off the corruption surfaces as an untyped decoder error
    (or silently wrong weights)."""
    import numpy as np

    from paddle_tpu.distributed.checkpoint import (CheckpointCorruptionError,
                                                   load_state_dict,
                                                   save_state_dict)
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec

    w = np.arange(4096, dtype=np.float32)

    def fault():
        return FaultPlan(seed=5, specs=[
            FaultSpec("checkpoint.shard", "truncate", at=0, count=1, arg=64)])

    with tempfile.TemporaryDirectory() as tmp:
        p1 = os.path.join(tmp, "c1")
        with fault():
            save_state_dict({"w": w}, p1)
        target = {"w": np.zeros_like(w)}
        if not recover:
            try:
                load_state_dict(target, p1, verify=False)
            except CheckpointCorruptionError:
                return True, "unexpected: typed error with verification off"
            except Exception as e:
                return (False, "verification off: untyped failure "
                        f"{type(e).__name__} (shard not named)")
            if np.array_equal(np.asarray(target["w"]), w):
                return False, "truncated shard read back clean?!"
            return False, "corrupt shard loaded silently"
        try:
            load_state_dict(target, p1)
            return False, "corruption not detected"
        except CheckpointCorruptionError as e:
            if "0_0.distcp" not in str(e):
                return False, f"bad shard not named: {e}"
            detected = str(e)
        # replica copy -> transparent recovery
        p2 = os.path.join(tmp, "c2")
        with fault():
            save_state_dict({"w": w}, p2, replica=True)
        target2 = {"w": np.zeros_like(w)}
        load_state_dict(target2, p2)
        if not np.array_equal(np.asarray(target2["w"]), w):
            return False, "replica recovery returned wrong data"
        return True, f"detected ({detected.split(':')[0]}), replica recovered"


# ---------------------------------------------------------------------------
# drill: serving engine saturation -> bounded-queue backpressure
# ---------------------------------------------------------------------------

def drill_engine_saturation(recover: bool):
    """Admission flood past the queue high-water mark. Recovery =
    EngineSaturated backpressure keeps the queue bounded while admitted
    requests decode to completion; without it the queue grows unbounded."""
    import numpy as np

    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              EngineSaturated, Request)

    cfg, m = _serving_model()
    eng = ContinuousBatchingEngine(m, max_batch=1, max_len=32, page_size=8,
                                   max_queue=2 if recover else None)
    rng = np.random.default_rng(0)
    reqs = [Request(rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
                    max_new_tokens=2) for _ in range(6)]
    admitted, rejected = [], 0
    for r in reqs:
        try:
            eng.add_request(r)
            admitted.append(r)
        except EngineSaturated:
            rejected += 1
    depth = len(eng._queue)
    eng.run_until_done()
    if rejected == 0:
        return False, f"no backpressure: queue grew to {depth}"
    if depth > 2:
        return False, f"queue exceeded high-water mark: {depth}"
    bad = [r.rid for r in admitted
           if not r.done or r.failed or len(r.tokens) != 2]
    if bad:
        return False, f"admitted requests did not complete: {bad}"
    return True, (f"{rejected} rejected at high-water 2, "
                  f"{len(admitted)} admitted all completed")


# ---------------------------------------------------------------------------
# drill: serving deadline -> eviction, not a hung slot
# ---------------------------------------------------------------------------

def drill_serving_deadline(recover: bool):
    """One slot's request exceeds its deadline mid-decode. Recovery = the
    slot is evicted and the request reported failed while the other slot
    keeps decoding to completion."""
    import numpy as np

    from paddle_tpu.inference.serving import ContinuousBatchingEngine, Request

    cfg, m = _serving_model()
    eng = ContinuousBatchingEngine(m, max_batch=2, max_len=64, page_size=8)
    rng = np.random.default_rng(1)
    fast = Request(rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
                   max_new_tokens=12)
    doomed = Request(rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32),
                     max_new_tokens=30,
                     deadline_s=0.15 if recover else None)
    eng.add_request(fast)
    eng.add_request(doomed)
    eng.step()
    time.sleep(0.2)                     # doomed's deadline expires mid-run
    eng.run_until_done(max_steps=200)
    if not recover:
        if doomed.failed:
            return True, "unexpected: evicted without a deadline"
        return False, ("no deadline: slow request ran to completion "
                       f"({len(doomed.tokens)} tokens), slot hogged")
    if not doomed.failed or not doomed.done:
        return False, "deadline-exceeded request not marked failed"
    if doomed.error is None or "deadline" not in doomed.error:
        return False, f"failure not attributed to deadline: {doomed.error!r}"
    if len(doomed.tokens) >= 30:
        return False, "evicted request decoded to completion anyway"
    if fast.failed or not fast.done or len(fast.tokens) != 12:
        return False, "healthy slot disturbed by the eviction"
    return True, (f"evicted after {len(doomed.tokens)} tokens "
                  f"({doomed.error}); other slot finished 12/12")


# ---------------------------------------------------------------------------
# drill: prefix-cache block-pool exhaustion -> backpressure, not corruption
# ---------------------------------------------------------------------------

def drill_prefix_cache_exhaustion(recover: bool):
    """Seeded KV block-pool exhaustion mid-admission (docs/SERVING.md).

    A request is decoding with its prompt blocks registered in the radix
    prefix cache when the pool is exhausted under a second admission.
    Recovery = the refcounted allocator DEFERS the admission (the queue
    backs up into EngineSaturated) and serves it only once completed
    requests release blocks — both token streams exactly match generate().
    Without recovery (``_unsafe_overcommit``: what a refcount-less
    allocator does) the second request is handed pages the first still
    reads, and the survivor's tokens are silently corrupted."""
    import numpy as np

    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              EngineSaturated, Request)

    cfg, m = _serving_model()

    def ref(prompt, n):
        import paddle_tpu as paddle

        out = m.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                         max_new_tokens=n, temperature=0.0).numpy()[0]
        return [int(t) for t in out]

    rng = np.random.default_rng(5)
    pa = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    pb = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    # pool: 2 slots * 4 pages; each request needs 3 (8 prompt + 16 new).
    # The fault holds 3 free blocks at B's admission -> 2 free + nothing
    # evictable (A holds its blocks) < 3 -> a correct allocator must defer.
    eng = ContinuousBatchingEngine(m, max_batch=2, max_len=32, page_size=8,
                                   block_size=2, prefix_cache=True,
                                   max_queue=1,
                                   _unsafe_overcommit=not recover)
    ra = Request(pa, max_new_tokens=16)
    rb = Request(pb, max_new_tokens=16)
    plan = FaultPlan(seed=9, specs=[
        FaultSpec("serving.block_pool", "exhaust", at=1, count=1, arg=3)])
    saturated = deferred = False
    with plan:
        eng.add_request(ra)
        eng.step()                  # A admitted; prefix registered
        eng.step()
        eng.add_request(rb)
        eng.step()                  # B's allocation hits the exhausted pool
        deferred = rb._n_out == 0 and len(eng._queue) == 1
        if deferred:
            try:
                eng.add_request(Request(pa, max_new_tokens=4))
            except EngineSaturated:
                saturated = True
        eng.run_until_done(max_steps=300)
    if not plan.log:
        return False, "exhaust fault never fired"
    ref_a = ref(pa, 16)
    if not recover:
        if ra.tokens == ref_a:
            return True, ("unexpected: overcommitted pool left shared "
                          "blocks intact")
        return False, ("no refcounted admission: pool overcommit handed "
                       "B pages A still reads — A's tokens corrupted "
                       f"({sum(x != y for x, y in zip(ra.tokens, ref_a))}"
                       f"/{len(ref_a)} wrong)")
    if not deferred:
        return False, "admission not deferred under exhaustion"
    if not saturated:
        return False, "backlog did not surface as EngineSaturated"
    if ra.tokens != ref_a:
        return False, "survivor's tokens corrupted despite refcounting"
    if rb.tokens != ref(pb, 16):
        return False, "deferred request served wrong tokens"
    return True, ("admission deferred at exhaustion, EngineSaturated "
                  "raised, both streams exact after blocks released "
                  f"({eng.stats['evictions']} LRU evictions)")


def drill_big_batch_saturation(recover: bool):
    """Seeded pool exhaustion mid-wave on the 128-slot FUSED engine
    (docs/SERVING.md mega-step section): a 6-request wave is decoding
    through the fused mega-step (device-resident tables, packed prefill)
    when the block pool is exhausted under a late admission.

    Recovery = the refcounted allocator DEFERS the admission (its table
    scatter never reaches the device), the queue backs up into
    EngineSaturated, and once the wave's blocks release the deferred
    request is served — every survivor's stream byte-identical to
    generate(). Without recovery (``_unsafe_overcommit``) the late request
    is handed radix pages live tables still map; its packed prefill then
    overwrites k/v a decoding survivor reads mid-stream — silent
    corruption at 128 slots, exactly what the deferral exists to
    prevent."""
    import numpy as np

    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              EngineSaturated,
                                              PrefixCacheConfig, Request)

    cfg, m = _serving_model()

    def ref(prompt, n):
        import paddle_tpu as paddle

        out = m.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                         max_new_tokens=n, temperature=0.0).numpy()[0]
        return [int(t) for t in out]

    rng = np.random.default_rng(12)
    wave = [rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
            for _ in range(6)]
    pb = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    eng = ContinuousBatchingEngine(
        m, max_batch=128, max_len=40, page_size=8, block_size=4,
        fused=True, prefix_cache=PrefixCacheConfig(prefill_chunk=8),
        _unsafe_overcommit=not recover)
    if not eng._fused:
        return False, "engine did not take the fused mega-step path"
    wave_reqs = [Request(p, max_new_tokens=30) for p in wave]
    rb = Request(pb, max_new_tokens=30)
    # the wave's 6 admissions are block-pool events 0-5; the late
    # request's allocation is event 6 — the hold empties the free list
    # there, and the wave's own blocks are all live (nothing evictable)
    plan = FaultPlan(seed=9, specs=[
        FaultSpec("serving.block_pool", "exhaust", at=6, count=1,
                  arg=10 ** 6)])
    saturated = deferred = False
    with plan:
        for r in wave_reqs:
            eng.add_request(r)
        eng.step()                  # wave admitted + packed prefill (0-5)
        eng.step()                  # mega-step decoding, everyone live
        eng.max_queue = 1           # arm the saturation probe
        eng.add_request(rb)
        eng.step()                  # late allocation (event 6) hits the
        #                             emptied pool — every wave block is
        #                             live (rc >= 1), nothing evictable
        deferred = rb._n_out == 0 and len(eng._queue) == 1
        if deferred:
            try:
                eng.add_request(Request(pb, max_new_tokens=4))
            except EngineSaturated:
                saturated = True
        eng.run_until_done(max_steps=500)
    if not plan.log:
        return False, "exhaust fault never fired"
    refs = [ref(p, 30) for p in wave]
    wrong = [i for i, (r, w) in enumerate(zip(wave_reqs, refs))
             if list(r.tokens) != w]
    if not recover:
        if not wrong:
            return True, ("unexpected: overcommitted 128-slot pool left "
                          "live tables intact")
        return False, ("no refcounted deferral: the late admission stole "
                       f"pages {len(wrong)}/6 decoding survivors still "
                       "read — streams silently corrupted at 128 slots")
    if not deferred:
        return False, "late admission not deferred under exhaustion"
    if not saturated:
        return False, "backlog did not surface as EngineSaturated"
    if wrong:
        return False, (f"survivors {wrong} corrupted despite refcounting")
    if list(rb.tokens) != ref(pb, 30):
        return False, "deferred request served wrong tokens after release"
    return True, ("128-slot fused wave: admission deferred at exhaustion, "
                  "EngineSaturated raised, all 7 streams exact "
                  f"(packed_rows={eng.stats['packed_rows']}, "
                  f"fused_updates={eng.stats['fused_updates']})")


# ---------------------------------------------------------------------------
# numeric drills: health word + GuardPolicy (docs/NUMERIC_GUARD.md)
# ---------------------------------------------------------------------------

def _guarded_fixture(policy):
    """Toy guarded trainer pieces shared by the numeric drills."""
    import numpy as np
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine

    D, B = 8, 8

    def data_fn(step):
        rng = np.random.default_rng(1000 + step)
        return (rng.standard_normal((B, D)).astype(np.float32),
                rng.standard_normal((B, D)).astype(np.float32))

    def build(alive):
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        paddle.seed(0)
        return Engine(_toy_model(D), mesh, lr=0.05, clip_norm=None,
                      guard=policy)

    return build, data_fn


def _numeric_policy(recover, action):
    """Recovery on = the requested policy; recovery off = WARN (detection
    stays armed, the anomalous update is applied — an unguarded run)."""
    from paddle_tpu.framework.numeric_guard import GuardPolicy

    kw = dict(warmup_steps=3, spike_factor=50.0)
    return (GuardPolicy(action=action, **kw) if recover
            else GuardPolicy(action="warn", **kw))


def drill_nan_grad(recover: bool):
    """A NaN gradient at one step. Recovery = the health word (computed
    on-device, one scalar) flags PT-NUM-001, the in-graph zero-apply skips
    the update (step counter advances, optimizer moments untouched), and
    training continues finite. Without recovery the NaN lands in the
    optimizer state and every later loss is NaN."""
    import warnings

    import numpy as np

    from paddle_tpu.distributed.resilience import (FaultPlan, FaultSpec,
                                                   ResilientTrainer)

    build, data_fn = _guarded_fixture(_numeric_policy(recover, "skip_step"))
    plan = FaultPlan(seed=13, specs=[
        FaultSpec("numeric.step", "nan_grad", at=3, count=1)])
    with tempfile.TemporaryDirectory() as tmp:
        trainer = ResilientTrainer(build, tmp, save_every=100,
                                   async_save=False)
        with plan, warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = trainer.fit(data_fn, 8)
    if not plan.log:
        return False, "nan_grad fault never fired"
    final = out["losses"][8]
    if not recover:
        if np.isfinite(final):
            return True, ("unexpected: NaN grads absorbed without the "
                          "skip policy")
        return False, ("no guard action: NaN reached the optimizer state, "
                       f"final loss {final}")
    if out["numeric_skips"] != [4]:
        return False, f"expected skip at step 4, got {out['numeric_skips']}"
    if not np.isfinite(final):
        return False, f"skip failed to protect state: final loss {final}"
    return True, (f"PT-NUM-001 at step 4 skipped in-graph, moments "
                  f"untouched, final loss {final:.6f} finite")


def drill_loss_spike(recover: bool):
    """A 1024x loss spike mid-run. Recovery = the EMA/deviation detector
    flags PT-NUM-004 and the ROLLBACK policy restores the last committed
    ring entry, deterministically re-seeds and replays — the final loss
    must MATCH the uninterrupted seeded run. Without recovery the spiked
    gradients wreck the trajectory."""
    import warnings

    import numpy as np

    from paddle_tpu.distributed.resilience import (FaultPlan, FaultSpec,
                                                   ResilientTrainer)

    build, data_fn = _guarded_fixture(_numeric_policy(True, "rollback"))
    with tempfile.TemporaryDirectory() as tmp:
        ref = ResilientTrainer(build, os.path.join(tmp, "ref"),
                               save_every=100, async_save=False
                               ).fit(data_fn, 8)
        ref_final = ref["losses"][8]

        build2, _ = _guarded_fixture(_numeric_policy(recover, "rollback"))
        plan = FaultPlan(seed=13, specs=[
            FaultSpec("numeric.step", "loss_spike", at=5, count=1)])
        trainer = ResilientTrainer(build2, os.path.join(tmp, "job"),
                                   save_every=2, async_save=False)
        with plan, warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = trainer.fit(data_fn, 8)
        if not plan.log:
            return False, "loss_spike fault never fired"
        final = out["losses"][8]
        if not recover:
            if np.allclose(final, ref_final, rtol=1e-3):
                return True, ("unexpected: 1024x spiked step left the "
                              "trajectory intact")
            return False, (f"no rollback: spiked update applied, final "
                           f"{final:.4f} vs uninterrupted {ref_final:.4f}")
        if out["numeric_rollbacks"] < 1:
            return False, "spike never triggered a rollback"
        if not np.allclose(final, ref_final, rtol=1e-3):
            return False, (f"post-rollback trajectory diverged: {final} vs "
                           f"uninterrupted {ref_final}")
        return True, (f"PT-NUM-004 at step 6, rolled back to "
                      f"{out['rollback_at'][0]}, replay matches "
                      f"uninterrupted ({final:.6f})")


def drill_poison_batch(recover: bool):
    """A seeded NaN-poisoned batch from the data pipeline. Recovery = skip
    the step AND capture the batch to ckpt_dir/badbatch/ where
    tools/replay_batch.py reproduces the anomaly in isolation. Without
    recovery the poisoned batch NaNs the run."""
    import warnings

    import numpy as np

    from paddle_tpu.distributed.resilience import (FaultPlan, FaultSpec,
                                                   ResilientTrainer)

    build, data_fn = _guarded_fixture(_numeric_policy(recover, "skip_step"))
    plan = FaultPlan(seed=5, specs=[
        FaultSpec("data.batch", "poison_batch", at=4, count=1, arg=4)])
    with tempfile.TemporaryDirectory() as tmp:
        trainer = ResilientTrainer(build, tmp, save_every=100,
                                   async_save=False)
        with plan, warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = trainer.fit(data_fn, 8)
        if not plan.log:
            return False, "poison_batch fault never fired"
        final = out["losses"][8]
        if not recover:
            if np.isfinite(final):
                return True, "unexpected: poisoned batch absorbed under warn"
            return False, f"no guard action: poisoned batch NaN'd the run"
        if not np.isfinite(final):
            return False, f"skip failed: final loss {final}"
        if out["numeric_skips"] != [5]:
            return False, f"expected skip at step 5, got {out['numeric_skips']}"
        from paddle_tpu.framework.numeric_guard import BadBatchRecorder

        rec = BadBatchRecorder(os.path.join(tmp, "badbatch"))
        if rec.steps() != [5]:
            return False, f"bad batch not captured: {rec.steps()}"
        meta, arrays = rec.load(5)
        if not np.isnan(arrays["input_ids"]).any() and \
                not np.isnan(arrays["labels"]).any():
            return False, "captured batch carries no NaN"
        return True, (f"poisoned batch skipped at step 5, captured "
                      f"({'|'.join(meta['bits'])}) for replay_batch.py")


# ---------------------------------------------------------------------------
# serving supervisor drills: crash, stall, overload (docs/SERVING.md)
# ---------------------------------------------------------------------------

def _crash_wave():
    """The crash drill wave: a short greedy request whose full-page prompt
    registers in the radix cache, a long seeded sampled request, and a
    repeat of the first prompt — admitted AFTER the first finished, so it
    takes the full-prompt-hit COW path and is mid-decode PAST the
    copy-on-write divergence point when the kill lands. Params only;
    Request objects are built fresh per run."""
    import numpy as np

    cfg, _ = _serving_model()
    rng = np.random.default_rng(17)
    pa = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)   # 1 full page
    pb = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    return [
        dict(prompt_ids=pa, max_new_tokens=4, seed=50),
        dict(prompt_ids=pb, max_new_tokens=12, temperature=0.9, seed=77),
        dict(prompt_ids=pa, max_new_tokens=8, seed=50),           # COW hit
    ]


def _crash_build():
    _, m = _serving_model()
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    return ContinuousBatchingEngine(m, max_batch=2, max_len=32, page_size=8,
                                    block_size=2, prefix_cache=True)


def _crash_refs():
    """Uninterrupted supervisor reference streams (computed once, cached —
    both recovery modes and the stall drill compare against them)."""
    if "crash_refs" not in _SERVING:
        from paddle_tpu.inference.serving import Request, ServingSupervisor

        with tempfile.TemporaryDirectory() as tmp:
            sup = ServingSupervisor(_crash_build,
                                    os.path.join(tmp, "ref.jrnl"))
            reqs = [Request(**kw) for kw in _crash_wave()]
            for r in reqs:
                sup.submit(r)
            sup.run_until_done(max_steps=500)
            sup.close()
        _SERVING["crash_refs"] = [list(r.tokens) for r in reqs]
    return _SERVING["crash_refs"]


def drill_serving_crash(recover: bool):
    """The engine process dies mid-decode (FaultPlan ``serving.step`` kill).
    Recovery = the ServingSupervisor rebuilds a fresh engine (new block
    pool, empty radix cache) and replays every journaled unfinished request
    — token streams BIT-IDENTICAL to the uninterrupted run (greedy, seeded,
    and across the COW divergence point). Without the supervisor's journal
    the crash loses every in-flight request."""
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.serving import Request, ServingSupervisor

    refs = _crash_refs()
    # at=3: the fourth engine step — the seeded request AND the COW-hit
    # repeat are both mid-decode (the repeat already past its COW point)
    plan = FaultPlan(seed=3, specs=[
        FaultSpec("serving.step", "kill", at=3, count=1)])
    with tempfile.TemporaryDirectory() as tmp:
        sup = ServingSupervisor(_crash_build, os.path.join(tmp, "j.jrnl"),
                                max_recoveries=2 if recover else 0)
        reqs = [Request(**kw) for kw in _crash_wave()]
        try:
            with plan:
                for r in reqs:
                    sup.submit(r)
                sup.run_until_done(max_steps=500)
        except Exception as e:
            if recover:
                return False, f"supervisor did not absorb the crash: {e!r}"
            lost = [r.rid for r in reqs if not r.done]
            if not lost:
                return True, "unexpected: crash raised but no request lost"
            return False, (f"no journal/supervisor: engine crash lost "
                           f"{len(lost)} in-flight request(s) {lost}")
        finally:
            sup.close()
        if not plan.log:
            return False, "serving.step kill never fired"
        if not recover:
            return True, "unexpected: crash absorbed without recovery"
        if sup.recoveries < 1:
            return False, "crash never triggered a rebuild"
        streams = [list(r.tokens) for r in reqs]
        if streams != refs:
            bad = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
            return False, (f"recovered stream(s) {bad} diverged from the "
                           "uninterrupted run")
        return True, (f"PT-SRV-001: crash at {plan.log[0][1]}, rebuilt + "
                      f"replayed {sup.stats['replayed_requests']} request(s) "
                      f"in {sup.stats['recovery_s']:.2f}s, all 3 streams "
                      "bit-identical (incl. COW + seeded sampling)")


def _mesh_model():
    """tp=4-capable tiny llama (4 kv heads so both tp=4 and the degraded
    tp=2 divide the head counts) — separate from ``_serving_model`` whose
    2 kv heads cap it at tp=2."""
    if "mesh_model" not in _SERVING:
        import paddle_tpu as paddle
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(11)
        cfg = LlamaConfig.tiny(num_hidden_layers=1, num_key_value_heads=4)
        _SERVING["mesh_model"] = (cfg, LlamaForCausalLM(cfg))
    return _SERVING["mesh_model"]


def _mesh_wave():
    """Greedy full-page prompt + long seeded sampled request — the
    byte-identity claim must survive the reshard in BOTH decode modes."""
    import numpy as np

    cfg, _ = _mesh_model()
    rng = np.random.default_rng(21)
    pa = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    pb = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    return [
        dict(prompt_ids=pa, max_new_tokens=6, seed=40),
        dict(prompt_ids=pb, max_new_tokens=10, temperature=0.9, seed=71),
    ]


def _mesh_build(mesh_tp=4):
    """Width-aware factory: the elastic supervisor rebuilds through it at
    the surviving width (mesh_tp=None = fall back to unsharded)."""
    _, m = _mesh_model()
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              MeshConfig, PrefixCacheConfig)

    mesh = None if mesh_tp is None else MeshConfig(tp=int(mesh_tp))
    return ContinuousBatchingEngine(
        m, max_batch=2, max_len=32, page_size=8, block_size=2, fused=True,
        prefix_cache=PrefixCacheConfig(extra_blocks=4), mesh=mesh)


def _mesh_refs():
    """Uninterrupted tp=4 supervisor reference streams (cached)."""
    if "mesh_refs" not in _SERVING:
        from paddle_tpu.inference.serving import Request, ServingSupervisor

        with tempfile.TemporaryDirectory() as tmp:
            sup = ServingSupervisor(_mesh_build,
                                    os.path.join(tmp, "ref.jrnl"))
            reqs = [Request(**kw) for kw in _mesh_wave()]
            for r in reqs:
                sup.submit(r)
            sup.run_until_done(max_steps=500)
            sup.close()
        _SERVING["mesh_refs"] = [list(r.tokens) for r in reqs]
    return _SERVING["mesh_refs"]


def drill_mesh_device_loss(recover: bool):
    """A tp=4 engine loses 2 of its devices mid-decode (FaultPlan
    ``device.loss`` -> MeshDegraded / PT-SRV-008). Recovery = the elastic
    ServingSupervisor harvests the column shards host-side, rebuilds at
    the widest surviving width (tp=2), re-splits the same bytes, and
    replays every journaled request — streams BIT-IDENTICAL to the
    uninterrupted tp=4 run (greedy + seeded; the column-parallel
    all_gather-only contract makes the widths interchangeable). Without
    the degrade path (elastic=False) the typed signal escapes and every
    in-flight request is lost with the device group."""
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.serving import Request, ServingSupervisor

    refs = _mesh_refs()
    # at=1: the SECOND engine step — step 1 admits + prefills, so the loss
    # lands with both requests mid-decode (the fused engine runs each wave
    # to its next completion event, so the whole drill is only ~3 steps)
    plan = FaultPlan(seed=5, specs=[
        FaultSpec("device.loss", "lose", at=1, count=1, arg=2)])
    with tempfile.TemporaryDirectory() as tmp:
        sup = ServingSupervisor(_mesh_build, os.path.join(tmp, "j.jrnl"),
                                elastic=recover)
        reqs = [Request(**kw) for kw in _mesh_wave()]
        try:
            with plan:
                for r in reqs:
                    sup.submit(r)
                sup.run_until_done(max_steps=500)
        except Exception as e:
            if recover:
                return False, f"supervisor did not absorb the degrade: {e!r}"
            lost = [r.rid for r in reqs if not r.done]
            if not lost:
                return True, "unexpected: degrade raised but no request lost"
            return False, (f"no elastic degrade path: losing 2 devices lost "
                           f"{len(lost)} in-flight request(s) {lost}")
        finally:
            sup.close()
        if not plan.log:
            return False, "device.loss never fired"
        if not recover:
            return True, "unexpected: degrade absorbed with elastic off"
        if sup.stats["mesh_reshards"] < 1:
            return False, "device loss never triggered a reshard"
        tp = (int(sup.engine.mesh.tp)
              if getattr(sup.engine, "mesh", None) is not None else 1)
        if tp != 2:
            return False, (f"expected the widest surviving width tp=2, "
                           f"engine is at tp={tp}")
        streams = [list(r.tokens) for r in reqs]
        if streams != refs:
            bad = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
            return False, (f"resharded stream(s) {bad} diverged from the "
                           "uninterrupted tp=4 run")
        return True, (f"PT-SRV-008: lost 2/4 devices at {plan.log[0][1]}, "
                      f"resharded tp=4->2 + replayed "
                      f"{sup.stats['replayed_requests']} request(s) in "
                      f"{sup.stats['recovery_s']:.2f}s, streams "
                      "bit-identical (greedy + seeded)")


def drill_serving_stall(recover: bool):
    """One engine step hangs (FaultPlan ``serving.stall``). Recovery = the
    threaded StepWatchdog flags PT-SRV-002 while the step is stuck and the
    supervisor rebuilds-from-journal; streams stay bit-identical. Without
    the watchdog the stall silently blows the per-step latency SLO.

    Runs on the legacy (cache-off) engine, WARMED with an identical wave
    first so every armed step reuses compiled programs — a compile-heavy
    step is indistinguishable from a stall, which is exactly why the
    supervisor warms before arming (and graces steps after a rebuild)."""
    import time as _t

    import numpy as np

    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              Request, ServingSupervisor)

    BUDGET, STALL = 0.6, 1.5
    cfg, m = _serving_model()
    rng = np.random.default_rng(29)
    ps = [rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
          for _ in range(2)]

    def build():
        return ContinuousBatchingEngine(m, max_batch=2, max_len=32,
                                        page_size=8, block_size=2)

    def wave(sup):
        reqs = [Request(p, max_new_tokens=8, seed=60 + i)
                for i, p in enumerate(ps)]
        for r in reqs:
            sup.submit(r)
        return reqs

    plan = FaultPlan(seed=4, specs=[
        FaultSpec("serving.stall", "stall", at=2, count=1, arg=STALL)])
    with tempfile.TemporaryDirectory() as tmp:
        sup = ServingSupervisor(build, os.path.join(tmp, "j.jrnl"))
        warm_reqs = wave(sup)              # identical wave: warms every
        sup.run_until_done(max_steps=200)  # program the armed wave will run
        refs = [list(r.tokens) for r in warm_reqs]
        if recover:
            sup.set_step_budget(BUDGET)
        reqs = wave(sup)
        step_s = []
        try:
            import warnings

            with plan, warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                while sup.has_work():
                    t0 = _t.perf_counter()
                    sup.step()
                    step_s.append(_t.perf_counter() - t0)
        finally:
            sup.close()
        if not plan.log:
            return False, "serving.stall never fired"
        streams = [list(r.tokens) for r in reqs]
        if not recover:
            worst = max(step_s)
            if worst <= BUDGET:
                return True, "unexpected: stall absorbed under budget"
            return False, (f"no watchdog: a step silently took {worst:.2f}s "
                           f"(budget {BUDGET}s) — stall undetected, SLO "
                           "violated")
        codes = [c for c, _ in sup.events]
        if "PT-SRV-002" not in codes:
            return False, f"watchdog never flagged the stall (events {codes})"
        if streams != refs:
            return False, "post-rebuild streams diverged"
        return True, (f"PT-SRV-002: stall flagged mid-hang, rebuilt in "
                      f"{sup.stats['recovery_s']:.2f}s, streams bit-identical")


def drill_serving_overload_shed(recover: bool):
    """An infeasible-deadline request arrives while the engine is busy.
    Recovery = deadline-feasibility shedding refuses it AT SUBMIT with a
    typed RequestShed (PT-SRV-003) — before it occupies a slot or queue
    time — and the running requests' streams are byte-identical to a run
    without it. Without shedding it queues, burns its wait, and dies by
    deadline eviction after the fact."""
    import numpy as np

    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              Request, RequestShed)

    cfg, m = _serving_model()
    rng = np.random.default_rng(23)
    ps = [rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
          for _ in range(2)]

    def survivors_wave(e):
        reqs = [Request(p, max_new_tokens=8, seed=100 + i)
                for i, p in enumerate(ps)]
        for r in reqs:
            e.add_request(r)
        return reqs

    def make():
        e = ContinuousBatchingEngine(m, max_batch=2, max_len=32, page_size=8,
                                     block_size=2,
                                     shed_infeasible=recover)
        warm = Request(np.asarray([4, 5], np.int32), max_new_tokens=2)
        e.add_request(warm)
        e.run_until_done()          # compiles + measures the decode rate
        return e

    if "shed_refs" not in _SERVING:
        eng0 = make()
        reqs0 = survivors_wave(eng0)
        eng0.run_until_done(max_steps=300)
        _SERVING["shed_refs"] = [list(r.tokens) for r in reqs0]
    refs = _SERVING["shed_refs"]

    eng = make()
    survivors = survivors_wave(eng)
    eng.step()                       # survivors admitted and decoding
    doomed = Request(ps[0], max_new_tokens=16, deadline_s=1e-3)
    shed = False
    try:
        eng.add_request(doomed)
    except RequestShed:
        shed = True
    eng.run_until_done(max_steps=300)
    streams = [list(r.tokens) for r in survivors]
    if not recover:
        if shed:
            return True, "unexpected: shed fired with shedding disabled"
        if not doomed.failed or "deadline" not in (doomed.error or ""):
            return False, ("no shedding: infeasible request neither shed "
                           "nor deadline-evicted — it just hogged the queue")
        return False, ("no shedding: infeasible request queued and died by "
                       f"deadline eviction after the fact ({doomed.error})")
    if not shed:
        return False, "infeasible request was not shed at submit"
    if doomed._n_out != 0 or doomed.rid in [r.rid for r in eng._queue]:
        return False, "shed request occupied engine state"
    if streams != refs:
        return False, "survivors' streams changed by the shed request"
    return True, (f"PT-SRV-003: infeasible deadline shed at submit "
                  f"({eng.stats['shed']} shed), survivors byte-identical")


def drill_kv_migration_corruption(recover: bool):
    """One migrated KV chain's page bytes are flipped in transit between
    the prefill and decode tiers (FaultPlan ``serving.kv_transfer``
    bitflip — docs/SERVING.md "Disaggregated tiers"). Recovery = the
    codec's per-page crc32 refuses the splice with a typed
    ``KVChainCorrupt`` (PT-SRV-007) and the decode replica re-runs prefill
    from the journaled admit — every stream byte-identical to a
    single-replica run (greedy and seeded). Without verification
    (``KVChainCodec(verify_crc=False)``: what a checksum-less transfer
    does) the corrupt pages are spliced into the decode pool and the
    migrated request's stream silently diverges."""
    import tempfile as _tempfile

    import numpy as np

    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.disagg import KVChainCodec, TieredRouter
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              Request)

    cfg, m = _serving_model()
    rng = np.random.default_rng(61)
    kws = []
    for i in range(4):
        p = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
        kw = dict(prompt_ids=p, max_new_tokens=8, seed=600 + i)
        if i % 2 == 1:
            kw.update(temperature=0.9)
        kws.append(kw)

    def build():
        return ContinuousBatchingEngine(m, max_batch=2, max_len=32,
                                        page_size=8, block_size=2,
                                        prefix_cache=True)

    if "disagg_refs" not in _SERVING:
        eng = build()
        reqs0 = [Request(**kw) for kw in kws]
        for r in reqs0:
            eng.add_request(r)
        eng.run_until_done(max_steps=500)
        _SERVING["disagg_refs"] = [list(r.tokens) for r in reqs0]
    refs = _SERVING["disagg_refs"]

    plan = FaultPlan(seed=3, specs=[
        FaultSpec("serving.kv_transfer", "bitflip", at=0, count=1, arg=256)])
    with _tempfile.TemporaryDirectory() as tmp:
        tiered = TieredRouter(build, build, tmp, num_prefill=1,
                              num_decode=1,
                              codec=KVChainCodec(verify_crc=recover))
        reqs = [Request(**kw) for kw in kws]
        try:
            with plan:
                for r in reqs:
                    tiered.submit(r)
                tiered.run_until_done(max_steps=2000)
        finally:
            tiered.close()
    if not plan.log:
        return False, "serving.kv_transfer bitflip never fired"
    streams = [list(r.tokens) for r in reqs]
    wrong = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
    if not recover:
        if not wrong:
            return True, ("unexpected: 256 flipped bits spliced without "
                          "changing any stream")
        return False, ("no chain verification: corrupt pages spliced into "
                       f"the decode pool — stream(s) {wrong} silently "
                       "diverged from the single-replica run")
    if tiered.stats["migration_corrupt"] < 1:
        return False, "corruption never detected at import"
    codes = [c for c, _ in tiered.events]
    if "PT-SRV-007" not in codes:
        return False, f"no typed PT-SRV-007 rejection (events {codes})"
    if tiered.stats["migration_reprefill"] < 1:
        return False, "decode side never re-ran the corrupted prefill"
    if wrong:
        return False, (f"stream(s) {wrong} diverged despite the re-run "
                       "(recovery broken)")
    # int8 block-format arm: a bitflip in the QUANTIZED page bytes of a
    # PTKV1 chain must still raise the typed PT-SRV-007 (the per-page crc
    # covers the int8 bytes exactly as stored; the dequant scales ride the
    # digest-protected header)
    from paddle_tpu.inference.disagg import KVChainCorrupt, KVChainCodec

    src = ContinuousBatchingEngine(m, max_batch=2, max_len=32, page_size=8,
                                   block_size=2, prefix_cache=True,
                                   kv_cache="int8")
    req8 = Request(rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32),
                   max_new_tokens=16)
    src.add_request(req8)
    src.step()
    codec = KVChainCodec()
    art = codec.export_chain(src, req8.rid)
    flipped = bytearray(art)
    flipped[-5] ^= 0x20                      # a quantized payload byte
    dst = ContinuousBatchingEngine(m, max_batch=2, max_len=32, page_size=8,
                                   block_size=2, prefix_cache=True,
                                   kv_cache="int8")
    try:
        codec.import_chain(dst, bytes(flipped))
        return False, ("int8 chain: flipped quantized byte spliced "
                       "without a PT-SRV-007 rejection")
    except KVChainCorrupt:
        pass
    src.withdraw_active(req8.rid)
    twin = codec.import_chain(dst, art)      # clean splice must still work
    dst.run_until_done(max_steps=200)
    if len(twin.tokens) != 16:
        return False, ("int8 chain: clean splice did not resume decode "
                       f"({len(twin.tokens)}/16 tokens)")
    return True, ("PT-SRV-007: flipped page refused at import (per-page "
                  "crc32), prefill re-run on the decode replica, all "
                  f"{len(reqs)} streams bit-identical "
                  f"({tiered.stats['migrations']} clean migration(s) "
                  "alongside); int8 chain bitflip equally refused and the "
                  "clean int8 splice resumed decode")


def drill_spec_decode_divergence(recover: bool):
    """Speculative multi-token decoding with its in-graph verification
    DISABLED (docs/SERVING.md "Speculative decode"). Recovery = the
    normal draft -> verify -> accept/rollback pipeline: greedy streams are
    byte-identical to the non-speculative mega-step (drafts only change
    how many tokens a dispatch emits, never which), with acceptance > 0 on
    the repetitive workload. Without verification
    (``SpecConfig(_unsafe_accept_all=True)``: what trusting a drafter
    blindly does) every draft is emitted as-is and the greedy streams
    silently diverge — the failure mode the verify program exists to
    prevent."""
    import numpy as np

    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              Request, SpecConfig)

    cfg, m = _serving_model()
    rng = np.random.default_rng(73)
    motif = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)
    prompts = [np.tile(motif, 6),                       # repetitive: the
               np.tile(motif, 6),                       # drafter's food
               rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32),
               rng.integers(0, cfg.vocab_size, (14,)).astype(np.int32)]
    new_toks = [24, 16, 12, 12]

    def wave(eng):
        reqs = [Request(p, max_new_tokens=k)
                for p, k in zip(prompts, new_toks)]
        for r in reqs:
            eng.add_request(r)
        eng.run_until_done(max_steps=800)
        return [list(r.tokens) for r in reqs]

    if "spec_refs" not in _SERVING:
        _SERVING["spec_refs"] = wave(ContinuousBatchingEngine(
            m, max_batch=4, max_len=64, page_size=8, block_size=2,
            fused=True))
    refs = _SERVING["spec_refs"]
    spec = SpecConfig(k=3, _unsafe_accept_all=not recover)
    eng = ContinuousBatchingEngine(m, max_batch=4, max_len=64, page_size=8,
                                   block_size=2, fused=True,
                                   speculative=spec)
    streams = wave(eng)
    wrong = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
    if not recover:
        if not wrong:
            return True, ("unexpected: accept-all emitted every draft yet "
                          "no stream diverged")
        return False, ("verification disabled (accept-all): draft tokens "
                       f"streamed unchecked — stream(s) {wrong} silently "
                       "diverged from the non-speculative mega-step")
    if wrong:
        return False, (f"stream(s) {wrong} diverged WITH verification on "
                       "(greedy byte-identity broken)")
    if eng.stats["spec_accepted"] < 1:
        return False, ("no draft accepted on the repetitive workload — "
                       "the drafter/verify pipeline is not speculating")
    return True, ("greedy streams byte-identical to the non-speculative "
                  f"mega-step with {eng.stats['spec_accepted']}/"
                  f"{eng.stats['spec_proposed']} drafts accepted over "
                  f"{eng.stats['spec_steps']} verify dispatches")


def _fleet_build():
    _, m = _serving_model()
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    return ContinuousBatchingEngine(m, max_batch=2, max_len=32, page_size=8,
                                    block_size=2)


def _fleet_wave_kwargs():
    """Mixed fleet wave: greedy and seeded-sampled requests (params only;
    Request objects are built fresh per run)."""
    import numpy as np

    cfg, _ = _serving_model()
    rng = np.random.default_rng(41)
    kws = []
    for i in range(6):
        p = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
        kw = dict(prompt_ids=p, max_new_tokens=8, seed=200 + i)
        if i % 3 == 2:
            kw.update(temperature=0.9)
        kws.append(kw)
    return kws


def _fleet_refs():
    """Uninterrupted single-engine reference streams — per-request
    determinism means any fleet placement must reproduce them exactly."""
    if "fleet_refs" not in _SERVING:
        from paddle_tpu.inference.serving import Request

        eng = _fleet_build()
        reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
        for r in reqs:
            eng.add_request(r)
        eng.run_until_done(max_steps=500)
        _SERVING["fleet_refs"] = [list(r.tokens) for r in reqs]
    return _SERVING["fleet_refs"]


def drill_fleet_replica_kill(recover: bool):
    """One of three replicas dies mid-traffic (FaultPlan
    ``fleet.replica_kill``). Recovery = the FleetRouter reads the dead
    replica's ON-DISK journal, re-admits its unfinished requests on
    survivors and catches them up to the delivered high-water marks —
    every stream byte-identical to an uninterrupted run (PT-FLT-001).
    Without failover the dead replica's in-flight requests are lost."""
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.fleet import FleetRouter
    from paddle_tpu.inference.serving import Request

    refs = _fleet_refs()
    plan = FaultPlan(seed=5, specs=[
        FaultSpec("fleet.replica_kill", "kill", at=2, count=1,
                  match="replica:0:")])
    with tempfile.TemporaryDirectory() as tmp:
        fleet = FleetRouter(_fleet_build, tmp, num_replicas=3,
                            failover=recover)
        reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
        try:
            with plan:
                for r in reqs:
                    fleet.submit(r)
                fleet.run_until_done(max_steps=500)
        finally:
            fleet.close()
    if not plan.log:
        return False, "fleet.replica_kill never fired"
    if fleet.stats["replica_deaths"] != 1:
        return False, (f"expected exactly one replica death, saw "
                       f"{fleet.stats['replica_deaths']}")
    lost = [r.rid for r in reqs if r.failed or not r.done]
    if not recover:
        if not lost:
            return True, "unexpected: replica death lost nothing"
        return False, (f"no failover: replica 0 died and lost {len(lost)} "
                       f"in-flight request(s) {lost}")
    if lost:
        return False, f"failover left request(s) {lost} failed/unfinished"
    streams = [list(r.tokens) for r in reqs]
    if streams != refs:
        bad = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
        return False, (f"failed-over stream(s) {bad} diverged from the "
                       "uninterrupted run")
    return True, (f"PT-FLT-001: replica 0 killed mid-traffic, "
                  f"{fleet.stats['failover_requests']} journaled request(s) "
                  f"re-admitted on survivors in "
                  f"{fleet.stats['failover_s']:.2f}s, all "
                  f"{len(reqs)} streams bit-identical (greedy + seeded)")


def drill_fleet_proc_kill(recover: bool):
    """One of two replica WORKER PROCESSES takes a real SIGKILL mid-decode
    (the ``fleet.proc_kill`` site fires inside the driver-side proxy,
    which kills the actual pid — inference/procfleet). Recovery = the
    router reads the dead PROCESS's on-disk journal, re-admits its
    unfinished requests on the surviving worker process and catches them
    up to the delivered high-water marks — every stream byte-identical to
    an uninterrupted run (PT-FLT-001 over the PT-PROC transport). Without
    failover the dead process's in-flight requests are lost."""
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.procfleet import (ProcFleetConfig,
                                                ProcFleetRouter)
    from paddle_tpu.inference.serving import Request

    refs = _fleet_refs()
    plan = FaultPlan(seed=5, specs=[
        FaultSpec("fleet.proc_kill", "kill", at=2, count=1,
                  match="replica:0:")])
    # the worker factory rebuilds the drill's model in the child with the
    # SAME seed (_serving_model seeds 11): byte-identity across processes
    # needs bit-identical weights per replica
    proc = ProcFleetConfig(
        factory="paddle_tpu.inference.procfleet.presets:tiny_llama_engine",
        factory_kwargs={"seed": 11}, env={"JAX_PLATFORMS": "cpu"})
    with tempfile.TemporaryDirectory() as tmp:
        fleet = ProcFleetRouter(proc, tmp, num_replicas=2,
                                failover=recover)
        pid0 = fleet.replicas[0].sup.worker_pid
        reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
        try:
            with plan:
                for r in reqs:
                    fleet.submit(r)
                fleet.run_until_done(max_steps=500)
        finally:
            fleet.close()
    if not plan.log:
        return False, "fleet.proc_kill never fired"
    try:
        os.kill(pid0, 0)
        return False, f"worker pid {pid0} survived its SIGKILL"
    except ProcessLookupError:
        pass
    if fleet.stats["replica_deaths"] != 1:
        return False, (f"expected exactly one process death, saw "
                       f"{fleet.stats['replica_deaths']}")
    lost = [r.rid for r in reqs if r.failed or not r.done]
    if not recover:
        if not lost:
            return True, "unexpected: process death lost nothing"
        return False, (f"no failover: worker process 0 was SIGKILL'd and "
                       f"lost {len(lost)} in-flight request(s) {lost}")
    if lost:
        return False, f"failover left request(s) {lost} failed/unfinished"
    streams = [list(r.tokens) for r in reqs]
    if streams != refs:
        bad = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
        return False, (f"failed-over stream(s) {bad} diverged from the "
                       "uninterrupted run")
    return True, (f"PT-PROC/PT-FLT-001: worker process {pid0} SIGKILL'd "
                  f"mid-decode, {fleet.stats['failover_requests']} "
                  "journaled request(s) re-admitted on the surviving "
                  f"process in {fleet.stats['failover_s']:.2f}s, all "
                  f"{len(reqs)} streams bit-identical (greedy + seeded)")


# ---------------------------------------------------------------------------
# drills: the transport seam — flaky wire under KV migration, slow peer
# ---------------------------------------------------------------------------

def _net_cfg(factory="tiny_llama_engine", fkw=None, **kw):
    """Loopback-transport fleet config for the net.* drills (workers are
    threads in THIS process — the chaos plan and the drill share one
    interpreter, and there is no process spawn in the latency budget)."""
    from paddle_tpu.inference.procfleet import ProcFleetConfig

    return ProcFleetConfig(
        factory=f"paddle_tpu.inference.procfleet.presets:{factory}",
        factory_kwargs={"seed": 11, **(fkw or {})},
        transport="loopback", **kw)


def _net_flat_refs():
    """Fault-free loopback FLAT fleet run (cached). Doubles as the jit
    warmup for the armed runs — loopback workers compile in this very
    process, and a cold compile under a tight chaos op-timeout would
    read as a wedged peer — and pins the loopback placement
    byte-identical to the single-engine reference streams."""
    if "net_flat" not in _SERVING:
        from paddle_tpu.inference.procfleet import ProcFleetRouter
        from paddle_tpu.inference.serving import Request

        refs = _fleet_refs()
        with tempfile.TemporaryDirectory() as tmp:
            fleet = ProcFleetRouter(_net_cfg(), tmp, num_replicas=2)
            reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
            try:
                for r in reqs:
                    fleet.submit(r)
                fleet.run_until_done(max_steps=500)
            finally:
                fleet.close()
        streams = [list(r.tokens) for r in reqs]
        if any(r.failed or not r.done for r in reqs) or streams != refs:
            raise RuntimeError("clean loopback fleet run did not reproduce "
                               "the reference streams")
        _SERVING["net_flat"] = refs
    return _SERVING["net_flat"]


def _net_tiered_refs():
    """Fault-free loopback TIERED run (cached): warms the prefill ->
    decode migration path (export/import/splice programs) on top of the
    flat warmup and pins it byte-identical to the same reference."""
    if "net_tiered" not in _SERVING:
        from paddle_tpu.inference.procfleet import ProcTieredRouter
        from paddle_tpu.inference.serving import Request

        refs = _net_flat_refs()
        with tempfile.TemporaryDirectory() as tmp:
            tiered = ProcTieredRouter(
                _net_cfg("tiny_llama_prefix_engine"),
                _net_cfg("tiny_llama_prefix_engine"),
                tmp, num_prefill=1, num_decode=2)
            reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
            try:
                for r in reqs:
                    tiered.submit(r)
                tiered.run_until_done(max_steps=500)
            finally:
                tiered.close()
        streams = [list(r.tokens) for r in reqs]
        if any(r.failed or not r.done for r in reqs) or streams != refs:
            raise RuntimeError("clean tiered loopback run did not reproduce "
                               "the reference streams")
        if tiered.stats["migrations"] < 1:
            raise RuntimeError("clean tiered run never migrated")
        _SERVING["net_tiered"] = refs
    return _SERVING["net_tiered"]


def drill_net_flaky_migration(recover: bool):
    """The wire goes flaky exactly under KV migration: a seeded plan
    DROPS one MIGRATE_IN frame outright and BITFLIPS the KV payload of
    another on ``net.send`` (the chaos transport re-frames after the
    flip, so the frame CRC is VALID over the damaged bytes — only the
    end-to-end per-page chain crc32 can catch it). Recovery = the
    transport seam absorbs both: the dropped splice times out CLEANLY
    (peer alive — no kill) and is hedged onto the next-least-loaded
    decode replica under a stable idempotence key, the bitflipped one is
    refused typed (KVChainCorrupt -> retry elsewhere / reprefill
    fallback) — every stream byte-identical to the fault-free run. The
    control arm is a checksum-less transport (``verify_crc=False``)
    with hedging off: the damaged pages splice silently and the
    migrated streams diverge."""
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.procfleet import ProcTieredRouter
    from paddle_tpu.inference.serving import Request

    refs = _net_tiered_refs()
    plan = FaultPlan(seed=7, specs=[
        FaultSpec("net.send", "drop", at=0, count=1, match="MIGRATE_IN"),
        FaultSpec("net.send", "bitflip", at=1, count=1, arg=64,
                  match="MIGRATE_IN")])

    def cfg():
        return _net_cfg("tiny_llama_prefix_engine", chaos=True,
                        op_timeout_s=5.0, hedge=recover,
                        verify_crc=recover)

    with tempfile.TemporaryDirectory() as tmp:
        tiered = ProcTieredRouter(cfg(), cfg(), tmp,
                                  num_prefill=1, num_decode=2)
        reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
        try:
            with plan:
                for r in reqs:
                    tiered.submit(r)
                tiered.run_until_done(max_steps=800)
        finally:
            tiered.close()
    fired = sorted({a for (_, _, a) in plan.log})
    if "drop" not in fired or "bitflip" not in fired:
        return False, f"net.send faults never fully fired (fired: {fired})"
    lost = [r.rid for r in reqs if r.failed or not r.done]
    streams = [list(r.tokens) for r in reqs]
    wrong = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
    s = tiered.stats
    if not recover:
        if s["migration_corrupt"]:
            return False, ("control arm still detected the flip "
                           "(verify_crc=False was not honored)")
        if lost:
            return False, (f"control arm lost request(s) {lost} — "
                           "expected SILENT corruption, not failure")
        if not wrong:
            return True, ("unexpected: checksum-less splice of flipped KV "
                          "pages changed no stream")
        return False, ("no chain verify + no hedging: damaged migration "
                       f"bytes spliced silently — stream(s) {wrong} "
                       "diverged from the fault-free run")
    if lost:
        return False, f"request(s) {lost} failed/unfinished under net faults"
    retries = sum(getattr(rep.sup, "transport_retries", 0)
                  for rep in tiered.replicas)
    recovered = s["migration_hedges"] + s["migration_corrupt"] + retries
    if recovered < 1:
        return False, (f"faults fired but no transport recovery engaged "
                       f"(stats {s})")
    if wrong:
        return False, (f"stream(s) {wrong} diverged despite typed refusal "
                       "+ hedged re-splice")
    return True, ("dropped + bitflipped MIGRATE_IN absorbed: "
                  f"{s['migration_hedges']} hedge(s), "
                  f"{s['migration_corrupt']} typed refusal(s), "
                  f"{s['migration_reprefill']} reprefill(s), "
                  f"{retries} clean timeout retry(s) — all {len(reqs)} "
                  "streams byte-identical to the fault-free run")


def drill_net_slow_peer(recover: bool):
    """One replica's wire turns SLOW-but-alive: a seeded plan stalls its
    next few replies (``net.recv`` stall — latency, not death; every
    reply still arrives, so kill-detection must NOT fire). Recovery =
    the per-peer circuit breaker: the first stalled reply blows the
    latency-EMA budget and trips CLOSED -> OPEN, the driver routes
    around the peer (typed BreakerOpen: submits fall through to
    survivors, step ticks are skipped) while HALF_OPEN probes riding the
    heartbeat re-test it off the driver path; once the weather passes a
    fast probe closes the breaker and the peer's streams finish —
    driver steps stay inside the latency budget and every stream is
    byte-identical. The control arm has no breaker: every stalled reply
    is eaten inline and driver step latency blows past the budget —
    the fleet-wide tail-latency incident the breaker exists to
    contain."""
    import time

    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.procfleet import ProcFleetRouter
    from paddle_tpu.inference.serving import Request

    refs = _net_flat_refs()
    # loopback workers + heartbeats + driver share one interpreter, and
    # GIL/dispatch contention makes even fault-free ops take ~1-2s here:
    # the stall must TOWER over that baseline or the drill measures noise
    budget_s, stall_s = 3.0, 4.0
    plan = FaultPlan(seed=9, specs=[
        FaultSpec("net.recv", "stall", at=0, count=3, arg=stall_s,
                  match="replica:0@")])
    kw = dict(chaos=True, op_timeout_s=10.0)
    if recover:
        kw.update(heartbeat_s=0.5,
                  breaker={"fail_threshold": 99, "latency_s": 2.5,
                           "cooldown_s": 1.0, "ema_alpha": 1.0})
    with tempfile.TemporaryDirectory() as tmp:
        # max_batch=1: exactly one prefill and one decode program shape,
        # so every compile lands in the pre-roll — a mid-measurement
        # batch-shape recompile would read as a stalled driver step
        # (byte-identity is batch-invariant, so the refs still hold)
        fleet = ProcFleetRouter(_net_cfg(fkw={"max_batch": 1}, **kw), tmp,
                                num_replicas=2)
        rep0 = fleet.replicas[0].sup
        reqs = [Request(**wkw) for wkw in _fleet_wave_kwargs()]
        slow, worst = 0, 0.0
        try:
            for r in reqs:
                fleet.submit(r)
            # un-measured pre-roll: each armed fleet builds FRESH engines,
            # and their first steps pay jit compile (seconds) — latency the
            # drill must not confuse with the injected stalls. Roll until
            # EVERY replica's streams are advancing (compiles done on both
            # — a compile-slow step legitimately trips the breaker, which
            # then hides the un-compiled peer from the driver) and the
            # breaker has closed again.
            deadline = time.monotonic() + 120.0
            prev = [None] * len(fleet.replicas)
            adv = [0] * len(fleet.replicas)
            sampled = [r for r, wkw in zip(reqs, _fleet_wave_kwargs())
                       if wkw.get("temperature")]
            while time.monotonic() < deadline:
                fleet.step()
                # throttle: loopback workers and heartbeat probes share
                # this interpreter — a hot driver spin starves them on the
                # GIL and inflates EVERY op into breaker-budget territory,
                # burying the injected stalls in noise
                time.sleep(0.005)
                for i, rep in enumerate(fleet.replicas):
                    sig = rep.sup.progress()
                    if sig != prev[i]:
                        prev[i] = sig
                        adv[i] += 1
                # the sampled-decode program is a SECOND shape that only
                # compiles once a temperature>0 request reaches decode —
                # the pre-roll must cover it too
                if (min(adv) >= 4
                        and all(len(r.tokens) >= 1 for r in sampled)
                        and (not recover
                             or rep0.breaker_state() == "closed")):
                    break
            trips0 = rep0._breaker.trips if recover else 0
            with plan:
                while (any(not (r.done or r.failed) for r in reqs)
                       and time.monotonic() < deadline):
                    t0 = time.perf_counter()
                    fleet.step()
                    dt = time.perf_counter() - t0
                    worst = max(worst, dt)
                    slow += dt > budget_s
                    time.sleep(0.005)       # same GIL throttle, untimed
            trips = rep0._breaker.trips - trips0 if recover else 0
            state = rep0.breaker_state() if recover else "off"
        finally:
            fleet.close()
    stalls = sum(1 for (_, _, a) in plan.log if a == "stall")
    if not stalls:
        return False, "net.recv stall never fired"
    lost = [r.rid for r in reqs if r.failed or not r.done]
    if lost:
        return False, f"request(s) {lost} failed/unfinished under stalls"
    if fleet.stats["replica_deaths"]:
        return False, ("slow-but-alive peer was declared DEAD "
                       f"({fleet.stats['replica_deaths']} death(s)) — "
                       "latency must not be misread as a kill")
    streams = [list(r.tokens) for r in reqs]
    wrong = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
    if wrong:
        return False, f"stream(s) {wrong} diverged under stall injection"
    if not recover:
        if slow < 2:
            return True, ("unexpected: stalls absorbed without a breaker "
                          f"(worst step {worst:.2f}s)")
        return False, (f"no circuit breaker: {slow} driver step(s) blew "
                       f"past the {budget_s:.1f}s budget (worst "
                       f"{worst:.2f}s) eating stalled replies inline")
    if trips < 1:
        return False, "stalls never tripped the breaker"
    if slow > 1:
        return False, (f"breaker failed to insulate the driver: {slow} "
                       f"step(s) over budget (worst {worst:.2f}s)")
    return True, (f"slow peer contained: breaker tripped {trips}x (final "
                  f"state {state}), {stalls} stall(s) injected and at most "
                  f"one eaten inline before the trip ({slow} driver "
                  f"step(s) over budget, worst {worst:.2f}s), 0 replica "
                  f"deaths, all {len(reqs)} streams byte-identical")


def drill_fleet_drain(recover: bool):
    """Rolling restart of every replica under traffic (the ``fleet.drain``
    site drives the same path when planned). Recovery = graceful drain:
    stop admitting, migrate still-queued requests, finish in-flight slots,
    rebuild, rejoin — zero failed or duplicated tokens (PT-FLT-002).
    The control arm models a deployment that hard-restarts replicas
    without draining: in-flight work is lost."""
    from paddle_tpu.inference.fleet import FleetRouter
    from paddle_tpu.inference.serving import Request

    refs = _fleet_refs()
    with tempfile.TemporaryDirectory() as tmp:
        fleet = FleetRouter(_fleet_build, tmp, num_replicas=2,
                            graceful_drain=recover)
        reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
        try:
            for r in reqs:
                fleet.submit(r)
            fleet.step()                    # traffic in flight
            fleet.rolling_restart(max_steps=500)
            fleet.run_until_done(max_steps=500)
        finally:
            fleet.close()
    lost = [r.rid for r in reqs if r.failed or not r.done]
    if not recover:
        if not lost:
            return True, "unexpected: hard restart lost nothing"
        return False, (f"no graceful drain: hard replica restarts lost "
                       f"{len(lost)} in-flight request(s) {lost}")
    if lost:
        return False, f"rolling restart left request(s) {lost} failed"
    if fleet.stats["restarts"] < 2:
        return False, "replicas were never rebuilt"
    streams = [list(r.tokens) for r in reqs]
    if streams != refs:
        bad = [i for i, (s, f) in enumerate(zip(streams, refs)) if s != f]
        return False, (f"stream(s) {bad} diverged across the rolling "
                       "restart (lost or duplicated tokens)")
    return True, (f"PT-FLT-002: rolling restart under traffic — "
                  f"{fleet.stats['migrated']} queued request(s) migrated, "
                  f"{fleet.stats['restarts']} replicas rebuilt, zero "
                  "failed/duplicated tokens, streams bit-identical")


def drill_fleet_overload(recover: bool):
    """A sheddable low-priority flood hits every replica at once. Recovery
    = fleet brownout: once EVERY alive replica sits at depth, sheddable
    traffic is refused at submit with a typed ``RequestShed`` (PT-FLT-003)
    BEFORE queues saturate, so priority traffic still admits everywhere;
    the brownout exits hysteretically once pressure clears (PT-FLT-004).
    Without it the flood saturates every queue and priority traffic is
    refused with ``EngineSaturated``."""
    import numpy as np

    from paddle_tpu.inference.fleet import FleetConfig, FleetRouter
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              EngineSaturated, Request,
                                              RequestShed)

    cfg, m = _serving_model()
    rng = np.random.default_rng(47)

    def build():
        return ContinuousBatchingEngine(m, max_batch=2, max_len=32,
                                        page_size=8, block_size=2,
                                        max_queue=2)

    config = FleetConfig(brownout_depth=(2 if recover else 10 ** 9),
                         brownout_enter_after=2, brownout_exit_after=2)
    with tempfile.TemporaryDirectory() as tmp:
        fleet = FleetRouter(build, tmp, num_replicas=3, config=config)
        shed = saturated = 0
        admitted = []
        try:
            for i in range(20):             # flood faster than service rate
                p = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
                low = Request(p, max_new_tokens=8, seed=300 + i,
                              priority=Request.PRIORITY_LOW)
                try:
                    fleet.submit(low)
                    admitted.append(low)
                except RequestShed:
                    shed += 1
                except EngineSaturated:
                    saturated += 1
                if i % 3 == 2:              # service interleaves, but slower
                    fleet.step()            # than the flood arrives
            vip_refused = 0
            vips = []
            for i in range(3):              # priority traffic mid-flood
                p = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
                vip = Request(p, max_new_tokens=4, seed=400 + i,
                              priority=Request.PRIORITY_HIGH)
                try:
                    fleet.submit(vip)
                    vips.append(vip)
                except (RequestShed, EngineSaturated):
                    vip_refused += 1
            fleet.run_until_done(max_steps=500)
        finally:
            fleet.close()
    if not recover:
        if not vip_refused:
            return True, ("unexpected: priority traffic admitted through "
                          "a saturating flood without fleet brownout")
        return False, (f"no fleet brownout: the flood saturated every "
                       f"replica ({saturated} EngineSaturated) and "
                       f"{vip_refused}/3 priority request(s) were refused")
    if fleet.stats["brownouts"] < 1:
        return False, "fleet brownout never entered under the flood"
    if not shed or fleet.stats["fleet_shed"] != shed:
        return False, f"flood was not shed at submit (shed={shed})"
    if saturated or vip_refused:
        return False, (f"brownout failed to protect admission "
                       f"(EngineSaturated={saturated}, vip_refused="
                       f"{vip_refused})")
    bad = [r.rid for r in vips + admitted if not r.done or r.failed]
    if bad:
        return False, f"admitted request(s) {bad} did not complete"
    if fleet._brownout_active:
        return False, "fleet brownout never exited after pressure cleared"
    return True, (f"PT-FLT-003/004: flood shed {shed}/20 at submit once "
                  f"every replica sat at depth, all 3 priority requests "
                  f"admitted + completed, zero EngineSaturated, brownout "
                  "exited hysteretically")


# ---------------------------------------------------------------------------
# drills: composed multi-site chaos + the full checkpoint-lifecycle arc
# ---------------------------------------------------------------------------

def drill_composed_chaos(recover: bool):
    """One seeded ComposedFaultPlan arms THREE fault sites at once against
    three subsystems running in parallel threads: the store daemon stalls
    past the client op deadline, a checkpoint shard is bitflipped on
    write, and a serving replica is killed mid-traffic. Recovery = each
    subsystem's own path absorbs its fault (PT-RETRY rides the stall,
    digest verification falls back to the replica copy, the fleet replays
    the dead replica's journal) — and the plan's per-spec RNG streams keep
    the injected damage byte-identical across runs no matter how the
    threads interleave. With recovery off (retries disabled, no replica
    copy, no failover) the same plan must bite."""
    import numpy as np

    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    from paddle_tpu.distributed.communication.store import TCPStore
    from paddle_tpu.distributed.resilience import (ComposedFaultPlan,
                                                   FaultSpec)
    from paddle_tpu.inference.fleet import FleetRouter
    from paddle_tpu.inference.serving import Request

    refs = _fleet_refs()
    w = np.arange(2048, dtype=np.float32)
    SITES = ("store.daemon", "checkpoint.shard", "fleet.replica_kill")

    def make_plan():
        return ComposedFaultPlan(seed=13, specs=[
            FaultSpec("store.daemon", "stall", at=2, count=1, arg=1.2),
            FaultSpec("checkpoint.shard", "bitflip", at=0, count=1, arg=4),
            FaultSpec("fleet.replica_kill", "kill", at=2, count=1,
                      match="replica:0:")])

    def shard_bytes(ckpt):
        with open(os.path.join(ckpt, "0_0.distcp"), "rb") as f:
            return f.read()

    prev = os.environ.get("PT_RETRY_DISABLE")
    if not recover:
        os.environ["PT_RETRY_DISABLE"] = "1"
    failures = []
    try:
        with tempfile.TemporaryDirectory() as tmp:
            plan = make_plan()
            store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1,
                             timeout=10.0, op_timeout=0.4)
            ckpt = os.path.join(tmp, "ckpt")

            def store_loop():
                try:
                    for i in range(6):
                        store.set(f"k{i}", str(i).encode())
                        if store.get(f"k{i}", wait=False) != str(i).encode():
                            failures.append(f"store: k{i} read back wrong")
                            return
                except Exception as e:
                    failures.append(f"store: {type(e).__name__}: {e}")

            def ckpt_loop():
                try:
                    save_state_dict({"w": w}, ckpt, replica=recover)
                    target = {"w": np.zeros_like(w)}
                    load_state_dict(target, ckpt)
                    if not np.array_equal(np.asarray(target["w"]), w):
                        failures.append("ckpt: replica returned wrong data")
                except Exception as e:
                    failures.append(f"ckpt: {type(e).__name__}: {e}")

            fleet = FleetRouter(_fleet_build, os.path.join(tmp, "fleet"),
                                num_replicas=3, failover=recover)
            reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
            threads = [threading.Thread(target=fn, daemon=True)
                       for fn in (store_loop, ckpt_loop)]
            try:
                with plan:
                    for t in threads:
                        t.start()
                    for r in reqs:
                        fleet.submit(r)
                    fleet.run_until_done(max_steps=500)
                    for t in threads:
                        t.join(timeout=60.0)
            finally:
                fleet.close()
                store.close()
            if any(t.is_alive() for t in threads):
                return False, "chaos thread(s) wedged past the join deadline"
            lost = [r.rid for r in reqs if r.failed or not r.done]
            if lost:
                failures.append(f"fleet: request(s) {lost} failed/unfinished")
            elif [list(r.tokens) for r in reqs] != refs:
                failures.append("fleet: streams diverged from the "
                                "uninterrupted reference")
            fired = plan.fired()
            damaged = shard_bytes(ckpt)
    finally:
        if prev is None:
            os.environ.pop("PT_RETRY_DISABLE", None)
        else:
            os.environ["PT_RETRY_DISABLE"] = prev
    if not recover:
        if not failures:
            return True, "unexpected: composed chaos bit nothing"
        return False, "recovery off: " + "; ".join(failures[:3])
    missing = [s for s in SITES if not fired.get(s)]
    if missing:
        return False, f"composed plan never fired site(s) {missing}"
    if failures:
        return False, "; ".join(failures[:3])
    # determinism across interleavings: a FRESH plan with the same seed
    # must damage the shard byte-identically even though run 1 had three
    # sites' threads racing (per-spec RNG streams, not one shared stream)
    with tempfile.TemporaryDirectory() as tmp2:
        replay = os.path.join(tmp2, "ckpt")
        with make_plan():
            save_state_dict({"w": w}, replay, replica=True)
        if shard_bytes(replay) != damaged:
            return False, ("per-spec RNG streams broke: the same seed "
                           "damaged the shard differently across runs")
    return True, (f"3 sites fired concurrently ({fired}), every recovery "
                  "path held, shard damage byte-identical across runs")


def drill_lifecycle_e2e(recover: bool):
    """The whole checkpoint lifecycle as ONE drill (docs/RESILIENCE.md
    "Checkpoint lifecycle"): train the tiny serving llama under a numeric
    guard with async checkpoints → an injected heartbeat loss kills the
    peer node and shrinks the mesh 8→4 devices → elastic resume over the
    survivors from the recorded checkpoint → train to completion →
    CheckpointPublisher digest-verifies the manifest and hot-swaps a live
    2-replica fleet via generation-fenced rolling restart → the swapped
    fleet serves byte-identically to a COLD engine built from the trained
    weights, and a second same-weights publish leaves every stream
    untouched. A ComposedFaultPlan arms three sites across the arc (store
    daemon stall, heartbeat kill, replica kill mid-wave). Control arm: no
    elastic manager, no failover — the same plan must flip the exit
    code."""
    import numpy as np
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.distributed.communication.store import TCPStore
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.resilience import (ComposedFaultPlan,
                                                   FaultSpec,
                                                   ResilientTrainer)
    from paddle_tpu.distributed.resilience.lifecycle import (
        CheckpointPublisher, lifecycle_stats, reset_lifecycle_stats,
        set_lifecycle_phase)
    from paddle_tpu.framework.numeric_guard import GuardPolicy
    from paddle_tpu.inference.fleet import FleetRouter
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              Request)
    from paddle_tpu.models import LlamaForCausalLM

    cfg, _ = _serving_model()       # config only — models are drill-local
    B, S, STEPS = 8, 8, 6

    def _arr(v):
        return np.asarray(v._data if hasattr(v, "_data") else v)

    def data_fn(step):
        rng = np.random.default_rng(5000 + step)
        ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
        return ids, ids                 # self-supervised LM (shifted CE)

    def build(alive):
        n = 8 if len(alive) >= 2 else 4
        mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
        paddle.seed(11)
        return Engine(LlamaForCausalLM(cfg), mesh, lr=1e-3, clip_norm=None,
                      guard=GuardPolicy(action="skip_step", warmup_steps=3,
                                        spike_factor=50.0))

    def serve_wave(fleet):
        reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
        for r in reqs:
            fleet.submit(r)
        fleet.run_until_done(max_steps=500)
        lost = [r.rid for r in reqs if r.failed or not r.done]
        return [list(r.tokens) for r in reqs], lost

    reset_lifecycle_stats()
    with tempfile.TemporaryDirectory() as tmp:
        store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1,
                         timeout=20.0)
        store_b = TCPStore("127.0.0.1", store.port, world_size=1,
                           timeout=20.0)
        plan = ComposedFaultPlan(seed=17, specs=[
            FaultSpec("store.daemon", "stall", at=4, count=1, arg=0.8),
            FaultSpec("elastic.heartbeat", "kill", at=3, count=-1,
                      match="nodeB"),
            FaultSpec("fleet.replica_kill", "kill", at=2, count=1,
                      match="replica:0:")])
        mgr_b = ElasticManager(store_b, "drill", "nodeB",
                               expected=["nodeA", "nodeB"],
                               heartbeat_interval=0.1, ttl=0.45)
        mgr_a = ElasticManager(store, "drill", "nodeA",
                               expected=["nodeA", "nodeB"],
                               heartbeat_interval=0.1, ttl=0.45) \
            if recover else None
        b_stop = threading.Event()

        def node_b_loop():
            i = 0
            while not b_stop.is_set():
                if mgr_b._thread is None or not mgr_b._thread.is_alive():
                    return              # heartbeat killed -> node is dead
                if i >= 3:
                    mgr_b.stop()        # deterministic death backstop
                    return
                try:
                    store_b.barrier(f"lcs{i}", world_size=2, timeout=3.0)
                except Exception:
                    return
                i += 1

        def coop_data_fn(step):
            ws = len(mgr_a.expected) if mgr_a is not None else 2
            if ws > 1:
                store.barrier(f"lcs{step}", world_size=ws, timeout=1.5)
            time.sleep(0.05)
            return data_fn(step)

        ckpt_dir = os.path.join(tmp, "job")
        plan.install()
        try:
            mgr_b.start()
            if mgr_a is not None:
                mgr_a.start()
            threading.Thread(target=node_b_loop, daemon=True).start()
            set_lifecycle_phase("train")
            trainer = ResilientTrainer(build, ckpt_dir, elastic=mgr_a,
                                       save_every=2)
            try:
                out = trainer.fit(coop_data_fn, STEPS)
            except Exception as e:
                return (False,
                        f"arc died in training: {type(e).__name__}: {e}")
            finally:
                b_stop.set()
                if mgr_a is not None:
                    mgr_a.stop()
                mgr_b.stop()

            if out["restarts"] < 1:
                return False, "peer loss never shrank the mesh"
            if not out["resumed_at"]:
                return False, "mesh shrank without an elastic resume"
            if out["final_step"] != STEPS:
                return False, f"train stopped at {out['final_step']}/{STEPS}"

            # publish: verify manifest -> load trained weights into the
            # live serving model -> generation-fenced rolling hot-swap
            paddle.seed(11)
            serve_model = LlamaForCausalLM(cfg)
            probe = sorted(serve_model.state_dict())[0]
            before = np.array(_arr(serve_model.state_dict()[probe]),
                              copy=True)

            def build_serve():
                return ContinuousBatchingEngine(serve_model, max_batch=2,
                                                max_len=32, page_size=8,
                                                block_size=2)

            publisher = CheckpointPublisher(ckpt_dir)
            fleet = FleetRouter(build_serve, os.path.join(tmp, "fleet"),
                                num_replicas=2, failover=recover)
            try:
                warm, lost0 = serve_wave(fleet)  # traffic on init weights
                if lost0:
                    return False, f"pre-publish wave lost request(s) {lost0}"
                pub = publisher.publish(serve_model, fleet)
                swapped, lost1 = serve_wave(fleet)
                pub2 = publisher.publish(serve_model, fleet)  # same weights
                again, lost2 = serve_wave(fleet)
            finally:
                fleet.close()

            if lost1 or lost2:
                return False, (f"post-publish wave lost request(s) "
                               f"{lost1 or lost2}")
            if pub["generation"] < 1 or pub["shards"] < 1 or pub["params"] < 1:
                return False, f"publish record looks torn: {pub}"
            if pub2["generation"] != pub["generation"]:
                return False, "same-weights republish changed generation"
            if np.array_equal(before,
                              _arr(serve_model.state_dict()[probe])):
                return False, "publish did not change the serving weights"

            # byte-identity contract: the hot-swapped fleet == a COLD
            # engine built from the published checkpoint; a same-weights
            # swap changes nothing
            cold_model = LlamaForCausalLM(cfg)
            publisher.load_weights(cold_model, pub["step"])
            cold = ContinuousBatchingEngine(cold_model, max_batch=2,
                                            max_len=32, page_size=8,
                                            block_size=2)
            cold_reqs = [Request(**kw) for kw in _fleet_wave_kwargs()]
            for r in cold_reqs:
                cold.add_request(r)
            cold.run_until_done(max_steps=500)
            cold_refs = [list(r.tokens) for r in cold_reqs]
        finally:
            plan.uninstall()
            store_b.close()
            store.close()

    if swapped != cold_refs:
        bad = [i for i, (s, c) in enumerate(zip(swapped, cold_refs))
               if s != c]
        return False, (f"hot-swapped stream(s) {bad} diverged from a cold "
                       "engine on the published weights")
    if again != swapped:
        return False, ("same-weights swap changed served streams "
                       "(before/after byte-identity broken)")
    fired = plan.fired()
    missing = [s for s in ("store.daemon", "elastic.heartbeat",
                           "fleet.replica_kill") if not fired.get(s)]
    if missing:
        return False, f"composed plan never fired site(s) {missing}"
    stats = lifecycle_stats()
    if (stats["publish_total"] != 2
            or stats["generation"] != pub["generation"]
            or stats["phase"] != "serve"):
        return False, f"lifecycle stats out of step: {stats}"
    return True, (f"8->4 shrink resumed at step {out['resumed_at'][0]}, "
                  f"published gen {pub['generation']} ({pub['shards']} "
                  f"shard(s), {pub['params']} params), hot-swap == cold "
                  f"engine, same-weights swap byte-stable, 3 chaos sites "
                  f"fired {fired}")


DRILLS = {
    "heartbeat": drill_heartbeat,
    "store_stall": drill_store_stall,
    "shard_corruption": drill_shard_corruption,
    "engine_saturation": drill_engine_saturation,
    "serving_deadline": drill_serving_deadline,
    "prefix_cache_exhaustion": drill_prefix_cache_exhaustion,
    "big_batch_saturation": drill_big_batch_saturation,
    "serving_crash": drill_serving_crash,
    "mesh_device_loss": drill_mesh_device_loss,
    "serving_stall": drill_serving_stall,
    "serving_overload_shed": drill_serving_overload_shed,
    "fleet_replica_kill": drill_fleet_replica_kill,
    "fleet_proc_kill": drill_fleet_proc_kill,
    "net_flaky_migration": drill_net_flaky_migration,
    "net_slow_peer": drill_net_slow_peer,
    "fleet_drain": drill_fleet_drain,
    "fleet_overload": drill_fleet_overload,
    "kv_migration_corruption": drill_kv_migration_corruption,
    "spec_decode_divergence": drill_spec_decode_divergence,
    "nan_grad": drill_nan_grad,
    "loss_spike": drill_loss_spike,
    "poison_batch": drill_poison_batch,
    "composed_chaos": drill_composed_chaos,
    "lifecycle_e2e": drill_lifecycle_e2e,
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--drill", choices=sorted(DRILLS))
    ap.add_argument("--no-recover", action="store_true",
                    help="disable the drill's recovery path (must flip rc)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the full matrix, both recovery modes")
    ap.add_argument("--only", default=None, metavar="A,B,...",
                    help="selftest subset: run only these drills")
    ap.add_argument("--skip", default=None, metavar="A,B,...",
                    help="selftest subset: run all but these drills "
                         "(local iteration on one drill family)")
    args = ap.parse_args(argv)

    if args.selftest:
        selected = dict(DRILLS)
        for flag, keep in ((args.only, True), (args.skip, False)):
            if flag is None:
                continue
            names = [n.strip() for n in flag.split(",") if n.strip()]
            unknown = [n for n in names if n not in DRILLS]
            if unknown:
                ap.error(f"unknown drill(s): {', '.join(unknown)}")
            selected = {k: v for k, v in selected.items()
                        if (k in names) == keep}
        h = _selftest.Harness("FAULT DRILL")
        for name, drill in selected.items():
            ok, info = drill(recover=True)
            h.case(f"{name} (recovery on)", ok, info)
            ok2, info2 = drill(recover=False)
            h.case(f"{name} (recovery off, fault must bite)", not ok2, info2)
        from paddle_tpu.distributed.resilience import retry_stats

        rs = retry_stats()
        h.note(f"retry stats: {rs['calls']} calls, {rs['attempts']} "
               f"attempts, {rs['retries']} retries, {rs['giveups']} "
               f"give-ups, {rs['latency_s']:.2f}s cumulative latency")
        return h.finish(
            f"FAULT DRILL OK: {len(selected)} fault classes recovered, "
            "each flips the gate without its recovery path",
            "FAULT DRILL FAIL: {failures} expectation(s) violated")

    if not args.drill:
        print(__doc__)
        return 2
    ok, info = DRILLS[args.drill](recover=not args.no_recover)
    print(f"[{'ok' if ok else 'FAIL'}] {args.drill}: {info}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
