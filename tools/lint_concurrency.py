"""Concurrency-lint gate: sweep the whole package with the PT-RACE
analyzer (paddle_tpu/static/concurrency — docs/STATIC_ANALYSIS.md).

The graph got a linter in PR 1 (tools/lint_graph.py); this is the same
gate for the threaded HOST stack — supervisors, watchdogs, metrics/HTTP
servers, heartbeat loops, async checkpoint writers. Pure AST: analyzed
modules are never imported, so the sweep is fast and side-effect free.

Exit code 0 iff every error-severity finding is either absent or covered
by the reviewed baseline file (tools/concurrency_baseline.json — one
entry per finding id WITH a justification string; an unreviewed defect
can only make the gate red, never silently pass).

Usage:
    python tools/lint_concurrency.py                  # full package gate
    python tools/lint_concurrency.py paddle_tpu/inference
    python tools/lint_concurrency.py --fail-on warning
    python tools/lint_concurrency.py --inject unguarded_write
    python tools/lint_concurrency.py --selftest       # all 5 PT-RACE classes
    python tools/lint_concurrency.py --write-baseline # refresh (review it!)

``--inject`` lints one fixture module seeded with a known defect class and
must flip the exit code; ``--selftest`` loops every class in-process plus a
clean fixture, exiting 0 iff each one was detected with its expected code —
both pinned in tests/test_ci_gates.py beside lint_graph / fault_drill /
scrape_metrics.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import _selftest

ROOT = _selftest.bootstrap()

BASELINE_PATH = os.path.join(ROOT, "tools", "concurrency_baseline.json")

#: cross-module thread entry points the per-module AST cannot see —
#: PUBLIC APIs that run on threads started elsewhere. Root only entry
#: points (never private helpers: rooting a helper disables the
#: caller-held-lock inheritance that proves it clean under its callers'
#: locks). Reviewed alongside the baseline file.
_TRACER_API = ["TraceRecorder." + m for m in (
    "submit", "shed", "admit", "prefill_chunk", "first_token", "tokens",
    "decode_block", "finish", "mark_recovered", "failover", "migrate",
    "migration_failure", "recovery", "publish", "resume", "instant",
    "span", "is_open", "incomplete", "lifecycle", "export_chrome",
    "slo_summary", "counters")]

THREAD_ROOTS = {
    # fleet parallel_step replica threads, the rpc ThreadPoolExecutor and
    # the elastic heartbeat daemon all funnel through retry_call
    "paddle_tpu/distributed/resilience/retry.py": ["retry_call"],
    # ONE TraceRecorder is stamped from every replica's step thread under
    # FleetConfig(parallel_step=True) while the driver reads exports
    "paddle_tpu/observability/tracing.py": _TRACER_API,
    # the MetricsServer scrape thread walks the registry while engine
    # threads record into the instruments
    "paddle_tpu/observability/metrics.py": [
        "MetricsRegistry.collect", "MetricsRegistry.dump",
        "_Instrument.family", "Histogram.family",
        "Counter.inc", "Gauge.set", "Histogram.observe",
        "Counter.value", "Gauge.value", "Histogram.count",
        "Histogram.quantile"],
    # ParameterServer methods execute on rpc handler threads
    "paddle_tpu/distributed/ps/__init__.py": [
        "ParameterServer.create_dense_table",
        "ParameterServer.create_sparse_table",
        "ParameterServer.pull_dense", "ParameterServer.push_dense",
        "ParameterServer.pull_sparse", "ParameterServer.push_sparse",
        "ParameterServer.stat"],
    "paddle_tpu/distributed/ps/_tables.py": [
        "DenseTable.pull", "DenseTable.push", "DenseTable.stat",
        "SparseTable.pull", "SparseTable.push", "SparseTable.stat"],
    # TCPStore client ops run on the elastic heartbeat thread beside the
    # main path
    "paddle_tpu/distributed/communication/store.py": [
        "TCPStore.add", "TCPStore.get"],
    # procfleet (docs/SERVING.md "Process fleet"): the proxy's heartbeat
    # thread and the fleet's parallel_step replica threads both drive the
    # wire helpers, and parallel_step threads enter the proxy through its
    # public replica surface (step/submit/progress/load run concurrently
    # with the driver reading finished()/metrics)
    "paddle_tpu/inference/procfleet/wire.py": ["send_msg", "recv_msg"],
    "paddle_tpu/inference/procfleet/proxy.py": [
        "ProcReplica.step", "ProcReplica.submit", "ProcReplica.progress",
        "ProcReplica.load", "ProcReplica.has_work", "ProcReplica.behind",
        "ProcReplica.heartbeat_count"],
    # the transport seam (docs/SERVING.md "Transport seam"): frame IO is
    # driven from the heartbeat thread and parallel_step replica threads
    # (serialized per proxy by its _io_lock), and the loopback worker is
    # a daemon THREAD whose entry point replaces the spawned process
    "paddle_tpu/inference/procfleet/transport.py": [
        "TcpTransport.send_frame", "TcpTransport.recv_frame",
        "LoopbackTransport.send_frame", "LoopbackTransport.recv_frame",
        "ChaosTransport.send_frame", "ChaosTransport.recv_frame"],
    "paddle_tpu/inference/procfleet/worker.py": ["worker_thread_main"],
}


# ---------------------------------------------------------------------------
# seeded-defect fixtures (one module per PT-RACE class + one clean)
# ---------------------------------------------------------------------------

FIXTURES = {
    "unguarded_write": '''
import threading

class Poller:
    def __init__(self):
        self.hits = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            self.hits += 1          # worker increments...

    def snapshot(self):
        out = self.hits             # ...main reads AND resets, no lock
        self.hits = 0
        return out
''',
    "inconsistent_guard": '''
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        threading.Thread(target=self._refresh, daemon=True).start()

    def _refresh(self):
        while True:
            with self._lock:
                self._entries["ts"] = 1

    def invalidate(self):
        self._entries.clear()       # everywhere else holds _lock
''',
    "lock_order": '''
import threading

class Transfer:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.a = 0
        self.b = 0
        threading.Thread(target=self._rebalance, daemon=True).start()

    def _rebalance(self):
        with self._block:           # B then A...
            with self._alock:
                self.a += 1
                self.b -= 1

    def move(self):
        with self._alock:           # ...A then B: inversion
            with self._block:
                self.a -= 1
                self.b += 1
''',
    "check_then_act": '''
import threading

class JobQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self):
        while True:
            if self._q:             # checked OUTSIDE the lock...
                with self._lock:
                    self._q.pop()   # ...acted on under it: stale decision

    def put(self, x):
        with self._lock:
            self._q.append(x)
''',
    "thread_leak": '''
import threading

def _writer(path):
    with open(path, "w") as f:
        f.write("x")

def export_logs(path):
    t = threading.Thread(target=_writer, args=(path,))
    t.start()                       # non-daemon, never joined anywhere
''',
}

EXPECTED_CODE = {
    "unguarded_write": "PT-RACE-001",
    "inconsistent_guard": "PT-RACE-002",
    "lock_order": "PT-RACE-003",
    "check_then_act": "PT-RACE-004",
    "thread_leak": "PT-RACE-005",
}

CLEAN_FIXTURE = '''
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = []
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self._lock:
                if self._stop:
                    return
                if self._jobs:
                    self._jobs.pop()

    def put(self, x):
        with self._lock:
            self._jobs.append(x)

    def close(self):
        with self._lock:
            self._stop = True
        self._thread.join(timeout=1.0)
'''


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str = BASELINE_PATH):
    """{finding_id: justification}. Entries WITHOUT a justification are
    rejected — the file is a review record, not a mute button."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    out = {}
    for entry in doc.get("entries", ()):
        fid = entry.get("id")
        just = (entry.get("justification") or "").strip()
        if not fid or not just:
            raise SystemExit(
                f"baseline entry {entry!r} is missing an id or a "
                "justification — every suppression must say why")
        out[fid] = just
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run_gate(paths, fail_on="error", baseline=None, verbose=False,
             use_roots=True):
    """Sweep ``paths``; returns (exit_code, report, gate_findings)."""
    from paddle_tpu.static.analysis import Severity
    from paddle_tpu.static.concurrency import analyze_paths

    report, analyzed = analyze_paths(
        paths, base=ROOT, thread_roots=THREAD_ROOTS if use_roots else {})
    floor = Severity.ERROR if fail_on == "error" else Severity.WARNING
    baseline = baseline if baseline is not None else {}
    gate, suppressed = [], []
    for d in report.at_least(floor):
        fid = getattr(d, "finding_id", None)
        if fid in baseline:
            suppressed.append(d)
        else:
            gate.append(d)
    shown = list(report) if verbose else gate
    for d in shown:
        fid = getattr(d, "finding_id", "")
        print(f"{d.format()}\n    id: {fid}")
    for d in suppressed:
        print(f"[baselined] {getattr(d, 'finding_id', '')}: "
              f"{baseline[getattr(d, 'finding_id', '')]}")
    stale = sorted(set(baseline) - {
        getattr(d, "finding_id", None) for d in report})
    for fid in stale:
        print(f"[stale baseline entry — remove it] {fid}")
    status = "FINDINGS AT GATE SEVERITY" if gate else "CLEAN"
    print(f"CONCURRENCY LINT {'FAIL' if gate else 'OK'}: "
          f"{len(analyzed)} module(s), {len(report)} finding(s), "
          f"{len(suppressed)} baselined, {len(gate)} at gate severity — "
          f"{status}")
    return (1 if gate else 0), report, gate


def selftest():
    """Every seeded PT-RACE class must be detected with its expected code
    at error severity; the clean fixture must lint clean; one end-to-end
    --inject arm pins the exit-code flip itself."""
    from paddle_tpu.static.concurrency import analyze_source

    h = _selftest.Harness("CONCURRENCY")
    rep = analyze_source(CLEAN_FIXTURE, "fixtures/clean.py")
    h.case("clean fixture", not rep.errors(),
           f"{len(rep)} finding(s), {len(rep.errors())} error(s)")
    for defect, src in FIXTURES.items():
        want = EXPECTED_CODE[defect]
        rep = analyze_source(src, f"fixtures/{defect}.py")
        hit = [d for d in rep.errors() if d.code == want]
        if hit:
            h.case(f"inject {defect}", True,
                   f"detected {want} — {hit[0].message[:70]}")
        else:
            h.case(f"inject {defect}", False,
                   f"wanted {want}, got {[d.code for d in rep]}")
    # end-to-end: the same defect through the real gate driver must flip
    # the exit code, and a baseline entry for it must un-flip it
    import tempfile

    with tempfile.TemporaryDirectory(dir=ROOT) as tmp:
        bad = os.path.join(tmp, "seeded.py")
        with open(bad, "w") as f:
            f.write(FIXTURES["unguarded_write"])
        rc_bad, report, gate = run_gate([bad], baseline={}, use_roots=False)
        h.case("gate flips on seeded defect", rc_bad == 1,
               f"rc={rc_bad}, {len(gate)} gate finding(s)")
        fid = getattr(gate[0], "finding_id", "") if gate else ""
        rc_ok, _, _ = run_gate([bad], baseline={fid: "selftest"},
                               use_roots=False)
        h.case("baseline entry un-flips it", rc_ok == 0, f"rc={rc_ok}")
    return h.finish(
        f"SELFTEST OK: {len(FIXTURES)} defect classes detected, clean "
        "fixture lints clean, gate + baseline exit codes pinned",
        "SELFTEST FAIL: {failures} expectation(s) violated")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(ROOT, "paddle_tpu")],
                    help="files/dirs to sweep (default: the whole package)")
    ap.add_argument("--fail-on", choices=("error", "warning"),
                    default="error")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file (show everything)")
    ap.add_argument("--inject", choices=sorted(FIXTURES), default=None,
                    help="lint one fixture seeded with a defect class")
    ap.add_argument("--selftest", action="store_true",
                    help="verify every defect class flips the gate")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as baseline entries "
                         "with TODO justifications (then review them!)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print sub-gate findings")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.inject:
        import tempfile

        with tempfile.TemporaryDirectory(dir=ROOT) as tmp:
            bad = os.path.join(tmp, f"{args.inject}.py")
            with open(bad, "w") as f:
                f.write(FIXTURES[args.inject])
            rc, _, _ = run_gate([bad], fail_on=args.fail_on, baseline={},
                                use_roots=False)
        return rc

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    rc, report, gate = run_gate(args.paths, fail_on=args.fail_on,
                                baseline=baseline, verbose=args.verbose)
    if args.write_baseline:
        entries = []
        for d in sorted(report.errors(),
                        key=lambda d: getattr(d, "finding_id", "")):
            fid = getattr(d, "finding_id", None)
            if fid:
                entries.append({
                    "id": fid,
                    "justification": baseline.get(
                        fid, "TODO: review and justify (or fix)"),
                })
        with open(args.baseline, "w") as f:
            json.dump({"_comment": [
                "Reviewed PT-RACE suppressions (docs/STATIC_ANALYSIS.md).",
                "Every entry needs a justification; stale entries are",
                "reported by the gate — remove them when the code is",
                "fixed."], "entries": entries}, f, indent=2)
            f.write("\n")
        print(f"baseline written: {args.baseline} ({len(entries)} entries)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
