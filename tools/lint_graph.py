"""Graph-lint gate: record every in-repo model-family program and run the
static analyzer suite (paddle_tpu/static/analysis) over each.

Exit code 0 iff every program lints clean at error severity. Each finding
prints as ``<program>: PT-XXXX-NNN [severity] op#i type @file:line: message``.

Usage:
    JAX_PLATFORMS=cpu python tools/lint_graph.py              # full zoo gate
    python tools/lint_graph.py --family bert                  # one family
    python tools/lint_graph.py --fail-on warning              # stricter gate
    python tools/lint_graph.py --inject shape_mismatch        # seeded defect
    python tools/lint_graph.py --selftest                     # all injections

``--inject`` plants exactly one defect of a known class into one recorded
program (or a tiny synthetic run for cache-hazard classes) and must flip the
exit code — tests/test_ci_gates.py pins this behavior. ``--selftest`` loops
every defect class in-process and exits 0 iff each one was detected with its
expected diagnostic code.
"""

from __future__ import annotations

import argparse
import sys

import _selftest

ROOT = _selftest.bootstrap()

import jax  # noqa: E402
import numpy as np  # noqa: E402

DEFECTS = ("shape_mismatch", "fp64_leak", "recompile_key",
           "unseeded_stochastic", "bad_mesh_axis", "uneven_shard",
           "unused_param", "async_borrow", "host_sync")

EXPECTED_CODE = {
    "shape_mismatch": "PT-SHAPE-001",
    "fp64_leak": "PT-DTYPE-001",
    "recompile_key": "PT-TRACE-001",
    "unseeded_stochastic": "PT-TRACE-003",
    "bad_mesh_axis": "PT-SPMD-001",
    "uneven_shard": "PT-SPMD-002",
    "unused_param": "PT-GRAPH-003",
    "async_borrow": "PT-TRACE-005",
    # warning-severity class: the selftest lints it at --fail-on warning
    "host_sync": "PT-TRACE-004",
}


# ---------------------------------------------------------------------------
# model-family recording
# ---------------------------------------------------------------------------

def _spec(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def record_bert():
    import paddle_tpu  # noqa: F401
    from paddle_tpu.models import BertConfig, BertForMaskedLM
    from paddle_tpu.static.analysis import layer_to_program

    m = BertForMaskedLM(BertConfig.tiny())
    prog = layer_to_program(m, _spec((2, 16), np.int32), _spec((2, 16), np.int32),
                            input_names=["input_ids", "token_type_ids"])
    return prog, m


def record_gpt():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.static.analysis import layer_to_program

    cfg = GPTConfig.tiny() if hasattr(GPTConfig, "tiny") else GPTConfig()
    m = GPTForCausalLM(cfg)
    prog = layer_to_program(m, _spec((2, 16), np.int32),
                            input_names=["input_ids"])
    return prog, m


def record_llama():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.static.analysis import layer_to_program

    cfg = LlamaConfig.tiny() if hasattr(LlamaConfig, "tiny") else LlamaConfig()
    m = LlamaForCausalLM(cfg)
    prog = layer_to_program(m, _spec((2, 16), np.int32),
                            input_names=["input_ids"])
    return prog, m


def record_vit():
    from paddle_tpu.vision.models import ViTConfig, VisionTransformer
    from paddle_tpu.static.analysis import layer_to_program

    m = VisionTransformer(ViTConfig.tiny())
    prog = layer_to_program(m, _spec((2, 3, 32, 32), np.float32),
                            input_names=["images"])
    return prog, m


def record_unet():
    from paddle_tpu.models import UNet2DConditionModel, UNetConfig
    from paddle_tpu.static.analysis import layer_to_program

    cfg = UNetConfig.tiny()
    m = UNet2DConditionModel(cfg)
    prog = layer_to_program(
        m, _spec((2, 4, 16, 16), np.float32), _spec((2,), np.int32),
        _spec((2, 6, cfg.cross_attention_dim), np.float32),
        input_names=["sample", "timesteps", "context"])
    return prog, m


def record_serving():
    """The fused mega-step serving program (inference/serving.py, ISSUE
    10): the ONE device program a 128-256-slot engine dispatches per
    decode block — decode + in-graph sampling + position advance over
    every row, inactive rows masked. Recorded through the engine's own
    ``_mega_step_fn`` so the linted program IS the production program
    (params as named inputs; caches/tables/sampling state as baked
    constants of the trace). The raw step fn also rides along as a
    ``static_fns`` context entry, so the PT-TRACE-004 host-sync scan
    covers the mega-step source — a ``.numpy()``/``.item()`` creeping
    into the fused step path is exactly the per-slot host sync the
    big-batch refactor removed."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              PrefixCacheConfig)
    from paddle_tpu.jit.api import _collect_state
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.static.analysis import trace_to_program

    paddle.seed(11)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    m = LlamaForCausalLM(cfg)
    eng = ContinuousBatchingEngine(
        m, max_batch=8, max_len=32, page_size=8, block_size=2, fused=True,
        prefix_cache=PrefixCacheConfig(prefill_chunk=8))
    run = eng._mega_step_fn()
    names, tensors = _collect_state(m)
    param_structs = [_spec(t._data.shape, t._data.dtype) for t in tensors]
    n_p = len(param_structs)
    kv, tables = eng.caches["kv"], eng.caches["tables"]
    seeds, temps, tops, topks = eng._dev_samp

    def flat(*args):
        params, (toks, pos, act) = list(args[:n_p]), args[n_p:]
        return run(params, toks, kv, tables, pos, act, seeds, temps, tops,
                   topks, n_steps=2, do_sample=True)

    B = eng.max_batch
    prog = trace_to_program(
        flat, _spec((B,), np.int32), _spec((B,), np.int32),
        _spec((B,), np.bool_), input_names=["toks", "pos", "act"],
        param_structs=param_structs, param_names=names,
        param_tensors=tensors)
    prog._static_fns = [run]        # host-sync scan target (lint_family)
    return prog, m


def record_migration():
    """The PR 12 KV-block migration programs (inference/disagg.py
    KVChainCodec via ops/paged_attention.py): the per-layer page gather
    that exports a chain plus ``scatter_chain_pages`` that imports it,
    traced as one roundtrip so the disagg path has the same graph-lint
    coverage as the mega-step. The linted program IS the cost auditor's
    ``migration`` program (ONE recorder, tools/audit_program_cost.py —
    lint coverage and cost coverage cannot silently diverge).
    ``gather_chain_pages`` itself is DELIBERATELY host-side (its
    np.asarray readback is the fence that orders the export behind
    in-flight decode writes — docs/SERVING.md), so what is traced is its
    device gather expression."""
    import types

    import audit_program_cost

    prog, _ = audit_program_cost.record_migration()
    # no Layer behind this family: the lint context needs a parameters()
    model = types.SimpleNamespace(parameters=lambda: [])
    return prog, model


FAMILIES = {
    "bert": record_bert,
    "gpt": record_gpt,
    "llama": record_llama,
    "vit": record_vit,
    "unet": record_unet,
    "serving": record_serving,
    "migration": record_migration,
}


# ---------------------------------------------------------------------------
# seeded-defect injection
# ---------------------------------------------------------------------------

def inject(defect, prog, model, context):
    """Plant one defect into ``prog`` / the analysis context. Returns the
    context dict handed to run_analysis."""
    import paddle_tpu as paddle
    from paddle_tpu.core.static_graph import Operation
    from paddle_tpu.framework import random as frandom

    blk = prog.global_block()
    first = next(op for op in blk.ops if op.outputs)

    if defect == "shape_mismatch":
        v = first.outputs[0]
        v._data = jax.ShapeDtypeStruct(tuple(v._data.shape) + (1,),
                                       v._data.dtype)
    elif defect == "fp64_leak":
        v = first.outputs[0]
        v._data = jax.ShapeDtypeStruct(tuple(v._data.shape), np.float64)
    elif defect == "recompile_key":
        # per-step feed-signature churn: one tiny program, three batch shapes
        from paddle_tpu import static
        from paddle_tpu.static import Executor, program_guard

        paddle.enable_static()
        try:
            main = static.Program()
            with program_guard(main):
                x = static.data("x", [None, 4], "float32")
                y = x * 2.0
            exe = Executor()
            for b in (1, 2, 3):
                exe.run(main, feed={"x": np.ones((b, 4), np.float32)},
                        fetch_list=[y])
        finally:
            paddle.disable_static()
        context["executors"] = [exe]
    elif defect == "unseeded_stochastic":
        frandom._global["seeded"] = False
        prog.random_seed = 0

        def draw(shape=(4,)):
            return jax.random.uniform(jax.random.key(0), shape)

        op = Operation(len(blk.ops), "uniform_random_injected", draw, [], {},
                       src="tools/lint_graph.py:inject")
        blk.ops.append(op)
        op.outputs.append(blk.create_var((4,), np.float32,
                                         name="injected_uniform", op=op))
    elif defect in ("bad_mesh_axis", "uneven_shard"):
        from paddle_tpu.distributed.auto_parallel import (ProcessMesh,
                                                          Replicate, Shard)

        target = None
        for op in blk.ops:
            for t in list(op.inputs) + list(op.captured):
                if getattr(t, "_data", None) is not None and \
                        len(getattr(t._data, "shape", ())) >= 1:
                    target = t
                    break
            if target is not None:
                break
        assert target is not None, "no shardable tensor in program"
        dim0 = int(target._data.shape[0])
        if defect == "bad_mesh_axis":
            mesh = ProcessMesh(shape=[2, 2], dim_names=["dp", "mp"])
            target.process_mesh = mesh
            target.placements = [Shard(99), Replicate()]
        else:
            mesh = ProcessMesh(shape=[dim0 + 1], dim_names=["mp"])
            target.process_mesh = mesh
            target.placements = [Shard(0)]  # dim0 % (dim0+1) != 0
    elif defect == "unused_param":
        ghost = paddle.Tensor(np.zeros((3, 3), np.float32))
        ghost.is_parameter = True
        ghost.name = "ghost_weight"
        params = list(context.get("parameters") or [])
        params.append(ghost)
        context["parameters"] = params
    elif defect == "async_borrow":
        # the PR-4 serving bug class, reduced: upload a host buffer with
        # jnp.asarray, then mutate it — the async transfer may read the
        # post-mutation bytes (PT-TRACE-005; a .copy() upload lints clean)
        def dispatch_tables(tables_host):
            import jax.numpy as jnp

            dev = jnp.asarray(tables_host)
            tables_host[0] = -1          # parks the row AFTER the borrow
            return dev

        context["borrow_fns"] = [dispatch_tables]
    elif defect == "host_sync":
        # the per-slot host sync the fused mega-step removed, reduced: a
        # token-value read (.item()) inside the traced step fn — exactly
        # what would drag a 256-row device program back to one host round
        # trip per slot (PT-TRACE-004; the real mega-step source is clean)
        def mega_step_with_sync(toks, pos):
            n_live = int(pos.item())     # host sync inside the traced step
            return toks[:n_live]

        context["static_fns"] = (list(context.get("static_fns") or [])
                                 + [mega_step_with_sync])
    else:
        raise SystemExit(f"unknown defect {defect!r} (choose: {DEFECTS})")
    return context


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def lint_family(name, defect=None, fail_on="error"):
    """Record one family, (optionally) inject, analyze. Returns (report,
    n_gate_findings)."""
    import paddle_tpu as paddle
    from paddle_tpu.static.analysis import Severity, run_analysis

    paddle.seed(2024)  # explicit seed: stochastic recordings are reproducible
    prog, model = FAMILIES[name]()
    context = {
        "targets": getattr(prog, "_outputs", None),
        "parameters": list(model.parameters()),
        # recording may attach traced callables (the serving mega-step fn)
        # for the PT-TRACE-002/004 source scans
        "static_fns": list(getattr(prog, "_static_fns", ())),
    }
    if defect is not None:
        context = inject(defect, prog, model, context)
    report = run_analysis(
        prog,
        targets=context.get("targets"),
        parameters=context.get("parameters"),
        executors=context.get("executors", ()),
        static_fns=context.get("static_fns", ()),
        borrow_fns=context.get("borrow_fns", ()),
    )
    floor = Severity.ERROR if fail_on == "error" else Severity.WARNING
    return prog, report, report.at_least(floor)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--family", choices=sorted(FAMILIES), default=None,
                    help="lint one family (default: all)")
    ap.add_argument("--inject", choices=DEFECTS, default=None,
                    help="plant one seeded defect (lints --family or bert)")
    ap.add_argument("--fail-on", choices=("error", "warning"),
                    default="error")
    ap.add_argument("--selftest", action="store_true",
                    help="verify every injection class flips the gate")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print warning/info findings")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest(args.family or "bert")

    families = [args.family] if args.family else sorted(FAMILIES)
    if args.inject:
        families = [args.family or "bert"]

    rc, linted = 0, 0
    for name in families:
        prog, report, gate = lint_family(name, defect=args.inject,
                                         fail_on=args.fail_on)
        linted += 1
        shown = gate if not args.verbose else list(report)
        for d in shown:
            print(f"{name}: {d.format()}")
        status = "FAIL" if gate else "ok"
        print(f"[{status}] {name}: {prog.num_ops} ops, "
              f"{len(report.errors())} error(s), "
              f"{len(report.warnings())} warning(s)")
        if gate:
            rc = 1
    print(f"LINTED {linted} program(s): "
          f"{'CLEAN' if rc == 0 else 'FINDINGS AT GATE SEVERITY'}")
    return rc


def selftest(family):
    """Every defect class must flip the gate with its expected code; the
    clean program must not (harness: tools/_selftest.py)."""
    h = _selftest.Harness("LINT")
    _, clean_report, clean_gate = lint_family(family)
    if clean_gate:
        print(f"SELFTEST FAIL: clean '{family}' has gate findings:")
        for d in clean_gate:
            print("  " + d.format())
        return 1
    print(f"clean {family}: ok ({len(clean_report)} sub-gate finding(s))")
    for defect in DEFECTS:
        # lint_family seeds (paddle.seed) before recording; the
        # unseeded_stochastic inject() un-seeds again afterwards itself.
        # host_sync is a WARNING-severity class (PT-TRACE-004): it must
        # flip the gate at --fail-on warning, the stricter operator mode
        _, report, gate = lint_family(
            family, defect=defect,
            fail_on="warning" if defect == "host_sync" else "error")
        code = EXPECTED_CODE[defect]
        hit = [d for d in gate if d.code == code]
        if hit:
            h.case(f"inject {defect}", True,
                   f"detected {code} — {hit[0].message[:80]}")
        else:
            h.case(f"inject {defect}", False,
                   f"wanted {code}, gate codes: "
                   f"{sorted({d.code for d in gate})}")
    return h.finish(
        f"SELFTEST OK: {len(DEFECTS)} defect classes detected, "
        "clean program lints clean",
        "SELFTEST FAIL: {failures} defect class(es) undetected")


if __name__ == "__main__":
    sys.exit(main())
