"""Telemetry scrape gate (docs/OBSERVABILITY.md).

Two modes:

- ``--url http://host:port/metrics`` — scrape a live endpoint, parse it,
  and print a per-family summary (operator smoke tool).
- ``--selftest`` — CI gate (tests/test_ci_gates.py, beside lint_graph and
  fault_drill): build a tiny 1-replica fleet (FleetRouter →
  ServingSupervisor → prefix-cache ContinuousBatchingEngine) with a
  TraceRecorder and a MetricsServer on an ephemeral port, put it under a
  real serving load, scrape over HTTP, and assert

  1. the scrape parses as Prometheus text and carries the engine / pool /
     radix / retry / guard / fleet / serving-SLO metric families,
  2. a traced request exports a Perfetto-loadable chrome-trace with a
     complete submit → admit → first_token → finish span chain and every
     submitted request reaching exactly ONE terminal span,
  3. the SLO summary computes finite TTFT percentiles from the
     histograms.

Exit code 0 on success, 1 naming the first failed check.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request

import _selftest

ROOT = _selftest.bootstrap(jax_cpu=False)   # selftest() defaults the env
_H = _selftest.Harness("SCRAPE")

#: metric families a serving deployment must expose (one representative
#: per source collector — the full catalogue is docs/OBSERVABILITY.md)
REQUIRED_FAMILIES = (
    # engine (ContinuousBatchingEngine.stats + schedule state)
    "pt_engine_queue_depth",
    "pt_engine_scheduled_tokens_total",
    "pt_engine_hit_tokens",
    # paged-KV pool + radix prefix cache occupancy
    "pt_pool_blocks_total",
    "pt_pool_free_blocks",
    "pt_radix_cached_blocks",
    # retry_call registry (distributed/resilience/retry.py)
    "pt_retry_attempts_total",
    # numeric guard escalation surface
    "pt_guard_health_events_total",
    # fleet router
    "pt_fleet_submitted",
    "pt_fleet_replica_load",
    # supervisor recovery stats
    "pt_supervisor_recoveries",
    # serving SLO histograms (TraceRecorder)
    "pt_serving_time_to_first_token_ms",
    "pt_serving_requests_submitted_total",
    # tracer health (a saturated recorder under-reports TTFT tails)
    "pt_tracer_dropped_total",
    "pt_tracer_gc_total",
    # disaggregated-tier KV migration (inference/disagg.py — counters and
    # the wall-time histogram register on every TraceRecorder and render
    # at zero, so a non-migrating fleet still exposes the families)
    "pt_migration_total",
    "pt_migration_pages_total",
    "pt_migration_failures_total",
    "pt_migration_time_ms",
    # process-per-replica fleet transport (inference/procfleet — the
    # procfleet_collector renders spawn/reap/heartbeat at zero on an
    # in-process fleet, so the families are REQUIRED unconditionally;
    # on a ProcFleetRouter it additionally fetches every live worker's
    # own /metrics endpoint and merges its families under replica=i
    # labels — docs/OBSERVABILITY.md remote-scrape topology)
    "pt_procfleet_spawned_total",
    "pt_procfleet_reaped_total",
    "pt_procfleet_heartbeats_total",
    "pt_procfleet_workers_alive",
    # transport seam (procfleet/transport.py): retryable wire timeouts,
    # hedged KV migrations and the per-replica breaker gauge — rendered
    # at zero over an in-process fleet like the families above
    "pt_transport_retries",
    "pt_transport_hedges",
    "pt_transport_breaker_state",
    # speculative decode + int8 KV block format (docs/SERVING.md): the
    # engine collector renders these at zero on non-spec / fp engines, so
    # the families are REQUIRED unconditionally
    "pt_spec_proposed_total",
    "pt_spec_accepted_total",
    "pt_spec_acceptance_rate",
    "pt_kv_quant_blocks",
    # mesh-sharded serving (docs/SERVING.md "Sharded serving"): the
    # engine collector renders tp width 1 / zero collective bytes on
    # unsharded engines, so the families are REQUIRED unconditionally
    "pt_serving_mesh_shape",
    "pt_serving_collective_bytes_total",
    "pt_serving_mesh_decode_steps_total",
    # elastic mesh degrade (docs/RESILIENCE.md "Elastic serving mesh"):
    # the supervisor collector renders both at zero on never-degraded
    # supervisors, so the families are REQUIRED unconditionally
    "pt_serving_mesh_reshards_total",
    "pt_serving_mesh_degraded",
    # checkpoint lifecycle (distributed/resilience/lifecycle.py — the
    # checkpoint_collector renders generation/publish counters at zero and
    # the phase gauge at "idle" with no publisher constructed, so the
    # families are REQUIRED unconditionally)
    "pt_checkpoint_generation",
    "pt_checkpoint_publish_total",
    "pt_checkpoint_publish_failures",
    "pt_lifecycle_phase",
)

#: the span chain a served request must produce, in order
REQUIRED_CHAIN = ("submit", "admit", "first_token", "finish")


fail = _H.fail_now                  # shared harness (tools/_selftest.py)


def check_families(text: str, required=REQUIRED_FAMILIES) -> int:
    from paddle_tpu.observability import parse_prometheus_text

    fams = parse_prometheus_text(text)      # raises on malformed lines
    missing = [name for name in required if name not in fams]
    if missing:
        fail(f"metric families missing from scrape: {missing}")
    for name, fam in fams.items():
        if not fam.samples:
            fail(f"family {name} rendered with no samples")
        if fam.kind == "histogram":
            if not any(s[0] == "_bucket" and s[1].get("le") == "+Inf"
                       for s in fam.samples):
                fail(f"histogram {name} has no +Inf bucket")
            if not any(s[0] == "_count" for s in fam.samples):
                fail(f"histogram {name} has no _count sample")
    return len(fams)


def check_trace(doc: dict, rids) -> int:
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail("chrome trace has no traceEvents list")
    for e in events:
        if not isinstance(e, dict) or "name" not in e or "ph" not in e:
            fail(f"malformed trace event: {e!r}")
        if "ts" not in e or not isinstance(e["ts"], (int, float)):
            fail(f"trace event without numeric ts: {e!r}")
        if e["ph"] == "X" and not isinstance(e.get("dur"), (int, float)):
            fail(f"complete span without dur: {e!r}")
    for rid in rids:
        names = [e["name"] for e in events if e.get("tid") == rid]
        it = iter(names)
        if not all(step in it for step in REQUIRED_CHAIN):
            fail(f"rid {rid}: span chain {names} missing ordered "
                 f"{REQUIRED_CHAIN}")
        terminals = [n for n in names
                     if n in ("finish", "evict", "shed", "fail")]
        if len(terminals) != 1:
            fail(f"rid {rid}: expected exactly one terminal span, got "
                 f"{terminals}")
    return len(events)


def selftest() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.fleet import FleetConfig, FleetRouter
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              Request)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import (MetricsRegistry, MetricsServer,
                                          TraceRecorder, checkpoint_collector,
                                          fleet_collector, guard_collector,
                                          procfleet_collector,
                                          retry_collector, tracer_collector)

    paddle.seed(11)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    registry = MetricsRegistry()
    tracer = TraceRecorder(registry=registry)
    registry.register_collector(retry_collector())
    registry.register_collector(guard_collector())
    registry.register_collector(tracer_collector(tracer))
    registry.register_collector(checkpoint_collector())

    def build():
        return ContinuousBatchingEngine(
            model, max_batch=2, max_len=32, page_size=8, block_size=2,
            prefix_cache=True)

    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as tmp:
        fleet = FleetRouter(build, tmp, num_replicas=1, tracer=tracer,
                            config=FleetConfig(brownout_depth=10 ** 9))
        registry.register_collector(fleet_collector(fleet))
        registry.register_collector(procfleet_collector(fleet))
        server = MetricsServer(registry, port=0)
        reqs = [Request(rng.integers(0, cfg.vocab_size, (8,))
                        .astype(np.int32), max_new_tokens=4, seed=100 + i)
                for i in range(4)]
        for r in reqs:
            fleet.submit(r)
        # scrape MID-LOAD once (the endpoint must answer while the engine
        # steps), then drain and scrape the settled state
        fleet.step()
        mid = urllib.request.urlopen(server.url, timeout=10).read()
        if b"pt_engine_queue_depth" not in mid:
            fail("mid-load scrape missing engine families")
        fleet.run_until_done(max_steps=2000)
        if not all(r.done and not r.failed for r in reqs):
            fail("serving wave did not complete cleanly")
        body = urllib.request.urlopen(server.url, timeout=10).read()
        hz = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=10).read()
        if hz != b"ok":
            fail("/healthz did not answer ok")
        server.close()
        fleet.close()

    n_fams = check_families(body.decode("utf-8"))

    trace_path = os.path.join(tempfile.gettempdir(),
                              f"pt_scrape_selftest_{os.getpid()}.json")
    tracer.export_chrome(trace_path)
    try:
        with open(trace_path) as f:
            doc = json.load(f)      # must round-trip as plain JSON
    finally:
        os.unlink(trace_path)
    n_events = check_trace(doc, [r.rid for r in reqs])
    if tracer.incomplete():
        fail(f"unterminated request lifecycles: {tracer.incomplete()}")

    slo = tracer.slo_summary()
    for key in ("p50_time_to_first_token_ms", "p99_time_to_first_token_ms"):
        v = slo.get(key)
        if not (isinstance(v, (int, float)) and v >= 0):
            fail(f"SLO summary {key} not computed: {v!r}")
    print(f"SCRAPE SELFTEST OK: {n_fams} metric families over HTTP, "
          f"{n_events} trace events, complete "
          f"{'->'.join(REQUIRED_CHAIN)} chains for {len(reqs)} requests, "
          f"p50/p99 TTFT {slo['p50_time_to_first_token_ms']}/"
          f"{slo['p99_time_to_first_token_ms']} ms")
    return 0


def main(argv) -> int:
    if "--selftest" in argv:
        return selftest()
    url = None
    for i, a in enumerate(argv):
        if a == "--url" and i + 1 < len(argv):
            url = argv[i + 1]
    if url is None:
        print(__doc__)
        return 2
    body = urllib.request.urlopen(url, timeout=10).read().decode("utf-8")
    from paddle_tpu.observability import parse_prometheus_text

    fams = parse_prometheus_text(body)
    for name in sorted(fams):
        fam = fams[name]
        print(f"{name} [{fam.kind}] {len(fam.samples)} sample(s)")
    print(f"OK: {len(fams)} families")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
