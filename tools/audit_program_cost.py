"""Program-cost gate (PT-COST — docs/STATIC_ANALYSIS.md): trace every
registered hot-path program (NO XLA compile — pure ``make_jaxpr`` through
``static.analysis.trace_to_program``) and audit its cost manifest against
the reviewed baseline (tools/program_cost_baseline.json).

What PR 9's PT-RACE gate is for thread-safety, this is for DEVICE-PROGRAM
COST: a machine-independent CI invariant over the programs the serving and
training hot paths actually dispatch — the fused mega-step (traced at TWO
slot widths for the slot-scaling law), the packed prefill chunk, the hapi
train step, and the PR 12 KV-migration scatters. The audit catches, before
any hardware run:

- PT-COST-001  a bf16 path silently widened to f32 (weak-type accident /
               upcast-census drift)
- PT-COST-002  a host-sync primitive inside a jitted program (jaxpr-level
               sibling of the PT-TRACE-004 source scan)
- PT-COST-003  a step-to-step carry the jitted program stopped donating
               (read off the traced pjit's ``donated_invars``)
- PT-COST-004  scatter/gather equation counts past the recorded contract
- PT-COST-005  program text or FLOPs growing superlinearly in slot count

Exit 0 iff every error-severity finding is fixed or covered by a reviewed
waiver WITH a justification (the PT-RACE baseline discipline — an
unreviewed defect can only make the gate red, never silently pass).

Usage:
    JAX_PLATFORMS=cpu python tools/audit_program_cost.py      # full gate
    python tools/audit_program_cost.py --program mega_step@8
    python tools/audit_program_cost.py --write-baseline       # refresh
    python tools/audit_program_cost.py --inject lost_donation # seeded demo
    python tools/audit_program_cost.py --selftest             # all 5 classes
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import _selftest

ROOT = _selftest.bootstrap()

BASELINE_PATH = os.path.join(ROOT, "tools", "program_cost_baseline.json")

import jax  # noqa: E402
import numpy as np  # noqa: E402

DEFECTS = ("f32_upcast", "host_sync", "lost_donation", "scatter_drift",
           "superlinear_scaling")

EXPECTED_CODE = {
    "f32_upcast": "PT-COST-001",
    "host_sync": "PT-COST-002",
    "lost_donation": "PT-COST-003",
    "scatter_drift": "PT-COST-004",
    "superlinear_scaling": "PT-COST-005",
}

#: slot widths the mega-step is traced at for the PT-COST-005 scaling law
SCALING_WIDTHS = (8, 32)


def _spec(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


# ---------------------------------------------------------------------------
# hot-path recorders — each returns (Program, HotPathSpec)
# ---------------------------------------------------------------------------

def record_mega_step(slots: int, mesh: int = 0):
    """The fused decode mega-step EXACTLY as the engine dispatches it:
    traced through ``_build_mega_jit()`` (donation included, so the audited
    ``donated_invars`` are the production program's), every buffer — params,
    kv pools, tables, device step state, sampling vectors — a named input.

    ``mesh=N`` traces the tp-sharded shard_map variant over an ABSTRACT
    tp mesh (no devices needed — docs/SERVING.md "Sharded serving"), so the
    manifest covers the column-parallel program the sharded engine really
    dispatches, all_gathers included."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              MeshConfig, PrefixCacheConfig)
    from paddle_tpu.jit.api import _collect_state
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.cost import HotPathSpec

    paddle.seed(11)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    m = LlamaForCausalLM(cfg)
    eng = ContinuousBatchingEngine(
        m, max_batch=slots, max_len=32, page_size=8, block_size=2,
        fused=True, prefix_cache=PrefixCacheConfig(prefill_chunk=8),
        mesh=MeshConfig(tp=mesh, abstract=True) if mesh else None)
    jf = eng._build_mega_jit()
    names, tensors = _collect_state(m)
    param_structs = [_spec(t._data.shape, t._data.dtype) for t in tensors]
    n_p = len(param_structs)
    kv = eng.caches["kv"]
    L = len(kv)
    B, maxp = eng.max_batch, eng._maxp

    def flat(*args):
        params, i = list(args[:n_p]), n_p
        toks = args[i]
        i += 1
        kvl = [(args[i + 2 * l], args[i + 2 * l + 1]) for l in range(L)]
        i += 2 * L
        tables, pos, act, seeds, temps, tops, topks = args[i:i + 7]
        return jf(params, toks, kvl, tables, pos, act, seeds, temps, tops,
                  topks, n_steps=2, do_sample=True)

    kv_specs = [_spec(a.shape, a.dtype) for pair in kv for a in pair]
    kv_names = [f"kv{l}_{t}" for l in range(L) for t in ("k", "v")]
    ins = ([_spec((B,), np.int32)] + kv_specs +
           [_spec((B, maxp), np.int32), _spec((B,), np.int32),
            _spec((B,), np.bool_), _spec((B,), np.int32),
            _spec((B,), np.float32), _spec((B,), np.float32),
            _spec((B,), np.int32)])
    in_names = (["toks"] + kv_names +
                ["tables", "pos", "act", "seeds", "temps", "tops", "topks"])
    prog = trace_to_program(flat, *ins, input_names=in_names,
                            param_structs=param_structs, param_names=names,
                            param_tensors=tensors)
    kv_lo = n_p + 1
    kv_hi = kv_lo + 2 * L
    fam = f"mega_step_tp{mesh}" if mesh else "mega_step"
    spec = HotPathSpec(
        f"{fam}@{slots}", slots=slots,
        carries={"kv": (kv_lo, kv_hi), "pos": (kv_hi + 1, kv_hi + 2)},
        notes="fused decode mega-step (serving.py), n_steps=2, sampled" +
              (f", column-parallel tp={mesh} shard_map" if mesh else ""))
    return prog, spec


def record_spec_verify(slots: int, mesh: int = 0):
    """The speculative verify mega-step (docs/SERVING.md "Speculative
    decode") EXACTLY as the engine dispatches it: traced through
    ``_build_spec_jit()`` so the audited ``donated_invars`` cover the real
    carry set — kv pools, positions AND the drafter's history ring/length.
    Traced at both SCALING_WIDTHS for the <=linear slot law; the in-graph
    draft -> K-wide verify -> accept/rollback scatters are census-pinned
    by the baseline contract (PT-COST-004)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              MeshConfig, PrefixCacheConfig,
                                              SpecConfig)
    from paddle_tpu.jit.api import _collect_state
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.cost import HotPathSpec

    paddle.seed(11)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    m = LlamaForCausalLM(cfg)
    eng = ContinuousBatchingEngine(
        m, max_batch=slots, max_len=32, page_size=8, block_size=2,
        fused=True, speculative=SpecConfig(k=3, ngram=2, history=16),
        prefix_cache=PrefixCacheConfig(prefill_chunk=8),
        mesh=MeshConfig(tp=mesh, abstract=True) if mesh else None)
    jf = eng._build_spec_jit()
    names, tensors = _collect_state(m)
    param_structs = [_spec(t._data.shape, t._data.dtype) for t in tensors]
    n_p = len(param_structs)
    kv = eng.caches["kv"]
    L = len(kv)
    B, maxp, H = eng.max_batch, eng._maxp, eng._spec.history

    def flat(*args):
        params, i = list(args[:n_p]), n_p
        toks = args[i]
        i += 1
        kvl = [(args[i + 2 * l], args[i + 2 * l + 1]) for l in range(L)]
        i += 2 * L
        tables, pos, act, hist, hlen, caps = args[i:i + 6]
        return jf(params, toks, kvl, tables, pos, act, hist, hlen, caps)

    kv_specs = [_spec(a.shape, a.dtype) for pair in kv for a in pair]
    kv_names = [f"kv{l}_{t}" for l in range(L) for t in ("k", "v")]
    ins = ([_spec((B,), np.int32)] + kv_specs +
           [_spec((B, maxp), np.int32), _spec((B,), np.int32),
            _spec((B,), np.bool_), _spec((B, H), np.int32),
            _spec((B,), np.int32), _spec((B,), np.int32)])
    in_names = (["toks"] + kv_names +
                ["tables", "pos", "act", "hist", "hlen", "caps"])
    prog = trace_to_program(flat, *ins, input_names=in_names,
                            param_structs=param_structs, param_names=names,
                            param_tensors=tensors)
    kv_lo = n_p + 1
    kv_hi = kv_lo + 2 * L
    fam = f"spec_verify_tp{mesh}" if mesh else "spec_verify"
    spec = HotPathSpec(
        f"{fam}@{slots}", slots=slots,
        carries={"kv": (kv_lo, kv_hi), "pos": (kv_hi + 1, kv_hi + 2),
                 "hist": (kv_hi + 3, kv_hi + 4),
                 "hlen": (kv_hi + 4, kv_hi + 5)},
        notes="speculative verify mega-step (serving.py), k=3 draft + "
              "bonus, n-gram drafter in-graph" +
              (f", column-parallel tp={mesh} shard_map" if mesh else ""))
    return prog, spec


def record_prefill_chunk(mesh: int = 0):
    """The packed prefill-chunk program (``_chunk_fn`` — shared by the
    legacy chunked path and the fused ``_run_pack``), at a 4-row bucket."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                              MeshConfig, PrefixCacheConfig)
    from paddle_tpu.jit.api import _collect_state
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.cost import HotPathSpec

    paddle.seed(11)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    m = LlamaForCausalLM(cfg)
    eng = ContinuousBatchingEngine(
        m, max_batch=8, max_len=32, page_size=8, block_size=2, fused=True,
        prefix_cache=PrefixCacheConfig(prefill_chunk=8),
        mesh=MeshConfig(tp=mesh, abstract=True) if mesh else None)
    g, C = 4, eng._chunk_tokens
    jf = eng._chunk_fn(g)
    names, tensors = _collect_state(m)
    param_structs = [_spec(t._data.shape, t._data.dtype) for t in tensors]
    n_p = len(param_structs)
    kv = eng.caches["kv"]
    L = len(kv)

    def flat(*args):
        params, i = list(args[:n_p]), n_p
        ids = args[i]
        i += 1
        kvl = [(args[i + 2 * l], args[i + 2 * l + 1]) for l in range(L)]
        i += 2 * L
        rows, starts = args[i], args[i + 1]
        return jf(params, ids, kvl, rows, starts)

    kv_specs = [_spec(a.shape, a.dtype) for pair in kv for a in pair]
    kv_names = [f"kv{l}_{t}" for l in range(L) for t in ("k", "v")]
    ins = ([_spec((g, C), np.int32)] + kv_specs +
           [_spec((g, eng._maxp), np.int32), _spec((g,), np.int32)])
    prog = trace_to_program(
        flat, *ins, input_names=["ids"] + kv_names + ["rows", "starts"],
        param_structs=param_structs, param_names=names,
        param_tensors=tensors)
    kv_lo = n_p + 1
    spec = HotPathSpec(
        f"prefill_chunk_tp{mesh}" if mesh else "prefill_chunk",
        carries={"kv": (kv_lo, kv_lo + 2 * L)},
        notes="packed prefill chunk (_chunk_fn g=4), chunk=8 tokens" +
              (f", column-parallel tp={mesh} shard_map" if mesh else ""))
    return prog, spec


def record_train_step():
    """The hapi jitted train step — forward + loss + backward + Adam update
    in one program; params/opt-state are the carries (hapi donates both via
    ``donate_argnums=(0, 1)`` — losing that shows up here)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.framework.random import next_key
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.cost import HotPathSpec

    paddle.seed(7)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 8))
    mdl = Model(net)
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    mdl.prepare(opt, paddle.nn.CrossEntropyLoss())
    mdl._build_train_step()          # builds mdl._jitted (donated)
    jf = mdl._jitted
    tensors = mdl._state_tensors
    state_structs = [_spec(t._data.shape, t._data.dtype) for t in tensors]
    n_s = len(state_structs)
    key = next_key()

    def flat(*args):
        state = list(args[:n_s])
        x, y = args[n_s], args[n_s + 1]
        # opt_state={} is the real first-call signature; key/lr/step ride
        # as trace constants (they are not cost-relevant inputs)
        return jf(state, {}, [x], [y], key, jnp.float32(1e-3),
                  jnp.int32(1))

    prog = trace_to_program(
        flat, _spec((8, 16), np.float32), _spec((8,), np.int64),
        input_names=["x", "labels"],
        param_structs=state_structs,
        param_names=[f"state_{i}" for i in range(n_s)],
        param_tensors=list(tensors))
    spec = HotPathSpec("train_step", carries={"state": (0, n_s)},
                       notes="hapi Model train step (MLP + CE + Adam)")
    return prog, spec


def record_migration():
    """The PR 12 KV-migration device programs (inference/disagg.py via
    ops/paged_attention.py): the per-layer page gather that exports a
    chain and ``scatter_chain_pages`` that imports it. These dispatch
    EAGERLY on the control plane (once per request, never on the decode
    hot path) — so no pjit wrapper exists and the kv carry is undonated by
    design: the source pool keeps serving concurrently-decoding slots
    while the bytes are in flight. That PT-COST-003 finding is WAIVED in
    the baseline with this justification. tools/lint_graph.py's
    ``migration`` family reuses THIS recorder, so graph-lint and cost
    coverage stay one program."""
    from paddle_tpu.ops.paged_attention import scatter_chain_pages
    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.cost import HotPathSpec

    P, H, PG, D, n = 8, 2, 8, 4, 3

    def roundtrip(k0, v0, k1, v1, src, dst):
        kv = [(k0, v0), (k1, v1)]
        pages = [(k[src], v[src]) for k, v in kv]   # device half of the
        #                                             gather_chain_pages export
        out = scatter_chain_pages(kv, dst, pages)
        return tuple(x for pair in out for x in pair)

    pool = _spec((P, H, PG, D), np.float32)
    prog = trace_to_program(
        roundtrip, pool, pool, pool, pool, _spec((n,), np.int32),
        _spec((n,), np.int32),
        input_names=["k0", "v0", "k1", "v1", "src_blocks", "dst_blocks"])
    spec = HotPathSpec("migration", carries={"kv": (0, 4)},
                       notes="KV-chain migration gather+scatter (eager "
                             "control-plane dispatch)")
    return prog, spec


def record_all(only=None):
    out = {}
    for slots in SCALING_WIDTHS:
        out[f"mega_step@{slots}"] = lambda s=slots: record_mega_step(s)
        out[f"spec_verify@{slots}"] = lambda s=slots: record_spec_verify(s)
    out["prefill_chunk"] = record_prefill_chunk
    # mesh-sharded serving variants (abstract tp=2 mesh; one width — the
    # slot-scaling law is carried by the unsharded family above)
    out["mega_step_tp2@8"] = lambda: record_mega_step(8, mesh=2)
    out["spec_verify_tp2@8"] = lambda: record_spec_verify(8, mesh=2)
    out["prefill_chunk_tp2"] = lambda: record_prefill_chunk(mesh=2)
    out["train_step"] = record_train_step
    out["migration"] = record_migration
    if only:
        if only not in out:
            raise SystemExit(f"unknown program {only!r} "
                             f"(choose: {sorted(out)})")
        out = {only: out[only]}
    return {name: rec() for name, rec in out.items()}


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str = BASELINE_PATH):
    """Returns (programs: {name: manifest dict}, waivers: {id: just}).
    Waiver entries without a justification are rejected — the file is a
    review record, not a mute button (PT-RACE discipline)."""
    if not os.path.exists(path):
        return {}, {}
    with open(path) as f:
        doc = json.load(f)
    waivers = {}
    for entry in doc.get("waivers", ()):
        fid = entry.get("id")
        just = (entry.get("justification") or "").strip()
        if not fid or not just:
            raise SystemExit(
                f"baseline waiver {entry!r} is missing an id or a "
                "justification — every suppression must say why")
        waivers[fid] = just
    return doc.get("programs", {}), waivers


def write_baseline(manifests, waivers, path: str = BASELINE_PATH):
    doc = {
        "_comment": [
            "PT-COST manifests + reviewed waivers",
            "(docs/STATIC_ANALYSIS.md, tools/audit_program_cost.py).",
            "Counts are CONTRACTS: scatter/gather/host-sync/upcast may",
            "only grow through a reviewed refresh. Every waiver needs a",
            "justification; stale waivers are reported by the gate —",
            "remove them when the code is fixed.",
        ],
        "programs": {k: m.to_dict() for k, m in sorted(manifests.items())},
        "waivers": [{"id": fid, "justification": waivers[fid]}
                    for fid in sorted(waivers)],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"baseline written: {path} ({len(manifests)} program(s), "
          f"{len(waivers)} waiver(s))")


# ---------------------------------------------------------------------------
# audit driver (shared by the real gate and the selftest fixtures)
# ---------------------------------------------------------------------------

def audit(programs, base_programs, waivers, skip_contract=False,
          report_stale=True, verbose=False):
    """Audit ``programs`` ({name: (Program, HotPathSpec)}). Returns
    (exit_code, manifests, gate_findings). ``report_stale=False`` for
    subset runs (``--program``): a waiver for an unaudited program is not
    stale, and telling the operator to delete it would lose the review."""
    from paddle_tpu.static.cost import (check_contract, check_donation,
                                        check_dtype_promotion,
                                        check_host_sync, check_slot_scaling,
                                        compute_manifest)

    manifests, findings = {}, []
    for name, (prog, spec) in programs.items():
        man = compute_manifest(prog, name=name, spec=spec)
        manifests[name] = man
        findings += check_dtype_promotion(prog, name)
        findings += check_host_sync(prog, name)
        findings += check_donation(man)
        if not skip_contract:
            findings += check_contract(man, base_programs.get(name))
    # slot-scaling law over every name traced at >=2 widths
    groups = {}
    for name, man in manifests.items():
        if man.slots and "@" in name:
            groups.setdefault(name.split("@")[0], []).append(man)
    for fam, group in sorted(groups.items()):
        if len(group) >= 2:
            findings += check_slot_scaling(group)
    gate, suppressed = [], []
    for d in findings:
        fid = getattr(d, "finding_id", None)
        (suppressed if fid in waivers else gate).append(d)
    for name, man in sorted(manifests.items()):
        scal = (man.scaling or {}).get("verdict", "-")
        print(f"[manifest] {name}: {man.num_eqns} eqns, "
              f"{man.flops_total:.3g} flops, {man.bytes_total:.3g} B, "
              f"AI {man.arithmetic_intensity:.2f}, "
              f"scatter/gather {man.scatter_ops}/{man.gather_ops}, "
              f"host-sync {man.host_sync_eqns}, "
              f"upcasts {man.upcast_converts}, "
              f"donated {sorted(man.donation.get('donated', []))} "
              f"missing {sorted(man.donation.get('missing', []))}, "
              f"scaling {scal}")
    for d in gate:
        print(f"{d.format()}\n    id: {getattr(d, 'finding_id', '')}")
    for d in suppressed:
        fid = getattr(d, "finding_id", "")
        print(f"[waived] {fid}: {waivers[fid]}")
    if report_stale:
        all_ids = {getattr(d, "finding_id", None) for d in findings}
        for fid in sorted(set(waivers) - all_ids):
            print(f"[stale waiver — remove it] {fid}")
    status = "FINDINGS AT GATE SEVERITY" if gate else "CLEAN"
    print(f"PROGRAM COST AUDIT {'FAIL' if gate else 'OK'}: "
          f"{len(manifests)} program(s), {len(findings)} finding(s), "
          f"{len(suppressed)} waived, {len(gate)} at gate severity — "
          f"{status}")
    return (1 if gate else 0), manifests, gate


# ---------------------------------------------------------------------------
# seeded-defect fixtures (synthetic, tiny — no model builds, no compiles)
# ---------------------------------------------------------------------------

def _fixture(width=8, donate=True, extra_scatter=False, upcast=False,
             sync=False, quadratic=False):
    """One tiny jitted step over (kv[16,8] f32, x[width,8] bf16) with a
    donated kv carry, one scatter, and a weak-typed scalar — each defect
    class is one knob away."""
    import jax.numpy as jnp

    from paddle_tpu.static.analysis import trace_to_program
    from paddle_tpu.static.cost import HotPathSpec

    def step(kv, x):
        kv = kv.at[0].add(x.sum(0).astype(kv.dtype))       # the one scatter
        if extra_scatter:
            kv = kv.at[1].add(x.sum(0).astype(kv.dtype))   # contract drift
        y = jnp.tanh(x) * 2.0            # weak-typed python scalar: stays bf16
        if upcast:
            y = y * np.float32(2.0)      # f32 SCALAR constant: promotes
        if quadratic:
            # an O(width^2) term: the accidental slot x slot interaction
            y = y + (x[:, :1] @ x[:, :1].T) @ x
        if sync:
            y = y + jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(y.shape, y.dtype), y)
        return kv, y.sum()

    jf = jax.jit(step, donate_argnums=(0,) if donate else ())
    prog = trace_to_program(
        lambda kv, x: jf(kv, x), _spec((16, 8), np.float32),
        _spec((width, 8), "bfloat16"), input_names=["kv", "x"])
    spec = HotPathSpec(f"fixture@{width}", slots=width,
                       carries={"kv": (0, 1)})
    return prog, spec


def _fixture_pair(**kw):
    return {f"fixture@{w}": _fixture(width=w, **kw) for w in (8, 32)}


def _fixture_baseline():
    from paddle_tpu.static.cost import compute_manifest

    base = {}
    for name, (prog, spec) in _fixture_pair().items():
        base[name] = compute_manifest(prog, name=name, spec=spec).to_dict()
    return base


def inject(defect, base_programs):
    """Programs for one seeded defect class, audited against the CLEAN
    fixture baseline."""
    if defect == "f32_upcast":
        return _fixture_pair(upcast=True)
    if defect == "host_sync":
        return _fixture_pair(sync=True)
    if defect == "lost_donation":
        return _fixture_pair(donate=False)
    if defect == "scatter_drift":
        return _fixture_pair(extra_scatter=True)
    if defect == "superlinear_scaling":
        return _fixture_pair(quadratic=True)
    raise SystemExit(f"unknown defect {defect!r} (choose: {DEFECTS})")


def selftest():
    """The clean fixture must audit clean against its own baseline; every
    seeded defect class must flip the exit code with its expected code
    (harness: tools/_selftest.py — pinned in tests/test_ci_gates.py)."""
    h = _selftest.Harness("COST")
    base = _fixture_baseline()
    rc, _, gate = audit(_fixture_pair(), base, waivers={})
    h.case("clean fixture", rc == 0, f"rc={rc}, {len(gate)} gate finding(s)")
    for defect in DEFECTS:
        want = EXPECTED_CODE[defect]
        rc, _, gate = audit(inject(defect, base), base, waivers={})
        hit = [d for d in gate if d.code == want]
        if rc == 1 and hit:
            h.case(f"inject {defect}", True,
                   f"detected {want} — {hit[0].message[:70]}")
        else:
            h.case(f"inject {defect}", False,
                   f"rc={rc}, wanted {want}, gate codes: "
                   f"{sorted({d.code for d in gate})}")
    # waiver discipline end-to-end: a waiver with a justification un-flips
    # exactly its finding; nothing else
    progs = inject("lost_donation", base)
    rc_bad, _, gate = audit(progs, base, waivers={})
    fids = {getattr(d, "finding_id", "") for d in gate}
    rc_ok, _, _ = audit(progs, base,
                        waivers={fid: "selftest" for fid in fids})
    h.case("waiver un-flips the gate", rc_bad == 1 and rc_ok == 0,
           f"rc {rc_bad} -> {rc_ok} with {len(fids)} waiver(s)")
    return h.finish(
        f"COST SELFTEST OK: {len(DEFECTS)} defect classes detected, "
        "clean fixture audits clean, waiver discipline pinned",
        "COST SELFTEST FAIL: {failures} expectation(s) violated")


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--program", default=None,
                    help="audit one registered program (default: all)")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show everything; the "
                         "unbaselined-program finding still fires)")
    ap.add_argument("--inject", choices=DEFECTS, default=None,
                    help="audit the synthetic fixture seeded with one "
                         "defect class (must flip the exit code)")
    ap.add_argument("--selftest", action="store_true",
                    help="verify every defect class flips the gate")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record the current manifests as the baseline "
                         "(review the diff!)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.inject:
        base = _fixture_baseline()
        rc, _, _ = audit(inject(args.inject, base), base, waivers={})
        return rc

    base_programs, waivers = ({}, {}) if args.no_baseline \
        else load_baseline(args.baseline)
    programs = record_all(only=args.program)
    rc, manifests, gate = audit(programs, base_programs, waivers,
                                skip_contract=args.write_baseline,
                                report_stale=args.program is None,
                                verbose=args.verbose)
    if args.write_baseline:
        if args.program:
            raise SystemExit("--write-baseline needs the full program set")
        write_baseline(manifests, waivers, args.baseline)
    return rc


if __name__ == "__main__":
    sys.exit(main())
