"""API-compatibility gate (reference: tools/check_api_compatible.py).

Compares the live public surfaces against the frozen manifest
(tools/api_manifest.json). A symbol REMOVED from any surface fails the gate;
additions are allowed (and `--update` refreezes the manifest to include them).

Run:  python tools/check_api_compatible.py [--update]
Also enforced in CI via tests/test_ci_gates.py.
"""

from __future__ import annotations

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
MANIFEST = os.path.join(HERE, "api_manifest.json")


def live_surfaces():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.inference import procfleet as _procfleet
    from paddle_tpu.inference import serving as _serving
    from paddle_tpu.static import comm as _comm
    from paddle_tpu.static import concurrency as _concurrency
    from paddle_tpu.static import cost as _cost

    def names(mod):
        all_ = getattr(mod, "__all__", None)
        if all_:
            return sorted(set(all_))
        return sorted(n for n in dir(mod) if not n.startswith("_"))

    return {
        "paddle.inference.procfleet": names(_procfleet),
        "paddle.inference.serving": names(_serving),
        "paddle.observability": names(paddle.observability),
        "paddle.quantization": names(paddle.quantization),
        "paddle.static.comm": names(_comm),
        "paddle.static.concurrency": names(_concurrency),
        "paddle.static.cost": names(_cost),
        "paddle": names(paddle),
        "paddle.tensor_methods": sorted(
            n for n in dir(paddle.Tensor) if not n.startswith("_")),
        "paddle.nn": names(paddle.nn),
        "paddle.nn.functional": names(paddle.nn.functional),
        "paddle.linalg": names(paddle.linalg),
        "paddle.optimizer": names(paddle.optimizer),
        "paddle.distributed": names(paddle.distributed),
        "paddle.incubate.nn.functional": names(paddle.incubate.nn.functional),
        "paddle.geometric": names(paddle.geometric),
        "paddle.incubate.asp": names(paddle.incubate.asp),
    }


def check(update: bool = False):
    live = live_surfaces()
    if update or not os.path.exists(MANIFEST):
        json.dump(live, open(MANIFEST, "w"), indent=0, sort_keys=True)
        print(f"manifest written: { {k: len(v) for k, v in live.items()} }")
        return []
    frozen = json.load(open(MANIFEST))
    problems = []
    for surface, want in frozen.items():
        have = set(live.get(surface, []))
        missing = sorted(set(want) - have)
        if missing:
            problems.append((surface, missing))
    return problems


if __name__ == "__main__":
    probs = check(update="--update" in sys.argv)
    for surface, missing in probs:
        print(f"API BREAK in {surface}: removed {missing}")
    sys.exit(1 if probs else 0)
