"""nn.Layer system + layer correctness tests (reference: test/legacy_test nn tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestLayerSystem:
    def test_parameters_registration(self):
        l = nn.Linear(4, 3)
        assert len(l.parameters()) == 2
        names = dict(l.named_parameters())
        assert "weight" in names and "bias" in names

    def test_sublayers(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        assert len(net.parameters()) == 4
        assert len(net.sublayers()) == 3

    def test_state_dict_roundtrip(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
        sd = net.state_dict()
        assert len(sd) == 4
        net2 = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
        net2.set_state_dict({k: v.numpy() for k, v in sd.items()})
        for (k1, p1), (k2, p2) in zip(net.named_parameters(), net2.named_parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy())

    def test_buffers(self):
        bn = nn.BatchNorm2D(4)
        buf_names = [n for n, _ in bn.named_buffers()]
        assert "_mean" in buf_names and "_variance" in buf_names
        assert "_mean" in bn.state_dict()

    def test_train_eval(self):
        net = nn.Sequential(nn.Dropout(0.5))
        assert net.training
        net.eval()
        assert not net[0].training

    def test_forward_hooks(self):
        l = nn.Linear(2, 2)
        calls = []
        h = l.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
        l(paddle.randn([1, 2]))
        assert calls
        h.remove()
        l(paddle.randn([1, 2]))
        assert len(calls) == 1

    def test_apply_and_to_dtype(self):
        net = nn.Linear(2, 2)
        net.bfloat16()
        assert net.weight.dtype == paddle.bfloat16
        net.float()
        assert net.weight.dtype == paddle.float32

    def test_layerlist(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4
        assert len(ll.parameters()) == 8


class TestFunctionalCorrectness:
    def test_linear(self):
        l = nn.Linear(4, 3)
        x = paddle.randn([2, 4])
        out = l(x)
        ref = x.numpy() @ l.weight.numpy() + l.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_conv2d_matches_manual(self):
        conv = nn.Conv2D(2, 3, 3, padding=1)
        x = paddle.randn([1, 2, 5, 5])
        out = conv(x)
        assert out.shape == [1, 3, 5, 5]
        # compare against scipy correlate on one output channel
        from scipy.signal import correlate

        xn = x.numpy()[0]
        w = conv.weight.numpy()
        ref00 = sum(correlate(xn[c], w[0, c], mode="same") for c in range(2)) + conv.bias.numpy()[0]
        np.testing.assert_allclose(out.numpy()[0, 0], ref00, rtol=1e-4, atol=1e-4)

    def test_conv_transpose_shape(self):
        deconv = nn.Conv2DTranspose(3, 2, 4, stride=2, padding=1)
        out = deconv(paddle.randn([1, 3, 8, 8]))
        assert out.shape == [1, 2, 16, 16]

    def test_pools(self):
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        mp = nn.MaxPool2D(2, 2)(x)
        np.testing.assert_allclose(mp.numpy()[0, 0], [[5, 7], [13, 15]])
        ap = nn.AvgPool2D(2, 2)(x)
        np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
        gap = nn.AdaptiveAvgPool2D(1)(x)
        np.testing.assert_allclose(gap.numpy()[0, 0], [[7.5]])

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = paddle.randn([4, 8])
        out = ln(x).numpy()
        np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)
        np.testing.assert_allclose(out.std(-1), 1, atol=1e-2)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(8)
        x = paddle.randn([4, 8])
        out = rn(x).numpy()
        xn = x.numpy()
        ref = xn / np.sqrt((xn**2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_batchnorm_train_updates_stats(self):
        bn = nn.BatchNorm2D(3, momentum=0.9)
        x = paddle.randn([8, 3, 4, 4]) * 2 + 5
        bn.train()
        bn(x)
        # momentum 0.9: running_mean ~= 0.1 * batch_mean(~5) = ~0.5
        assert abs(bn._mean.numpy().mean() - 0.5) < 0.1

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        out = gn(paddle.randn([2, 4, 3, 3]))
        assert out.shape == [2, 4, 3, 3]

    def test_embedding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(paddle.to_tensor(np.array([[1, 0, 3]])))
        assert out.shape == [1, 3, 4]
        np.testing.assert_allclose(out.numpy()[0, 1], np.zeros(4))

    def test_dropout_train_eval(self):
        d = nn.Dropout(0.5)
        x = paddle.ones([1000])
        train_out = d(x)
        assert abs(float(train_out.numpy().mean()) - 1.0) < 0.2
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), x.numpy())

    def test_activations(self):
        x = paddle.to_tensor(np.array([-2.0, 0.0, 2.0], np.float32))
        np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 2])
        np.testing.assert_allclose(nn.functional.gelu(x).numpy(),
                                   [-0.0455, 0.0, 1.9545], atol=1e-3)
        np.testing.assert_allclose(nn.functional.softmax(x).numpy().sum(), 1.0, rtol=1e-6)

    def test_losses(self):
        logits = paddle.to_tensor(np.array([[2.0, 1.0, 0.1]], np.float32))
        label = paddle.to_tensor(np.array([0]))
        loss = nn.CrossEntropyLoss()(logits, label)
        ref = -np.log(np.exp(2.0) / np.exp([2.0, 1.0, 0.1]).sum())
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-5)
        x, y = paddle.randn([4, 3]), paddle.randn([4, 3])
        np.testing.assert_allclose(
            float(nn.MSELoss()(x, y).numpy()), ((x.numpy() - y.numpy()) ** 2).mean(), rtol=1e-5
        )

    def test_cross_entropy_ignore_index(self):
        logits = paddle.randn([4, 5])
        label = paddle.to_tensor(np.array([1, -100, 2, -100]))
        loss = nn.functional.cross_entropy(logits, label, ignore_index=-100)
        l0 = nn.functional.cross_entropy(logits[0:1], label[0:1])
        l2 = nn.functional.cross_entropy(logits[2:3], label[2:3])
        np.testing.assert_allclose(float(loss.numpy()), (float(l0.numpy()) + float(l2.numpy())) / 2, rtol=1e-5)


class TestAttention:
    def test_sdpa_matches_reference(self):
        b, s, h, d = 2, 6, 2, 8
        q = paddle.randn([b, s, h, d])
        k = paddle.randn([b, s, h, d])
        v = paddle.randn([b, s, h, d])
        out = nn.functional.scaled_dot_product_attention(q, k, v, is_causal=True)
        qn, kn, vn = (t.numpy().transpose(0, 2, 1, 3) for t in (q, k, v))
        logits = qn @ kn.transpose(0, 1, 3, 2) / np.sqrt(d)
        mask = np.tril(np.ones((s, s), bool))
        logits = np.where(mask, logits, -1e9)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        ref = (probs @ vn).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3, atol=1e-4)

    def test_mha_layer(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 5, 16])
        out = mha(x)
        assert out.shape == [2, 5, 16]

    def test_transformer_encoder(self):
        enc_layer = nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0)
        enc = nn.TransformerEncoder(enc_layer, 2)
        out = enc(paddle.randn([2, 5, 16]))
        assert out.shape == [2, 5, 16]


class TestRNN:
    def test_lstm_shapes(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        out, (h, c) = lstm(paddle.randn([3, 7, 4]))
        assert out.shape == [3, 7, 8]
        assert h.shape == [2, 3, 8]

    def test_gru_bidirectional(self):
        gru = nn.GRU(4, 8, direction="bidirect")
        out, h = gru(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 16]

    def test_lstm_grad_flows(self):
        lstm = nn.LSTM(3, 4)
        x = paddle.randn([2, 5, 3])
        out, _ = lstm(x)
        out.sum().backward()
        assert lstm.parameters()[0].grad is not None
