"""Distribution tests (reference: test/distribution) — scipy-referenced
log_prob, moment-checked sampling, KL registry dispatch."""

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu import distribution as D

T = paddle.to_tensor


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(7)


def _logprob_close(dist, ref_logpdf, xs, rtol=1e-4, atol=1e-5):
    got = np.asarray(dist.log_prob(T(xs.astype(np.float32))).numpy())
    np.testing.assert_allclose(got, ref_logpdf(xs), rtol=rtol, atol=atol)


def test_laplace():
    d = D.Laplace(0.5, 2.0)
    xs = np.linspace(-3, 3, 7)
    _logprob_close(d, lambda x: st.laplace.logpdf(x, 0.5, 2.0), xs)
    s = d.sample([4000]).numpy()
    assert abs(s.mean() - 0.5) < 0.2
    np.testing.assert_allclose(float(d.variance), 8.0)
    # cdf/icdf roundtrip
    q = d.cdf(T(np.array([1.0], np.float32)))
    back = d.icdf(q)
    np.testing.assert_allclose(back.numpy(), [1.0], rtol=1e-4)


def test_cauchy_chi2_studentt():
    xs = np.linspace(0.5, 5, 6)
    _logprob_close(D.Cauchy(0.0, 1.5), lambda x: st.cauchy.logpdf(x, 0, 1.5), xs)
    _logprob_close(D.Chi2(3.0), lambda x: st.chi2.logpdf(x, 3), xs)
    _logprob_close(D.StudentT(5.0, 1.0, 2.0),
                   lambda x: st.t.logpdf(x, 5, 1.0, 2.0), xs)


def test_lognormal_gumbel():
    xs = np.linspace(0.2, 4, 6)
    _logprob_close(D.LogNormal(0.3, 0.8),
                   lambda x: st.lognorm.logpdf(x, 0.8, scale=np.exp(0.3)), xs)
    xs2 = np.linspace(-2, 4, 6)
    _logprob_close(D.Gumbel(0.5, 1.2),
                   lambda x: st.gumbel_r.logpdf(x, 0.5, 1.2), xs2)


def test_discrete_families():
    ks = np.arange(0, 6).astype(np.float64)
    _logprob_close(D.Poisson(2.5), lambda k: st.poisson.logpmf(k, 2.5), ks)
    _logprob_close(D.Geometric(0.3), lambda k: st.geom.logpmf(k + 1, 0.3), ks)
    _logprob_close(D.Binomial(np.float32(10), np.float32(0.4)),
                   lambda k: st.binom.logpmf(k, 10, 0.4), ks)
    s = D.Poisson(4.0).sample([3000]).numpy()
    assert abs(s.mean() - 4.0) < 0.3


def test_multivariate_normal():
    cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
    loc = np.array([1.0, -1.0], np.float32)
    d = D.MultivariateNormal(loc, covariance_matrix=cov)
    xs = np.array([[0.0, 0.0], [1.0, -1.0]], np.float32)
    ref = st.multivariate_normal.logpdf(xs, loc, cov)
    np.testing.assert_allclose(d.log_prob(T(xs)).numpy(), ref, rtol=1e-4)
    s = d.sample([5000]).numpy()
    np.testing.assert_allclose(np.cov(s.T), cov, atol=0.2)
    ent = float(d.entropy())
    np.testing.assert_allclose(ent, st.multivariate_normal(loc, cov).entropy(),
                               rtol=1e-4)


def test_independent_reinterprets_batch():
    base = D.Normal(np.zeros((3, 4), np.float32), np.ones((3, 4), np.float32))
    d = D.Independent(base, 1)
    assert d.batch_shape == [3] and d.event_shape == [4]
    lp = d.log_prob(T(np.zeros((3, 4), np.float32)))
    assert list(np.asarray(lp.numpy()).shape) == [3]


def test_lkj_cholesky_valid_factors():
    d = D.LKJCholesky(4, concentration=2.0)
    L = d.sample().numpy()
    assert L.shape == (4, 4)
    corr = L @ L.T
    np.testing.assert_allclose(np.diag(corr), 1.0, atol=1e-5)
    assert (np.linalg.eigvalsh(corr) > -1e-6).all()
    lp = float(d.log_prob(T(L)))
    assert np.isfinite(lp)


def test_continuous_bernoulli_normalized():
    d = D.ContinuousBernoulli(np.float32(0.3))
    xs = np.linspace(1e-3, 1 - 1e-3, 400).astype(np.float32)
    probs = np.exp(d.log_prob(T(xs)).numpy())
    integral = np.trapezoid(probs, xs)
    np.testing.assert_allclose(integral, 1.0, atol=0.02)


def test_kl_registry():
    p, q = D.Laplace(0.0, 1.0), D.Laplace(1.0, 2.0)
    kl = float(D.kl_divergence(p, q).numpy())
    # monte-carlo cross-check
    s = p.sample([20000]).numpy().astype(np.float32)
    mc = float(np.mean(p.log_prob(T(s)).numpy() - q.log_prob(T(s)).numpy()))
    np.testing.assert_allclose(kl, mc, atol=0.05)
    # builtin pairs still dispatch
    kn = D.kl_divergence(D.Normal(0.0, 1.0), D.Normal(1.0, 1.0))
    np.testing.assert_allclose(float(kn.numpy()), 0.5, rtol=1e-5)

    @D.register_kl(D.Poisson, D.Gumbel)
    def _fake(p, q):
        return paddle.to_tensor(np.float32(42.0))

    assert float(D.kl_divergence(D.Poisson(1.0), D.Gumbel(0.0, 1.0))) == 42.0
