"""Fused mega-step serving (inference/serving.py ``fused=``, auto at
max_batch >= 32 — docs/SERVING.md): device-resident block tables /
positions / sampling state updated by traced scatters, ONE jitted decode
program over all rows with masked inactive rows, prompt-packing prefill,
and O(active) host bookkeeping.

The contract under test: fused token streams are BYTE-IDENTICAL to the
legacy per-slot step path (greedy AND seeded), at any slot count, prefix
cache on or off, warm or cold, across COW divergence and crash replay.
The 128-slot acceptance pin (ISSUE 10) is slow-marked; every behavior has
a fast 8-slot pin here — tier-1 sits near its 870 s ceiling.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                          PrefixCacheConfig, Request)


@pytest.fixture(scope="module")
def model():
    paddle.seed(11)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    return cfg, LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def leg(model):
    """Legacy per-slot reference engine (2 slots, prefix off)."""
    _, m = model
    return ContinuousBatchingEngine(m, max_batch=2, max_len=64, page_size=8,
                                    block_size=4, fused=False)


@pytest.fixture(scope="module")
def fus(model):
    """Fused engine, prefix off (8 slots — same programs the 128-slot
    engine runs, cheaper to compile)."""
    _, m = model
    return ContinuousBatchingEngine(m, max_batch=8, max_len=64, page_size=8,
                                    block_size=4, fused=True)


@pytest.fixture(scope="module")
def fusp(model):
    """Fused engine with the prefix cache + packed prefill."""
    _, m = model
    return ContinuousBatchingEngine(
        m, max_batch=8, max_len=64, page_size=8, block_size=4, fused=True,
        prefix_cache=PrefixCacheConfig(prefill_chunk=16, extra_blocks=8))


def _prompt(cfg, n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)


def _wave(cfg):
    """Mixed greedy/seeded requests; prompt 16 is a full-page multiple so
    a warm re-serve takes the FULL-prompt-hit COW path, and prompt 40 is
    LONGER than the fused engine's prefill_chunk (16) so the packed
    prefill carries several chunks of one prompt in a single call — the
    append-before-gather ordering `_run_pack` stakes bit-identity on."""
    prompts = [_prompt(cfg, n, 300 + n) for n in (5, 16, 9, 16, 40, 3)]
    kws = [dict(max_new_tokens=6), dict(max_new_tokens=4),
           dict(max_new_tokens=8, temperature=0.8, seed=7, top_k=5),
           dict(max_new_tokens=4, temperature=1.1, seed=3, top_p=0.9),
           dict(max_new_tokens=6), dict(max_new_tokens=8)]
    return prompts, kws


def _serve(eng, prompts, kws, stagger=True):
    reqs = [Request(p, **k) for p, k in zip(prompts, kws)]
    head, tail = (reqs[:3], reqs[3:]) if stagger else (reqs, [])
    for r in head:
        eng.add_request(r)
    if tail:
        eng.step()
        eng.step()
        for r in tail:
            eng.add_request(r)
    eng.run_until_done(max_steps=500)
    return [list(r.tokens) for r in reqs]


def test_fused_matches_legacy_greedy_and_seeded(model, leg, fus):
    """The core contract: fused 8-slot streams == legacy 2-slot streams,
    byte for byte, mixed greedy + seeded sampling, staggered arrivals."""
    cfg, _ = model
    prompts, kws = _wave(cfg)
    want = _serve(leg, prompts, kws)
    got = _serve(fus, prompts, kws)
    assert got == want
    assert fus.stats["fused_updates"] > 0      # scatters actually ran
    # device state drained: every row inactive, every slot free again
    assert not np.asarray(fus._dev_act).any()
    assert fus.active_slots() == 0 and len(fus._free_slots) == fus.max_batch


def test_fused_prefix_warm_cold_cow_identity(model, leg, fusp):
    """Prefix-cache fused: cold == warm == legacy. The warm wave re-serves
    two full-page prompts, so the batched-COW path (one device dispatch
    for the wave's copies) and the radix hits are both on the tested
    path; the packed prefill must also have fired."""
    cfg, _ = model
    prompts, kws = _wave(cfg)
    want = _serve(leg, prompts, kws)
    cold = _serve(fusp, prompts, kws)
    warm = _serve(fusp, prompts, kws)
    assert cold == want and warm == want
    assert fusp.stats["hit_tokens"] > 0
    assert fusp.stats["cow_copies"] > 0        # full-prompt hits -> COW
    assert fusp.stats["packed_rows"] > 0       # prompt-packing prefill ran
    # prefix fused: every table row parked on device once drained
    assert (np.asarray(fusp.caches["tables"]) == fusp._park).all()


def test_fused_eos_early_exit(model, leg, fus):
    """eos-carrying fused batches pace at block_size and stop early,
    exactly like the legacy path (token-for-token, including the cut)."""
    cfg, _ = model
    p = _prompt(cfg, 7, 401)
    out = []
    for eng in (leg, fus):
        r = Request(p, max_new_tokens=12, eos_token_id=3)
        eng.add_request(r)
        eng.run_until_done(max_steps=200)
        out.append(list(r.tokens))
    assert out[0] == out[1]


def test_fused_deadline_eviction_survivor_unharmed(model, fusp):
    """Deadline eviction in fused mode: the expired slot is failed and its
    row parked via the update queue; the surviving stream is untouched.
    The no-deadline fast path stays O(1) (``_n_deadlined`` gate)."""
    cfg, _ = model
    import time

    pa, pb = _prompt(cfg, 5, 402), _prompt(cfg, 9, 403)
    ref = Request(pa, max_new_tokens=6)
    fusp.add_request(ref)
    fusp.run_until_done(max_steps=200)
    surv = Request(pa, max_new_tokens=6)
    doomed = Request(pb, max_new_tokens=40, deadline_s=0.0005)
    fusp.add_request(surv)
    fusp.shed_infeasible = False    # exercise EVICTION, not submit shedding
    try:
        fusp.add_request(doomed)
    finally:
        fusp.shed_infeasible = True
    assert fusp._n_deadlined == 1
    fusp.step()
    time.sleep(0.01)
    fusp.run_until_done(max_steps=200)
    assert doomed.failed and "deadline" in doomed.error
    assert fusp._n_deadlined == 0
    assert not surv.failed and list(surv.tokens) == list(ref.tokens)


def test_fused_counters_track_occupancy(model, fus):
    """O(active) bookkeeping invariants: occupied dict + free-slot deque +
    has_work stay consistent with the slot array through admit/finish."""
    cfg, _ = model
    reqs = [Request(_prompt(cfg, 5, 500 + i), max_new_tokens=16)
            for i in range(3)]
    for r in reqs:
        fus.add_request(r)
    assert fus.has_work()
    fus.step()
    assert fus.active_slots() == 3
    assert len(fus._free_slots) == fus.max_batch - 3
    assert sorted(fus._occupied) == [i for i, s in enumerate(fus._slots)
                                     if s is not None]
    fus.run_until_done(max_steps=200)
    assert not fus.has_work() and fus.active_slots() == 0
    assert len(fus._free_slots) == fus.max_batch
    assert all(r.done and not r.failed for r in reqs)


@pytest.mark.slow   # crash + rebuild = a second fused compile wave (~23s);
#                     replay-determinism keeps fast coverage via
#                     test_serving_recovery's journal-restart test (same
#                     posture as PR 5's crash-recovery slow-mark)
def test_fused_crash_replay_bit_identical(model, tmp_path):
    """ServingSupervisor over a FUSED engine: a ``serving.step`` kill
    mid-wave rebuilds from the journal and the replayed streams (greedy +
    seeded) are byte-identical to an uninterrupted fused run — the
    device-resident state is fully reconstructible from the journal, as
    the recovery contract requires."""
    cfg, m = model
    from paddle_tpu.inference.recovery import ServingSupervisor

    def build():
        return ContinuousBatchingEngine(
            m, max_batch=4, max_len=32, page_size=8, block_size=2,
            fused=True, prefix_cache=PrefixCacheConfig(prefill_chunk=8))

    pa, pb = _prompt(cfg, 8, 601), _prompt(cfg, 6, 602)

    def wave():
        return [Request(pa, max_new_tokens=6, seed=70),
                Request(pb, max_new_tokens=10, temperature=0.9, seed=71)]

    ref_eng = build()
    refs = wave()
    for r in refs:
        ref_eng.add_request(r)
    ref_eng.run_until_done(max_steps=300)

    plan = FaultPlan(seed=5, specs=[
        FaultSpec("serving.step", "kill", at=2, count=1)])
    sup = ServingSupervisor(build, str(tmp_path / "fused.jrnl"))
    reqs = wave()
    with plan:
        for r in reqs:
            sup.submit(r)
        done = sup.run_until_done(max_steps=300)
    sup.close()
    assert plan.log, "serving.step kill never fired"
    assert sup.recoveries == 1
    assert set(done) == {r.rid for r in reqs}
    for got, want in zip(reqs, refs):
        assert got.done and not got.failed
        assert list(got.tokens) == list(want.tokens)


def test_tracer_batched_stamps_equal_per_slot_stamps():
    """decode_block_batch / first_tokens / tokens_batch (one lock per
    step) must book exactly what the per-slot calls book."""
    from paddle_tpu.observability.tracing import TraceRecorder

    a, b = TraceRecorder(), TraceRecorder()
    for rid in (1, 2):
        a.submit(rid, 4, 8)
        b.submit(rid, 4, 8)
    # per-slot stamping (legacy shape)
    for rid in (1, 2):
        a.first_token(rid)
        a.tokens(rid, 1)
    a.decode_block(a.now(), 4, 2)
    for rid in (1, 2):
        a.tokens(rid, 5)
    # batched stamping (fused shape)
    b.first_tokens([(1, 1), (2, 1)])
    b.decode_block_batch(b.now(), 4, 2, [(1, 5), (2, 5)])
    sa, sb = a.slo_summary(), b.slo_summary()
    assert sa["tokens_streamed"] == sb["tokens_streamed"] == 10
    assert sa["submitted"] == sb["submitted"] == 2
    assert ([e["name"] for e in a.events if e["tid"] == 1]
            == [e["name"] for e in b.events if e["tid"] == 1])


@pytest.mark.slow   # one 128-row compile wave (~3-4 min budget class) —
#                     the fast 8-slot pins above cover every behavior;
#                     this is the ISSUE 10 acceptance config end-to-end
def test_fused_128_slots_byte_identical_to_legacy(model, leg):
    """Acceptance pin: max_batch=128 fused engine (prefix cache + packed
    prefill + batched COW) serves a 160-request mixed wave with every
    stream byte-identical to the legacy 8-slot-class path, cold AND warm,
    and the engine drains clean."""
    cfg, m = model
    eng = ContinuousBatchingEngine(
        m, max_batch=128, max_len=32, page_size=8, block_size=4,
        prefix_cache=PrefixCacheConfig(prefill_chunk=8, extra_blocks=16))
    assert eng._fused                     # auto-enabled at big batch
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size,
                            (8 + (i % 3) * 4,)).astype(np.int32)
               for i in range(160)]
    news = [4 + (i % 4) * 2 for i in range(160)]

    def wave(e):
        reqs = [Request(p, max_new_tokens=k)
                for p, k in zip(prompts, news)]
        for r in reqs:
            e.add_request(r)
        e.run_until_done(max_steps=2000)
        return [list(r.tokens) for r in reqs]

    cold = wave(eng)
    warm = wave(eng)
    want = wave(ContinuousBatchingEngine(m, max_batch=8, max_len=32,
                                         page_size=8, block_size=4,
                                         fused=False))
    assert cold == want and warm == want
    assert eng.stats["cow_copies"] > 0 and eng.stats["packed_rows"] > 0
    assert eng.active_slots() == 0 and len(eng._free_slots) == 128


@pytest.mark.slow   # one extra prefix-engine compile wave beside the module
#                     fixtures (tier-1 ceiling) — the fast pins are
#                     test_program_cost.py::test_engine_declares_mega_and_
#                     chunk_donation (declaration covers the carries) plus
#                     EVERY fused-vs-legacy identity test above, which runs
#                     the donated path (donate_carry defaults True) against
#                     the undonated legacy engine
def test_donation_off_byte_identity(model, fusp):
    """PT-COST triage proof (docs/STATIC_ANALYSIS.md "Program cost"):
    donating the mega-step / prefill-chunk / first-token kv carries is a
    memory optimization only — a donate_carry=False engine serving the
    same mixed wave (prefix cache, packed prefill, COW, warm + cold)
    produces byte-identical streams to the donated module fixture."""
    cfg, m = model
    prompts, kws = _wave(cfg)
    want_cold = _serve(fusp, prompts, kws)
    want_warm = _serve(fusp, prompts, kws)
    eng = ContinuousBatchingEngine(
        m, max_batch=8, max_len=64, page_size=8, block_size=4, fused=True,
        prefix_cache=PrefixCacheConfig(prefill_chunk=16, extra_blocks=8),
        donate_carry=False)
    assert eng._donate_carry is False
    assert fusp._donate_carry is True
    cold = _serve(eng, prompts, kws)
    warm = _serve(eng, prompts, kws)
    assert cold == want_cold and warm == want_warm
