"""Test environment: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors SURVEY.md §4's implication: distributed logic is tested single-host on a
virtual device mesh (the analogue of the reference's multi-process-on-one-host
collective tests, test/legacy_test/test_dist_base.py:1209).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize) force-sets jax_platforms programmatically,
# overriding the env var — override it back before any backend is initialized.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle

    paddle.seed(1234)
    np.random.seed(1234)
    yield


@pytest.fixture
def mesh8():
    import jax

    assert jax.device_count() == 8
    return jax.devices()
