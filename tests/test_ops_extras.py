"""Numpy-referenced tests for the breadth-completion ops (OpTest pattern:
test/legacy_test/op_test.py — each op vs its numpy reference)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft as pfft
from paddle_tpu import signal as psignal

T = paddle.to_tensor


class TestMathExtras:
    def test_addmm(self):
        i = np.ones((2, 3), np.float32)
        a = np.random.rand(2, 4).astype(np.float32)
        b = np.random.rand(4, 3).astype(np.float32)
        out = paddle.addmm(T(i), T(a), T(b), beta=0.5, alpha=2.0)
        np.testing.assert_allclose(out.numpy(), 0.5 * i + 2.0 * (a @ b), rtol=1e-5)

    def test_cdist_dist(self):
        x = np.random.rand(5, 3).astype(np.float32)
        y = np.random.rand(4, 3).astype(np.float32)
        out = paddle.cdist(T(x), T(y))
        ref = np.sqrt(((x[:, None] - y[None]) ** 2).sum(-1))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
        d = paddle.dist(T(x[:4]), T(y), p=2)
        np.testing.assert_allclose(float(d), np.linalg.norm((x[:4] - y).ravel()),
                                   rtol=1e-5)

    def test_diff(self):
        x = np.array([1.0, 4.0, 9.0, 16.0], np.float32)
        np.testing.assert_allclose(paddle.diff(T(x)).numpy(), np.diff(x))

    def test_special_functions(self):
        from scipy import special as sp

        x = np.linspace(0.5, 3.0, 6).astype(np.float32)
        np.testing.assert_allclose(paddle.gammaln(T(x)).numpy(),
                                   sp.gammaln(x), rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(paddle.i0e(T(x)).numpy(), sp.i0e(x),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(paddle.sinc(T(x)).numpy(), np.sinc(x),
                                   rtol=1e-4, atol=1e-6)
        p = np.linspace(0.1, 0.9, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.logit(T(p)).numpy(),
                                   np.log(p / (1 - p)), rtol=1e-4, atol=1e-6)

    def test_logcumsumexp(self):
        x = np.random.rand(6).astype(np.float32)
        out = paddle.logcumsumexp(T(x), axis=0)
        ref = np.log(np.cumsum(np.exp(x)))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_isin_and_inf_checks(self):
        x = np.array([1.0, np.inf, -np.inf, 2.0], np.float32)
        assert paddle.isposinf(T(x)).numpy().tolist() == [False, True, False, False]
        assert paddle.isneginf(T(x)).numpy().tolist() == [False, False, True, False]
        out = paddle.isin(T(np.array([1, 2, 3])), T(np.array([2, 3])))
        assert out.numpy().tolist() == [False, True, True]

    def test_trapezoid(self):
        y = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(float(paddle.trapezoid(T(y), dx=1.0)), 4.0)
        ct = paddle.cumulative_trapezoid(T(y), dx=1.0)
        np.testing.assert_allclose(ct.numpy(), [1.5, 4.0])

    def test_reduce_as(self):
        x = np.random.rand(4, 3).astype(np.float32)
        tgt = np.zeros((1, 3), np.float32)
        out = paddle.reduce_as(T(x), T(tgt))
        np.testing.assert_allclose(out.numpy(), x.sum(0, keepdims=True), rtol=1e-6)

    def test_renorm_sgn_signbit(self):
        x = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)
        out = paddle.renorm(T(x), p=2.0, axis=0, max_norm=1.0)
        norms = np.linalg.norm(out.numpy(), axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        assert paddle.sgn(T(np.array([-2.0, 0.0, 5.0]))).numpy().tolist() == [-1, 0, 1]
        assert paddle.signbit(T(np.array([-1.0, 1.0]))).numpy().tolist() == [True, False]

    def test_vander_nanquantile(self):
        x = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(paddle.vander(T(x)).numpy(), np.vander(x))
        y = np.array([1.0, np.nan, 3.0, 4.0], np.float32)
        np.testing.assert_allclose(float(paddle.nanquantile(T(y), 0.5)),
                                   np.nanquantile(y, 0.5))


class TestLinalgExtras:
    def test_inverse(self):
        a = np.random.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
        out = paddle.inverse(T(a))
        np.testing.assert_allclose(out.numpy() @ a, np.eye(3), atol=1e-4)

    def test_cholesky_inverse(self):
        a = np.random.rand(3, 3).astype(np.float32)
        spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        l = np.linalg.cholesky(spd)
        out = paddle.cholesky_inverse(T(l))
        np.testing.assert_allclose(out.numpy(), np.linalg.inv(spd), atol=1e-3)

    def test_block_diag(self):
        a, b = np.ones((2, 2), np.float32), 2 * np.ones((1, 3), np.float32)
        out = paddle.block_diag([T(a), T(b)])
        assert out.shape == [3, 5]
        np.testing.assert_allclose(out.numpy()[:2, :2], a)
        np.testing.assert_allclose(out.numpy()[2:, 2:], b)

    def test_svd_lowrank(self):
        rng = np.random.default_rng(0)
        a = (rng.standard_normal((8, 3)) @ rng.standard_normal((3, 6))).astype(np.float32)
        u, s, v = paddle.svd_lowrank(T(a), q=3)
        rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        np.testing.assert_allclose(rec, a, atol=1e-3)

    def test_ormqr(self):
        import scipy.linalg as sla

        a = np.random.rand(4, 3).astype(np.float32)
        (qr, tau), _ = sla.qr(a, mode="raw")  # LAPACK geqrf reflector layout
        qr = np.ascontiguousarray(qr).astype(np.float32)
        y = np.random.rand(4, 2).astype(np.float32)
        out = paddle.ormqr(T(qr), T(tau.astype(np.float32)), T(y))
        q_full = sla.qr(a)[0]  # full 4x4 Q from the same reflectors
        np.testing.assert_allclose(out.numpy(), q_full @ y, rtol=1e-3,
                                   atol=1e-4)


class TestManipExtras:
    def test_splits(self):
        x = np.arange(24).reshape(4, 6).astype(np.float32)
        h = paddle.hsplit(T(x), 2)
        assert len(h) == 2 and h[0].shape == [4, 3]
        v = paddle.vsplit(T(x), 2)
        assert v[0].shape == [2, 6]
        ts = paddle.tensor_split(T(x), 3, axis=1)
        assert [t.shape for t in ts] == [[4, 2]] * 3

    def test_reverse_unflatten_unfold(self):
        x = np.arange(6).astype(np.float32)
        np.testing.assert_allclose(paddle.reverse(T(x), 0).numpy(), x[::-1])
        u = paddle.unflatten(T(np.zeros((2, 6), np.float32)), 1, [2, 3])
        assert u.shape == [2, 2, 3]
        w = paddle.unfold(T(x), 0, size=3, step=2)
        np.testing.assert_allclose(w.numpy(), [[0, 1, 2], [2, 3, 4]])

    def test_as_strided(self):
        x = np.arange(12).astype(np.float32)
        out = paddle.as_strided(T(x), [3, 2], [4, 1])
        np.testing.assert_allclose(
            out.numpy(), np.lib.stride_tricks.as_strided(
                x, (3, 2), (16, 4)))

    def test_scatter_family(self):
        x = np.zeros((3, 4), np.float32)
        out = paddle.index_fill(T(x), T(np.array([0, 2])), 0, 5.0)
        assert (out.numpy()[[0, 2]] == 5.0).all() and (out.numpy()[1] == 0).all()
        d = paddle.diagonal_scatter(T(np.zeros((3, 3), np.float32)),
                                    T(np.array([1.0, 2.0, 3.0], np.float32)))
        np.testing.assert_allclose(np.diag(d.numpy()), [1, 2, 3])
        s = paddle.select_scatter(T(x), T(np.ones(4, np.float32)), 0, 1)
        assert (s.numpy()[1] == 1).all()
        sl = paddle.slice_scatter(T(x), T(np.ones((3, 2), np.float32)),
                                  [1], [0], [2], [1])
        assert (sl.numpy()[:, :2] == 1).all() and (sl.numpy()[:, 2:] == 0).all()
        ms = paddle.masked_scatter(T(x), T(x == 0),
                                   T(np.arange(12, dtype=np.float32)))
        np.testing.assert_allclose(ms.numpy().ravel(), np.arange(12))

    def test_predicates(self):
        assert paddle.is_tensor(T(np.zeros(2)))
        assert not paddle.is_tensor(np.zeros(2))
        assert paddle.is_floating_point(T(np.zeros(2, np.float32)))
        assert paddle.is_integer(T(np.zeros(2, np.int32)))
        assert paddle.is_empty(T(np.zeros((0, 3), np.float32)))


class TestSampling:
    def test_top_p_sampling(self):
        paddle.seed(0)
        logits = np.full((2, 10), -1e9, np.float32)
        logits[:, 3] = 10.0  # all mass on token 3
        val, idx = paddle.top_p_sampling(T(logits), 0.9)
        assert idx.numpy().ravel().tolist() == [3, 3]


class TestFFT:
    def test_fft_roundtrip(self):
        x = np.random.rand(16).astype(np.float32)
        spec = pfft.fft(T(x))
        back = pfft.ifft(spec)
        np.testing.assert_allclose(back.numpy().real, x, atol=1e-5)

    def test_rfft_matches_numpy(self):
        x = np.random.rand(16).astype(np.float32)
        np.testing.assert_allclose(pfft.rfft(T(x)).numpy(),
                                   np.fft.rfft(x), rtol=1e-4, atol=1e-5)

    def test_fft2_shift(self):
        x = np.random.rand(4, 4).astype(np.float32)
        s = pfft.fftshift(pfft.fft2(T(x)))
        ref = np.fft.fftshift(np.fft.fft2(x))
        np.testing.assert_allclose(s.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_fftfreq(self):
        np.testing.assert_allclose(pfft.fftfreq(8).numpy(), np.fft.fftfreq(8))


class TestSignal:
    def test_stft_istft_roundtrip(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal(512).astype(np.float32)
        win = np.hanning(128).astype(np.float32)
        spec = psignal.stft(T(x), n_fft=128, hop_length=32, window=T(win))
        back = psignal.istft(spec, n_fft=128, hop_length=32, window=T(win),
                             length=512)
        np.testing.assert_allclose(back.numpy(), x, atol=1e-3)

    def test_frame_overlap_add(self):
        x = np.arange(10, dtype=np.float32)
        fr = psignal.frame(T(x), frame_length=4, hop_length=2)
        assert fr.shape == [4, 4]
        np.testing.assert_allclose(fr.numpy()[:, 0], [0, 1, 2, 3])


class TestNewOptimizers:
    def _fit(self, opt_cls, **kw):
        rng = np.random.default_rng(0)
        w_true = np.array([[2.0], [-1.0]], np.float32)
        lin = paddle.nn.Linear(2, 1)
        opt = opt_cls(parameters=list(lin.parameters()), **kw)
        for _ in range(150):
            x = T(rng.standard_normal((32, 2)).astype(np.float32))
            loss = paddle.mean((lin(x) - T(x.numpy() @ w_true)) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
        return float(loss)

    def test_asgd_actually_averages(self):
        # batch_num=4, grads 1 then 3 -> updates use mean over the window:
        # step1 d=[1,0,0,0] -> -lr*1/4 ; step2 d=[1,3,0,0] -> -lr*4/4
        import jax.numpy as jnp

        p = paddle.Parameter(np.zeros(1, np.float32))
        opt = paddle.optimizer.ASGD(learning_rate=1.0, batch_num=4,
                                    parameters=[p])
        p.grad = T(np.array([1.0], np.float32))
        opt.step()
        np.testing.assert_allclose(p.numpy(), [-0.25])
        p.grad = T(np.array([3.0], np.float32))
        opt.step()
        np.testing.assert_allclose(p.numpy(), [-1.25])  # -0.25 - (1+3)/4

    def test_asgd_converges(self):
        assert self._fit(paddle.optimizer.ASGD, learning_rate=0.1,
                         batch_num=1) < 0.05

    def test_rprop_converges(self):
        assert self._fit(paddle.optimizer.Rprop, learning_rate=0.01) < 0.05


class TestTopLevelCompletion:
    def test_inplace_family(self):
        x = T(np.array([1.0, -2.0], np.float32))
        x.abs_()
        np.testing.assert_allclose(x.numpy(), [1.0, 2.0])
        paddle.tanh_(x)
        np.testing.assert_allclose(x.numpy(), np.tanh([1.0, 2.0]), rtol=1e-6)
        y = T(np.zeros((2, 3), np.float32))
        y.transpose_([1, 0])
        assert y.shape == [3, 2]

    def test_stack_family_and_products(self):
        a, b = np.ones(3, np.float32), 2 * np.ones(3, np.float32)
        assert paddle.hstack([T(a), T(b)]).shape == [6]
        assert paddle.vstack([T(a), T(b)]).shape == [2, 3]
        assert paddle.column_stack([T(a), T(b)]).shape == [3, 2]
        cp = paddle.cartesian_prod([T(np.array([1, 2])), T(np.array([3, 4]))])
        np.testing.assert_array_equal(cp.numpy(),
                                      [[1, 3], [1, 4], [2, 3], [2, 4]])
        cb = paddle.combinations(T(np.array([1, 2, 3])))
        np.testing.assert_array_equal(cb.numpy(), [[1, 2], [1, 3], [2, 3]])

    def test_pdist_and_misc(self):
        d = paddle.pdist(T(np.array([[0., 0.], [3., 4.]], np.float32)))
        np.testing.assert_allclose(d.numpy(), [5.0])
        assert paddle.rank(T(np.zeros((2, 3)))).numpy() == 2
        assert paddle.shape(T(np.zeros((2, 5)))).numpy().tolist() == [2, 5]
        assert paddle.finfo("float32").max > 1e38
        assert paddle.iinfo("int32").max == 2**31 - 1
        assert paddle.is_grad_enabled()

    def test_where_inplace_targets_x(self):
        c = T(np.array([True, False]))
        x = T(np.array([1.0, 2.0], np.float32))
        y = T(np.array([9.0, 9.0], np.float32))
        out = paddle.where_(c, x, y)
        np.testing.assert_allclose(x.numpy(), [1.0, 9.0])  # x updated
        assert c.numpy().tolist() == [True, False]  # condition untouched
        assert out is x

    def test_random_inplace_fills(self):
        paddle.seed(1)
        y = paddle.zeros([200])
        y.geometric_(0.5)
        assert (y.numpy() >= 0).all()
        y.log_normal_(0.0, 0.25)
        assert (y.numpy() > 0).all()

    def test_reference_top_level_surface_complete(self):
        import re, pathlib

        p = pathlib.Path("/root/reference/python/paddle/__init__.py")
        if not p.exists():
            pytest.skip("reference checkout not mounted")
        ref = p.read_text()
        names = re.findall(r"^\s+'(\w+)',\s*$", ref.split("__all__")[1], re.M)
        missing = [n for n in names if not hasattr(paddle, n)]
        assert not missing, missing
