"""SLO observatory (docs/OBSERVABILITY.md "Traffic replay & SLO
attainment"): open-loop workload generation, windowed attainment/goodput,
and the SLO-pressure autoscaler.

Fast tests are pure-host (no model compiles — the schedule generator, the
attainment math on synthetic spans, the autoscaler state machine on
scripted series, the histogram window reads). The one fleet-under-burst
integration test is slow-marked (tier-1 budget); its behaviors are also
CI-gated end-to-end by ``tools/traffic_replay.py --selftest``.
"""

import dataclasses
import threading

import pytest

from paddle_tpu.observability import (Histogram, MetricsRegistry,
                                      ReplayDriver, SLOConfig, SLOMonitor,
                                      TenantSpec, TraceRecorder,
                                      VirtualClock, WorkloadConfig,
                                      decode_schedule, encode_schedule,
                                      generate_schedule, schedule_digest,
                                      slo_collector, tracer_collector)


def _cfg(**kw):
    base = dict(seed=5, duration_s=20.0, rate_rps=6.0, vocab_size=97,
                prompt_min=4, prompt_max=32, output_min=2, output_max=16)
    base.update(kw)
    return WorkloadConfig(**base)


class TestWorkload:
    def test_same_seed_byte_identical_schedule(self):
        for arrival in ("poisson", "diurnal", "burst"):
            cfg = _cfg(arrival=arrival)
            a = generate_schedule(cfg)
            b = generate_schedule(cfg)
            assert encode_schedule(a) == encode_schedule(b)
            assert schedule_digest(a) == schedule_digest(b)
            c = generate_schedule(dataclasses.replace(cfg, seed=6))
            assert encode_schedule(a) != encode_schedule(c)

    def test_arrivals_sorted_bounded_and_clipped(self):
        cfg = _cfg(arrival="poisson")
        sched = generate_schedule(cfg)
        assert len(sched) > 50          # ~rate*duration = 120 expected
        ts = [a.t for a in sched]
        assert ts == sorted(ts)
        assert all(0.0 <= t < cfg.duration_s for t in ts)
        for a in sched:
            assert cfg.prompt_min <= len(a.prompt) <= cfg.prompt_max
            assert cfg.output_min <= a.max_new <= cfg.output_max
            assert all(0 <= tok < cfg.vocab_size for tok in a.prompt)

    def test_burst_windows_are_denser(self):
        cfg = _cfg(arrival="burst", burst_every_s=5.0, burst_len_s=1.0,
                   burst_multiplier=6.0, duration_s=30.0)
        sched = generate_schedule(cfg)
        in_burst = sum(1 for a in sched
                       if (a.t % cfg.burst_every_s) < cfg.burst_len_s)
        out_burst = len(sched) - in_burst
        # burst fifth carries 6x the rate: its per-second density must
        # dominate the baseline's by a wide, assertable margin
        assert in_burst / cfg.burst_len_s > 2.0 * (
            out_burst / (cfg.burst_every_s - cfg.burst_len_s))

    def test_diurnal_peak_vs_trough(self):
        cfg = _cfg(arrival="diurnal", diurnal_period_s=20.0,
                   diurnal_depth=0.9, duration_s=20.0, rate_rps=20.0)
        sched = generate_schedule(cfg)
        peak = sum(1 for a in sched if 2.5 <= a.t < 7.5)     # sin max @ 5
        trough = sum(1 for a in sched if 12.5 <= a.t < 17.5)  # sin min @ 15
        assert peak > 2 * max(1, trough)

    def test_tenant_mix_shared_prefix_and_priority(self):
        cfg = _cfg(tenants=(TenantSpec("sys", weight=3.0, prefix_len=8),
                            TenantSpec("low", weight=1.0, prefix_len=0,
                                       priority=2)))
        sched = generate_schedule(cfg)
        sys_prompts = [a.prompt for a in sched if a.tenant == "sys"]
        low = [a for a in sched if a.tenant == "low"]
        assert sys_prompts and low
        # every sys request shares the SAME 8-token system prefix (the
        # radix-cache workload), low-tenant requests carry its priority
        head = sys_prompts[0][:8]
        assert all(p[:8] == head for p in sys_prompts)
        assert all(a.priority == 2 for a in low)
        assert len(sys_prompts) > len(low)          # 3:1 weights

    def test_unknown_arrival_process_rejected(self):
        with pytest.raises(ValueError, match="unknown arrival"):
            generate_schedule(_cfg(arrival="flat"))

    def test_encode_decode_roundtrip(self):
        sched = generate_schedule(_cfg(duration_s=3.0,
                                       tenants=(TenantSpec("t", 1.0,
                                                           prefix_len=4),)))
        back = decode_schedule(encode_schedule(sched))
        assert back == sched        # dataclass equality, field for field
        assert schedule_digest(back) == schedule_digest(sched)


class TestHistogramWindows:
    def test_snapshot_delta_isolates_the_window(self):
        h = Histogram("w_ms", buckets=(1.0, 10.0, 100.0))
        h.observe(5.0)
        h.observe(50.0)
        mark = h.snapshot()
        assert h.row_count(mark) == 2
        h.observe(0.5)
        h.observe(500.0)
        row = h.delta(mark)
        assert h.row_count(row) == 2            # only the window's two
        assert row[0] == 1.0 and row[3] == 1.0  # 0.5 bucket + +Inf
        assert h.delta(None) == h.snapshot()    # None = everything so far

    def test_row_quantile_and_fraction_le(self):
        h = Histogram("q_ms", buckets=(10.0, 20.0, 40.0))
        for v in (5.0, 15.0, 15.0, 35.0):
            h.observe(v)
        row = h.snapshot()
        assert h.row_quantile(row, 0.5) == pytest.approx(15.0, abs=5.0)
        # 3 of 4 at/below 20 (exact bucket edge — no interpolation slack)
        assert h.row_fraction_le(row, 20.0) == pytest.approx(0.75)
        assert h.row_fraction_le(row, 1e9) == pytest.approx(1.0)
        assert h.row_fraction_le((0.0,) * 5, 10.0) is None  # empty row
        h.observe(1e9)                           # +Inf bucket: never <= v
        assert h.row_fraction_le(h.snapshot(), 40.0) == pytest.approx(0.8)

    def test_reads_stay_consistent_under_concurrent_observes(self):
        h = Histogram("c_ms", buckets=(1.0, 10.0))
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                h.observe(5.0)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            for _ in range(200):
                row = h.snapshot()
                # sum tracks count exactly (5.0 each): a torn read would
                # break the invariant
                assert row[-1] == pytest.approx(5.0 * h.row_count(row))
        finally:
            stop.set()
            t.join()


def _stamp_request(tr, clock, rid, tenant, ttft_s, n_out, qwait_s=0.0,
                   kind="finish"):
    tr.submit(rid, 8, n_out, {"tenant": tenant} if tenant else None)
    clock.advance(ttft_s)
    if kind == "shed":
        tr.shed(rid)
        return
    tr.admit(rid, qwait_s)
    tr.first_token(rid)
    tr.finish(rid, n_out, failed=kind in ("evict", "fail"),
              error="deadline exceeded" if kind == "evict" else None)


class TestAttainmentMath:
    def test_windowed_attainment_goodput_and_tenants(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=100.0, window_s=1.0),
                         tracer=tr)
        _stamp_request(tr, clock, 1, "a", 0.05, 10)          # meets
        _stamp_request(tr, clock, 2, "a", 0.20, 10)          # ttft miss
        _stamp_request(tr, clock, 3, "b", 0.01, 5)           # meets
        _stamp_request(tr, clock, 4, "b", 0.01, 5, kind="shed")
        w = mon.roll_window(duration_s=2.0)
        assert w["finished"] == 4 and w["met"] == 2
        assert w["attainment"] == pytest.approx(0.5)
        assert w["tokens"] == 25 and w["good_tokens"] == 15
        assert w["goodput_tokens_per_sec"] == pytest.approx(7.5)
        assert w["throughput_tokens_per_sec"] == pytest.approx(12.5)
        assert w["by_tenant"]["a"] == {"finished": 2, "met": 1,
                                       "attainment": 0.5}
        assert w["by_tenant"]["b"]["attainment"] == pytest.approx(0.5)
        # per-signal window read straight off the histograms
        assert w["signals"]["ttft_ms"]["count"] == 3    # shed never admits
        assert w["signals"]["ttft_ms"]["attainment"] == pytest.approx(
            2 / 3, abs=0.01)
        # next window starts empty (snapshot marks advanced)
        w2 = mon.roll_window(duration_s=1.0)
        assert w2["finished"] == 0 and w2["attainment"] is None
        assert w2["signals"]["ttft_ms"]["count"] == 0

    def test_eviction_and_failure_never_meet_slo(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=1e9, window_s=1.0), tracer=tr)
        _stamp_request(tr, clock, 1, None, 0.01, 4, kind="evict")
        _stamp_request(tr, clock, 2, None, 0.01, 4, kind="fail")
        w = mon.roll_window(duration_s=1.0)
        assert w["finished"] == 2 and w["met"] == 0
        assert w["good_tokens"] == 0 and w["tokens"] == 8

    def test_unmeasured_signal_is_vacuously_met(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=100.0, inter_token_ms=1.0,
                                   queue_wait_ms=1.0, window_s=1.0),
                         tracer=tr)
        # 1-token response: no inter-token latency exists to miss
        _stamp_request(tr, clock, 1, None, 0.05, 1)
        w = mon.roll_window(duration_s=1.0)
        assert w["met"] == 1

    def test_queue_wait_target_enforced(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=None, queue_wait_ms=10.0,
                                   window_s=1.0), tracer=tr)
        _stamp_request(tr, clock, 1, None, 0.0, 4, qwait_s=0.005)
        _stamp_request(tr, clock, 2, None, 0.0, 4, qwait_s=0.5)
        w = mon.roll_window(duration_s=1.0)
        assert w["finished"] == 2 and w["met"] == 1

    def test_shed_then_reroute_books_the_real_finish(self):
        """A fleet router catching one replica's shed and placing the
        request on the next candidate re-opens the rid — the pending shed
        is cancelled and the REAL terminal is what counts (the review
        found rerouted requests booked as permanent SLO misses)."""
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=100.0, window_s=1.0),
                         tracer=tr)
        tr.submit(1, 8, 4, {"tenant": "a"})   # candidate A...
        tr.shed(1)                            # ...refuses
        tr.submit(1, 8, 4, {"tenant": "a"})   # candidate B accepts (reopen)
        clock.advance(0.05)
        tr.admit(1, 0.01)
        tr.first_token(1)
        tr.finish(1, 4)
        w = mon.roll_window(duration_s=1.0)
        assert w["finished"] == 1 and w["met"] == 1 and w["shed"] == 0
        assert w["good_tokens"] == 4
        assert w["by_tenant"]["a"] == {"finished": 1, "met": 1,
                                       "attainment": 1.0}

    def test_unrerouted_shed_finalizes_at_roll(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=100.0, window_s=1.0),
                         tracer=tr)
        tr.submit(1, 8, 4, {"tenant": "a"})
        tr.shed(1)
        w = mon.roll_window(duration_s=1.0)
        assert w["finished"] == 1 and w["met"] == 0 and w["shed"] == 1
        assert w["attainment"] == pytest.approx(0.0)
        assert w["served_attainment"] is None     # nothing was served
        assert w["by_tenant"]["a"]["finished"] == 1
        assert mon.report()["totals"]["shed"] == 1

    def test_served_attainment_excludes_sheds(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=100.0, window_s=1.0),
                         tracer=tr)
        _stamp_request(tr, clock, 1, None, 0.01, 4)          # served, met
        _stamp_request(tr, clock, 2, None, 0.01, 4, kind="shed")
        w = mon.roll_window(duration_s=1.0)
        assert w["attainment"] == pytest.approx(0.5)         # shed counts
        assert w["served_attainment"] == pytest.approx(1.0)  # ...here not

    def test_windows_total_outlives_the_bounded_deque(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(window_s=1.0), tracer=tr,
                         max_windows=4)
        for _ in range(10):
            mon.roll_window(duration_s=1.0)
        rep = mon.report()
        assert len(rep["windows"]) == 4          # deque truncated
        assert rep["windows_total"] == 10        # counter monotonic

    def test_second_terminal_for_same_rid_books_once(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=1e9, window_s=1.0), tracer=tr)
        _stamp_request(tr, clock, 1, None, 0.01, 4)
        mon.note_terminal(1, "finish", 4, None)   # no staged submit left
        w = mon.roll_window(duration_s=1.0)
        assert w["finished"] == 1

    def test_attainment_aggregate_and_report(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=100.0, window_s=1.0),
                         tracer=tr)
        _stamp_request(tr, clock, 1, None, 0.01, 4)
        mon.roll_window(duration_s=1.0)
        _stamp_request(tr, clock, 2, None, 0.5, 4)
        _stamp_request(tr, clock, 3, None, 0.5, 4)
        mon.roll_window(duration_s=1.0)
        assert mon.attainment() == pytest.approx(1 / 3)
        assert mon.attainment(last_n=1) == pytest.approx(0.0)
        rep = mon.report()
        assert rep["totals"]["finished"] == 3
        assert len(rep["windows"]) == 2


class _FakeSup:
    def __init__(self):
        self._load = 0

    def load(self):
        return self._load


class _FakeReplica:
    def __init__(self, idx):
        from paddle_tpu.inference.fleet import ReplicaState

        self.idx = idx
        self.state = ReplicaState.ALIVE
        self.sup = _FakeSup()


class _FakeRouter:
    """Duck-typed FleetRouter for the autoscaler state machine: records
    actions, never touches an engine."""

    def __init__(self, n=1):
        self.replicas = [_FakeReplica(i) for i in range(n)]
        self.actions = []

    def add_replica(self):
        idx = len(self.replicas)
        self.replicas.append(_FakeReplica(idx))
        self.actions.append(("add", idx))
        return idx

    def retire_replica(self, idx):
        from paddle_tpu.inference.fleet import ReplicaState

        self.replicas[idx].state = ReplicaState.RETIRED
        self.actions.append(("retire", idx))
        return True

    def force_brownout(self, active):
        self.actions.append(("brownout", bool(active)))


class _ScriptedMonitor:
    """Feeds the autoscaler a scripted attainment series. An entry may be
    a float (overall attainment), None (empty window), or an
    ``(attainment, served_attainment)`` pair (brownout windows where the
    sheds cap the overall number)."""

    def __init__(self, series, finished=10):
        self.config = SLOConfig(target_attainment=0.9)
        self._series = list(series)
        self._finished = finished
        self._i = -1

    def advance(self):
        self._i += 1

    def last_window(self):
        if self._i < 0 or self._i >= len(self._series):
            return None
        att = self._series[self._i]
        served = None
        if isinstance(att, tuple):
            att, served = att
        fin = self._finished if att is not None else 0
        return {"window": self._i + 1, "attainment": att,
                "served_attainment": served, "finished": fin,
                "met": 0 if att is None else int(att * fin)}


def _tick(scaler, mon):
    mon.advance()
    return scaler.tick()


class TestAutoscalerHysteresis:
    def _make(self, series, n=1, **cfg_kw):
        from paddle_tpu.inference.autoscale import (AutoscaleConfig,
                                                    SLOAutoscaler)

        base = dict(min_replicas=1, max_replicas=3, up_after=2,
                    down_after=3, cooldown_windows=1)
        base.update(cfg_kw)
        router = _FakeRouter(n)
        mon = _ScriptedMonitor(series)
        return router, mon, SLOAutoscaler(router, mon,
                                          AutoscaleConfig(**base))

    def test_scale_up_needs_consecutive_pressure(self):
        # one bad window + recovery: no action; two consecutive: scale up
        router, mon, scaler = self._make([0.5, 0.95, 0.5, 0.5])
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) is None       # counter reset by the good
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) == "scale_up"
        assert router.actions == [("add", 1)]

    def test_cooldown_quiets_the_controller(self):
        router, mon, scaler = self._make([0.5] * 5, cooldown_windows=2)
        decisions = [_tick(scaler, mon) for _ in range(5)]
        # up at window 2, then 2 cooldown windows, then up again at 5
        assert decisions == [None, "scale_up", None, None, "scale_up"]

    def test_brownout_at_max_replicas_and_exit_on_headroom(self):
        router, mon, scaler = self._make(
            [0.5, 0.5] + [0.99] * 4, n=3, cooldown_windows=0)
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) == "brownout"     # at max: degrade
        assert ("brownout", True) in router.actions
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) == "brownout_exit"
        assert ("brownout", False) in router.actions
        assert scaler.stats["brownouts"] == 1
        assert scaler.stats["brownout_exits"] == 1

    def test_forced_brownout_exits_on_served_attainment(self):
        """While the controller's own brownout sheds a third of traffic,
        overall attainment is capped at ~0.67 and can never reach
        headroom — the exit must be judged on the attainment of the
        traffic actually served (review finding: brownout otherwise
        locks in forever)."""
        router, mon, scaler = self._make(
            [0.5, 0.5] + [(0.66, 0.99)] * 4, n=3, cooldown_windows=0)
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) == "brownout"
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) == "brownout_exit"
        assert ("brownout", False) in router.actions

    def test_scale_down_on_sustained_headroom_but_never_below_min(self):
        router, mon, scaler = self._make([0.99] * 8, n=2,
                                         cooldown_windows=0)
        decisions = [_tick(scaler, mon) for _ in range(8)]
        assert decisions[2] == "scale_down"         # after down_after=3
        from paddle_tpu.inference.fleet import ReplicaState

        alive = [r for r in router.replicas
                 if r.state == ReplicaState.ALIVE]
        assert len(alive) == 1                      # floor respected
        assert decisions.count("scale_down") == 1

    def test_empty_windows_are_no_evidence(self):
        router, mon, scaler = self._make([0.5, None, 0.5, 0.5])
        assert _tick(scaler, mon) is None
        assert _tick(scaler, mon) is None     # None window: counters HOLD
        assert _tick(scaler, mon) == "scale_up"

    def test_disabled_controller_observes_but_never_acts(self):
        from paddle_tpu.inference.autoscale import (AutoscaleConfig,
                                                    SLOAutoscaler)

        router = _FakeRouter(1)
        mon = _ScriptedMonitor([0.1] * 6)
        scaler = SLOAutoscaler(router, mon, AutoscaleConfig(up_after=2),
                               enabled=False)
        for _ in range(6):
            assert _tick(scaler, mon) is None
        assert router.actions == []
        assert scaler.stats["pressured_windows"] == 6

    def test_decisions_are_traced_and_counted(self):
        from paddle_tpu.inference.autoscale import (AutoscaleConfig,
                                                    SLOAutoscaler)

        registry = MetricsRegistry()
        tracer = TraceRecorder(registry=registry)
        router = _FakeRouter(1)
        mon = _ScriptedMonitor([0.5, 0.5])
        scaler = SLOAutoscaler(router, mon,
                               AutoscaleConfig(up_after=2),
                               registry=registry, tracer=tracer)
        _tick(scaler, mon)
        assert _tick(scaler, mon) == "scale_up"
        assert registry.get("pt_autoscaler_scale_ups_total").value() == 1.0
        assert registry.get("pt_autoscaler_replicas").value() == 2.0
        names = [e["name"] for e in tracer.events]
        assert "autoscale" in names
        assert scaler.decisions[0]["action"] == "scale_up"
        assert scaler.report()["stats"]["scale_ups"] == 1


class TestTracerCountersAndCollectors:
    def test_drop_and_gc_counters_surface(self):
        tr = TraceRecorder(max_events=3, max_requests=2)
        clock = VirtualClock()
        for rid in (1, 2, 3):
            tr.submit(rid, 4, 4)
            tr.finish(rid, 4)
        c = tr.counters()
        assert c["dropped"] > 0                 # 3-event buffer overflowed
        assert c["gc"] > 0                      # terminal rid evicted
        assert c["events"] == 3
        registry = MetricsRegistry()
        registry.register_collector(tracer_collector(tr))
        text = registry.dump()
        assert "pt_tracer_dropped_total" in text
        assert "pt_tracer_gc_total" in text
        del clock

    def test_slo_collector_families(self):
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=100.0, window_s=1.0),
                         tracer=tr)
        _stamp_request(tr, clock, 1, "t0", 0.01, 4)
        mon.roll_window(duration_s=1.0)
        registry = MetricsRegistry()
        registry.register_collector(slo_collector(mon))
        from paddle_tpu.observability import parse_prometheus_text

        fams = parse_prometheus_text(registry.dump())
        for name in ("pt_slo_requests_finished_total",
                     "pt_slo_requests_met_total",
                     "pt_slo_good_tokens_total", "pt_slo_attainment",
                     "pt_slo_goodput_tokens_per_sec",
                     "pt_slo_windows_total"):
            assert name in fams, name
        scopes = {s[1].get("scope")
                  for s in fams["pt_slo_attainment"].samples}
        assert {"window", "total", "tenant:t0",
                "signal:ttft_ms"} <= scopes


class _FakeTarget:
    """Engine-shaped sink for driver tests: serves ``per_step`` queued
    requests per step (pure host)."""

    def __init__(self, per_step=0, refuse_after=None):
        self.queue = []
        self.done = []
        self.per_step = per_step
        self.refuse_after = refuse_after
        self.submit_times = []

    def submit(self, req):
        from paddle_tpu.inference.serving import EngineSaturated

        if (self.refuse_after is not None
                and len(self.submit_times) >= self.refuse_after):
            raise EngineSaturated("full")
        self.submit_times.append(req)
        self.queue.append(req)

    def step(self):
        for _ in range(self.per_step):
            if self.queue:
                self.done.append(self.queue.pop(0))

    def has_work(self):
        return bool(self.queue)


class TestReplayDriver:
    def test_open_loop_submits_on_schedule_not_on_progress(self):
        sched = generate_schedule(_cfg(duration_s=2.0, rate_rps=10.0))
        clock = VirtualClock()
        target = _FakeTarget(per_step=0)      # server makes NO progress
        drv = ReplayDriver(target, sched, clock=clock, dt_s=0.1,
                           max_steps=30)
        drv.run()
        # every arrival submitted by t=2.0 (20 ticks) even though nothing
        # ever completed — the open-loop contract
        assert drv.stats["submitted"] == len(sched)
        assert target.has_work()

    def test_refusals_counted_never_retried(self):
        sched = generate_schedule(_cfg(duration_s=2.0, rate_rps=10.0))
        clock = VirtualClock()
        target = _FakeTarget(per_step=1, refuse_after=5)
        drv = ReplayDriver(target, sched, clock=clock, dt_s=0.1,
                           max_steps=100)
        drv.run()
        assert drv.stats["submitted"] == 5
        assert drv.stats["refused"] == len(sched) - 5

    def test_windows_rolled_and_report_shape(self):
        sched = generate_schedule(_cfg(duration_s=3.0, rate_rps=5.0))
        clock = VirtualClock()
        tr = TraceRecorder(clock=clock)
        mon = SLOMonitor(SLOConfig(ttft_ms=100.0, window_s=1.0),
                         tracer=tr)
        target = _FakeTarget(per_step=3)
        drv = ReplayDriver(target, sched, clock=clock, dt_s=0.1,
                           monitor=mon, max_steps=100)
        rep = drv.run()
        assert drv.stats["windows"] >= 3
        assert rep["schedule"]["digest"] == schedule_digest(sched)
        assert rep["slo"]["windows"]


@pytest.mark.slow   # two fleet replays over a real tiny-llama engine
#                     (per-replica compiles; ~30-60s) — the CI-gated
#                     subprocess twin is tools/traffic_replay.py
#                     --selftest; fast pins are the classes above
def test_fleet_under_burst_autoscaler_control_arm(tmp_path):
    """The acceptance demonstration, in-process: under the SAME seeded
    burst schedule a fixed 1-replica fleet's attainment collapses below
    target, while the autoscaled fleet adds replicas (and at max engages
    brownout) and recovers the post-control attainment — token streams
    stay intact (every non-shed request completes cleanly)."""
    import os as _os
    import sys as _sys

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    _sys.path.insert(0, _os.path.join(root, "tools"))
    try:
        import traffic_replay as tr
    finally:
        _sys.path.pop(0)

    paddle.seed(11)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
    off = tr.run_replay(str(tmp_path / "off"), autoscale_on=False,
                        model=model)
    on = tr.run_replay(str(tmp_path / "on"), autoscale_on=True,
                       model=model)
    target = off["slo"]["config"]["target_attainment"]
    att_off = tr.second_half_attainment(off)
    att_on = tr.second_half_attainment(on)
    stats = on["autoscaler"]["stats"]
    # control arm: collapse below target, judged failing
    assert att_off is not None and att_off < target
    assert tr.report_exit(off) == 1
    # autoscaled arm: the controller acted and the judgment passes
    # (recovered attainment or brownout engaged at max replicas)
    assert stats["scale_ups"] >= 1
    assert tr.report_exit(on) == 0
    assert att_on > att_off
    # byte-identical schedule drove both arms
    assert on["schedule"]["digest"] == off["schedule"]["digest"]
    # goodput is a real number and positive once recovered
    good = [w["goodput_tokens_per_sec"] for w in on["slo"]["windows"]
            if w["goodput_tokens_per_sec"]]
    assert good and max(good) > 0
