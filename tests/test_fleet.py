"""Fleet-level serving resilience (inference/fleet.py — docs/SERVING.md).

Covers the replica router (least-loaded spread, radix-affinity placement,
warm-prefix hit rate vs a single replica), journal-backed failover with
byte-identical streams (PT-FLT-001, greedy + seeded), rolling drain/restart
with zero failed or duplicated tokens (PT-FLT-002), fleet brownout/shedding
with hysteretic exit (PT-FLT-003/004), the progress-heartbeat wedge
detector, and the drill control arms (failover off, hard restart).

The end-to-end seeded drills (fleet_replica_kill / fleet_drain /
fleet_overload, each flipping the exit code with recovery off) run in
tools/fault_drill.py and are CI-gated via tests/test_ci_gates.py.
"""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
from paddle_tpu.inference.fleet import (FleetConfig, FleetRouter,
                                        ReplicaState)
from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                          EngineSaturated,
                                          PrefixCacheConfig, Request,
                                          RequestShed)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model():
    paddle.seed(13)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    return cfg, LlamaForCausalLM(cfg)


def _prompt(cfg, n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)


def _ref(m, prompt, n):
    out = m.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                     max_new_tokens=n, temperature=0.0,
                     max_length=32).numpy()[0]
    return [int(t) for t in out]


def _build(m, **kw):
    def build():
        return ContinuousBatchingEngine(m, max_batch=2, max_len=32,
                                        page_size=8, block_size=2, **kw)
    return build


class TestRouting:
    def test_least_loaded_spread(self, model, tmp_path):
        """Hash-spread traffic balances: distinct-prompt requests land on
        distinct replicas before any replica doubles up."""
        cfg, m = model
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=3,
                            config=FleetConfig(affinity=False))
        for i in range(3):
            fleet.submit(Request(_prompt(cfg, 6, i), max_new_tokens=2))
        assert sorted(fleet.load().values()) == [1, 1, 1]
        for i in range(3):
            fleet.submit(Request(_prompt(cfg, 6, 10 + i), max_new_tokens=2))
        assert sorted(fleet.load().values()) == [2, 2, 2]
        fleet.run_until_done()
        fleet.close()

    def test_affinity_sticks_and_yields_to_balance(self, model, tmp_path):
        """Same-prefix requests follow the replica that holds the chain,
        UNLESS it is queue_slack deeper than the best candidate."""
        cfg, m = model
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=2,
                            config=FleetConfig(queue_slack=1))
        shared = _prompt(cfg, 16, 3)        # two full 8-token pages
        r0 = Request(shared, max_new_tokens=2)
        fleet.submit(r0)
        home = fleet._assigned[r0.rid]
        # same-prefix request (prefix chain matches both pages) sticks
        r1 = Request(np.concatenate([shared[:8], _prompt(cfg, 8, 4)]),
                     max_new_tokens=2)
        fleet.submit(r1)
        assert fleet._assigned[r1.rid] == home
        assert fleet.stats["affinity_hits"] == 1
        # pile load onto the warm replica until affinity must yield
        spread = []
        for i in range(4):
            r = Request(shared, max_new_tokens=2)
            fleet.submit(r)
            spread.append(fleet._assigned[r.rid])
        assert any(idx != home for idx in spread), \
            "affinity never yielded to queue_slack balance"
        fleet.run_until_done()
        fleet.close()

    def test_no_alive_replica_raises(self, model, tmp_path):
        cfg, m = model
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=1)
        fleet.replicas[0].state = ReplicaState.DEAD
        with pytest.raises(EngineSaturated, match="no alive replica"):
            fleet.submit(Request(_prompt(cfg, 6, 5), max_new_tokens=2))
        fleet.replicas[0].state = ReplicaState.ALIVE   # let close() flush
        fleet.close()


class TestFailover:
    @pytest.mark.slow   # the CI-gated fleet_replica_kill drill covers this
    #                     end-to-end; fast failover coverage lives in
    #                     test_heartbeat_wedge_drives_failover + the
    #                     journal-restart test below
    def test_kill_one_of_three_byte_identical(self, model, tmp_path):
        """Acceptance drill: kill 1 of 3 replicas mid-traffic — every
        unfinished request completes with a stream byte-identical to an
        uninterrupted run (greedy AND seeded sampling)."""
        cfg, m = model
        prompts = [_prompt(cfg, 6, 20 + i) for i in range(6)]
        kws = [dict(max_new_tokens=8, seed=70 + i) for i in range(6)]
        for i in (2, 5):                     # two seeded-sampled streams
            kws[i].update(temperature=0.9, top_p=0.9)
        # uninterrupted single-engine reference: per-request determinism
        # (explicit seeds) makes any fleet placement reproduce it exactly
        ref_eng = _build(m)()
        ref_reqs = [Request(p, **kw) for p, kw in zip(prompts, kws)]
        for r in ref_reqs:
            ref_eng.add_request(r)
        ref_eng.run_until_done(max_steps=500)
        refs = [list(r.tokens) for r in ref_reqs]
        plan = FaultPlan(seed=0, specs=[
            FaultSpec("fleet.replica_kill", "kill", at=2, count=1,
                      match="replica:0:")])
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=3)
        reqs = [Request(p, **kw) for p, kw in zip(prompts, kws)]
        with plan:
            for r in reqs:
                fleet.submit(r)
            fleet.run_until_done(max_steps=500)
        assert plan.log, "kill never fired"
        assert fleet.stats["replica_deaths"] == 1
        assert fleet.stats["failovers"] == 1
        assert [c for c, _ in fleet.events].count("PT-FLT-001") >= 1
        for r, e in zip(reqs, refs):
            assert r.done and not r.failed, r.error
            assert list(r.tokens) == e
        # the dead replica can rejoin cold and serve again
        dead = fleet.stats and fleet.replicas[0]
        assert dead.state == ReplicaState.DEAD
        fleet.restart(0)
        assert fleet.replicas[0].state == ReplicaState.ALIVE
        assert fleet.replicas[0].gen == 1
        fleet.close()

    def test_failover_disabled_control_arm(self, model, tmp_path):
        """failover=False (the drill's control arm): a replica death
        surfaces its in-flight requests as failures instead of hanging."""
        cfg, m = model
        plan = FaultPlan(seed=0, specs=[
            FaultSpec("fleet.replica_kill", "kill", at=1, count=1,
                      match="replica:0:")])
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=2,
                            failover=False)
        reqs = [Request(_prompt(cfg, 6, 30 + i), max_new_tokens=8)
                for i in range(4)]
        with plan:
            for r in reqs:
                fleet.submit(r)
            fleet.run_until_done(max_steps=500)
        lost = [r for r in reqs if r.failed]
        assert lost, "replica death lost nothing with failover disabled"
        assert all("PT-FLT-001" in r.error for r in lost)
        survivors = [r for r in reqs if not r.failed]
        assert all(r.done for r in survivors)
        fleet.close()

    def test_kill_sole_replica_fails_requests(self, model, tmp_path):
        """No survivor to fail over to: requests surface as failed with
        the PT-FLT-001 error instead of hanging the caller."""
        cfg, m = model
        plan = FaultPlan(seed=0, specs=[
            FaultSpec("fleet.replica_kill", "kill", at=1, count=1)])
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=1)
        r = Request(_prompt(cfg, 6, 40), max_new_tokens=8)
        with plan:
            fleet.submit(r)
            fleet.run_until_done(max_steps=100)
        assert r.failed and "no surviving replica" in r.error
        fleet.close()

    def test_heartbeat_wedge_drives_failover(self, model, tmp_path):
        """A replica whose steps keep RETURNING without advancing any
        stream (e.g. every slot deferring forever) is declared dead by the
        progress heartbeat and its journaled work fails over."""
        cfg, m = model
        prompts = [_prompt(cfg, 6, 50 + i) for i in range(2)]
        refs = [_ref(m, p, 6) for p in prompts]
        fleet = FleetRouter(
            _build(m), str(tmp_path), num_replicas=2,
            config=FleetConfig(affinity=False, heartbeat_ttl_s=0.0))
        reqs = [Request(p, max_new_tokens=6) for p in prompts]
        for r in reqs:
            fleet.submit(r)
        wedged = fleet.replicas[0]
        wedged.sup.engine.step = lambda: None      # steps "succeed", no work
        fleet.run_until_done(max_steps=200)
        assert fleet.stats["replica_deaths"] == 1
        assert any("heartbeat stale" in msg for _, msg in fleet.events)
        for r, e in zip(reqs, refs):
            assert r.done and not r.failed, r.error
            assert list(r.tokens) == e
        # the dead journal was retired (migr records): a router restarted
        # over this fleet_dir must not replay work survivors now own
        from paddle_tpu.inference.recovery import RequestJournal
        recs = RequestJournal.load(wedged.journal_path)
        done = {r["rid"] for r in recs if r["k"] in ("fin", "migr")}
        assert all(r["rid"] in done for r in recs if r["k"] == "admit")
        fleet.close()


class TestDrainRestart:
    @pytest.mark.slow   # the CI-gated fleet_drain drill covers this
    #                     end-to-end; fast drain coverage is
    #                     test_drain_migrates_queued_keeps_inflight
    def test_rolling_restart_zero_loss(self, model, tmp_path):
        """Acceptance drill: rolling restart of ALL replicas under traffic
        — zero failed requests, zero duplicated tokens, streams
        byte-identical; every replica rebuilt with a fresh generation."""
        cfg, m = model
        prompts = [_prompt(cfg, 6, 60 + i) for i in range(6)]
        refs = [_ref(m, p, 8) for p in prompts]     # greedy: seed-free
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=3)
        reqs = [Request(p, max_new_tokens=8, seed=90 + i)
                for i, p in enumerate(prompts)]
        for r in reqs:
            fleet.submit(r)
        fleet.step()                        # work in flight everywhere
        fleet.rolling_restart(max_steps=500)
        fleet.run_until_done(max_steps=500)
        assert fleet.stats["restarts"] == 3
        assert all(rep.gen == 1 and rep.state == ReplicaState.ALIVE
                   for rep in fleet.replicas)
        # fresh journals: the generation-0 files are closed and done with
        assert all(rep.journal_path.endswith(".g1.jrnl")
                   for rep in fleet.replicas)
        for r, e in zip(reqs, refs):
            assert r.done and not r.failed, r.error
            assert list(r.tokens) == e      # byte-identical => no dup/loss
        fleet.close()

    def test_drain_migrates_queued_keeps_inflight(self, model, tmp_path):
        """drain(): still-QUEUED requests migrate to survivors (journaled
        ``migr``); requests already in a slot finish on the draining
        replica; the replica rebuilds once idle."""
        cfg, m = model
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=2,
                            config=FleetConfig(affinity=False))
        # 6 requests -> 3 per replica: 2 slotted after a step, 1 queued
        reqs = [Request(_prompt(cfg, 6, 70 + i), max_new_tokens=8)
                for i in range(6)]
        for r in reqs:
            fleet.submit(r)
        fleet.step()
        fleet.drain(0)
        assert fleet.replicas[0].state == ReplicaState.DRAINING
        assert fleet.stats["migrated"] >= 1
        recs = fleet.replicas[0].sup.journal.records
        assert any(rec["k"] == "migr" for rec in recs)
        with pytest.raises(EngineSaturated):     # draining: not routable
            probe = Request(_prompt(cfg, 6, 99), max_new_tokens=2)
            fleet.replicas[1].state = ReplicaState.DEAD   # force no target
            try:
                fleet.submit(probe)
            finally:
                fleet.replicas[1].state = ReplicaState.ALIVE
        fleet.run_until_done(max_steps=500)
        assert fleet.replicas[0].state == ReplicaState.ALIVE
        assert fleet.replicas[0].gen == 1
        assert all(r.done and not r.failed for r in reqs)
        fleet.close()

    def test_hard_restart_control_arm(self, model, tmp_path):
        """graceful_drain=False models restart-without-drain deployments:
        in-flight work is lost (the mode graceful drain exists to
        prevent), and the replica comes back cold."""
        cfg, m = model
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=2,
                            graceful_drain=False)
        reqs = [Request(_prompt(cfg, 6, 80 + i), max_new_tokens=8)
                for i in range(4)]
        for r in reqs:
            fleet.submit(r)
        fleet.step()
        fleet.drain(0)
        lost = [r for r in reqs if r.failed]
        assert lost and all("PT-FLT-002" in r.error for r in lost)
        assert fleet.replicas[0].state == ReplicaState.ALIVE   # respawned
        assert fleet.replicas[0].gen == 1
        fleet.run_until_done(max_steps=500)
        assert all(r.done for r in reqs)
        fleet.close()


class TestBrownout:
    def test_fleet_brownout_sheds_and_exits(self, model, tmp_path):
        """PT-FLT-003/004: when EVERY alive replica sits at depth the
        fleet sheds sheddable-priority traffic at submit with a typed
        RequestShed; priority traffic still admits; the brownout exits
        hysteretically once pressure clears."""
        cfg, m = model
        fleet = FleetRouter(
            _build(m, max_queue=4), str(tmp_path), num_replicas=2,
            config=FleetConfig(brownout_depth=1, brownout_enter_after=2,
                               brownout_exit_after=2))
        flood = [Request(_prompt(cfg, 6, 100 + i), max_new_tokens=4,
                         priority=Request.PRIORITY_LOW) for i in range(8)]
        shed = 0
        for r in flood:
            try:
                fleet.submit(r)
            except RequestShed as e:
                assert "PT-FLT-003" in str(e)
                shed += 1
        assert fleet.stats["brownouts"] == 1
        assert shed and fleet.stats["fleet_shed"] == shed
        vip = Request(_prompt(cfg, 6, 120), max_new_tokens=4,
                      priority=Request.PRIORITY_HIGH)
        fleet.submit(vip)                   # priority bypasses the shed
        fleet.run_until_done(max_steps=500)
        assert vip.done and not vip.failed
        for _ in range(3):                  # serving loops tick when idle —
            fleet.step()                    # pressure-free events accumulate
        assert not fleet._brownout_active   # hysteretic exit happened
        assert any(c == "PT-FLT-004" and "exited" in msg
                   for c, msg in fleet.events)
        fleet.close()


class TestAffinityHitRate:
    def test_warm_prefix_hit_rate_vs_single_replica(self, model, tmp_path):
        """Acceptance: the affinity router keeps the fleet's warm-prefix
        hit rate at least at the single-replica baseline — same-prefix
        sessions stick to the replica holding the blocks instead of
        scattering to cold caches."""
        cfg, m = model
        build = _build(m, prefix_cache=PrefixCacheConfig(extra_blocks=4))
        shared = _prompt(cfg, 16, 7)         # two full pages of prefix

        def sessions():
            # 6 same-prefix sessions in 3 arrival waves, decoded between
            # waves so later sessions can hit blocks earlier ones cached
            rng = np.random.default_rng(8)
            return [np.concatenate(
                [shared, rng.integers(0, cfg.vocab_size, (4,))
                 .astype(np.int32)]) for _ in range(6)]

        def hit_rate(fleet):
            for wave in range(3):
                for p in sessions()[wave * 2:(wave + 1) * 2]:
                    fleet.submit(Request(p, max_new_tokens=2))
                fleet.run_until_done(max_steps=500)
            hits = misses = 0
            for rep in fleet.replicas:
                hits += rep.sup.engine.stats["hit_tokens"]
                misses += rep.sup.engine.stats["miss_tokens"]
            fleet.close()
            return hits / max(1, hits + misses)

        single = hit_rate(FleetRouter(build, str(tmp_path / "one"),
                                      num_replicas=1))
        fleet = hit_rate(FleetRouter(build, str(tmp_path / "three"),
                                     num_replicas=3))
        assert single > 0, "baseline never hit its own cache"
        assert fleet >= single, (fleet, single)


class TestFleetJournalRestart:
    def test_router_restart_over_journals(self, model, tmp_path):
        """A FleetRouter constructed over an existing fleet_dir finds each
        replica's generation-0 journal; every supervisor re-admits its own
        unfinished requests automatically and the reconstructed streams
        complete byte-identically."""
        cfg, m = model
        prompts = [_prompt(cfg, 6, 130 + i) for i in range(2)]
        refs = [_ref(m, p, 6) for p in prompts]
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=2,
                            config=FleetConfig(affinity=False))
        reqs = [Request(p, max_new_tokens=6) for p in prompts]
        for r in reqs:
            fleet.submit(r)
        fleet.step()                        # some tokens delivered
        for rep in fleet.replicas:          # process dies: no clean close
            rep.sup.abandon()
        fleet2 = FleetRouter(_build(m), str(tmp_path), num_replicas=2,
                             config=FleetConfig(affinity=False))
        fleet2.run_until_done(max_steps=500)
        out = []
        for rep in fleet2.replicas:
            out.extend(rep.sup.requests.values())
        assert sorted([r.rid for r in out]) == sorted(r.rid for r in reqs)
        by_rid = {r.rid: r for r in out}
        for r, e in zip(reqs, refs):
            got = by_rid[r.rid]
            assert got.done and not got.failed, got.error
            assert [int(t) for t in got.output] == e
        fleet2.close()

    def test_router_restart_resumes_latest_generation(self, model, tmp_path):
        """A rolling restart leaves g1 journals; a router restarted over
        the fleet_dir must resume THOSE (replaying a superseded g0 would
        lose the newer work)."""
        cfg, m = model
        fleet = FleetRouter(_build(m), str(tmp_path), num_replicas=2,
                            config=FleetConfig(affinity=False))
        fleet.rolling_restart()             # idle: drains instantly, g0->g1
        assert all(rep.gen == 1 for rep in fleet.replicas)
        prompts = [_prompt(cfg, 6, 140 + i) for i in range(2)]
        refs = [_ref(m, p, 6) for p in prompts]
        reqs = [Request(p, max_new_tokens=6) for p in prompts]
        for r in reqs:
            fleet.submit(r)
        fleet.step()
        for rep in fleet.replicas:
            rep.sup.abandon()               # router process dies
        fleet2 = FleetRouter(_build(m), str(tmp_path), num_replicas=2,
                             config=FleetConfig(affinity=False))
        assert all(rep.gen == 1 and rep.journal_path.endswith(".g1.jrnl")
                   for rep in fleet2.replicas)
        fleet2.run_until_done(max_steps=500)
        out = {r.rid: r for rep in fleet2.replicas
               for r in rep.sup.requests.values()}
        for r, e in zip(reqs, refs):
            got = out[r.rid]
            assert got.done and not got.failed, got.error
            assert [int(t) for t in got.output] == e
        fleet2.close()
