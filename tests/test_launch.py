"""Tests for distributed.launch (controllers, env contract, elastic manager).

Mirrors the reference's single-host multi-process launch tests
(test/legacy_test/test_parallel_dygraph_dataparallel.py start_local_trainers):
real subprocesses on one host, CPU backend, results checked via files.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.distributed.communication.store import TCPStore
from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus
from paddle_tpu.distributed.launch.controllers import (
    CollectiveController, Context, LaunchArgs)

WORKER = """
import json, os, sys
out = sys.argv[1]
rec = {k: os.environ.get(k) for k in (
    "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "PADDLE_TRAINER_ENDPOINTS",
    "PADDLE_RANK_IN_NODE", "PADDLE_NNODES", "MASTER_ADDR", "MASTER_PORT")}
with open(os.path.join(out, f"rank{os.environ['PADDLE_TRAINER_ID']}.json"), "w") as f:
    json.dump(rec, f)
"""


def test_single_node_launch(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    args = LaunchArgs(script=str(script), script_args=[str(tmp_path)],
                      nproc_per_node=3, log_dir=str(tmp_path / "log"))
    code = CollectiveController(Context(args)).run()
    assert code == 0
    recs = {}
    for r in range(3):
        recs[r] = json.load(open(tmp_path / f"rank{r}.json"))
    assert recs[0]["PADDLE_TRAINERS_NUM"] == "3"
    assert recs[2]["PADDLE_TRAINER_ID"] == "2"
    assert len(recs[0]["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 3
    assert recs[1]["PADDLE_RANK_IN_NODE"] == "1"


def test_launch_nonzero_exit(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)")
    args = LaunchArgs(script=str(script), nproc_per_node=2,
                      log_dir=str(tmp_path / "log"))
    code = CollectiveController(Context(args)).run()
    assert code == 3


def test_launch_cli_module(tmp_path):
    env = dict(os.environ)
    env["PT_LAUNCH_OUT"] = str(tmp_path)
    script = tmp_path / "w.py"
    script.write_text(WORKER)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "log"),
         str(script), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert (tmp_path / "rank0.json").exists()
    assert (tmp_path / "rank1.json").exists()


def test_multinode_rendezvous_via_store(tmp_path):
    """Two 'nodes' (threads driving controllers) rendezvous over one store."""
    import threading

    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2, timeout=30)
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    codes = {}

    def run_node(idx):
        args = LaunchArgs(script=str(script), script_args=[str(tmp_path)],
                          master=f"127.0.0.1:{master.port}", nnodes="2",
                          nproc_per_node=1, job_id="t2",
                          log_dir=str(tmp_path / f"log{idx}"))
        codes[idx] = CollectiveController(Context(args)).run()

    ts = [threading.Thread(target=run_node, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    master.close()
    assert codes == {0: 0, 1: 0}
    recs = [json.load(open(tmp_path / f"rank{r}.json")) for r in range(2)]
    assert {r["PADDLE_TRAINER_ID"] for r in recs} == {"0", "1"}
    assert all(r["PADDLE_TRAINERS_NUM"] == "2" for r in recs)
    assert all(r["PADDLE_NNODES"] == "2" for r in recs)


def test_elastic_manager_detects_dead_peer():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2, timeout=10)
    a = ElasticManager(master, "job", "nodeA", ["nodeA", "nodeB"],
                       heartbeat_interval=0.1, ttl=0.5)
    b = ElasticManager(master, "job", "nodeB", ["nodeA", "nodeB"],
                       heartbeat_interval=0.1, ttl=0.5)
    a.start()
    b.start()
    try:
        time.sleep(0.3)
        assert sorted(a.alive_peers()) == ["nodeA", "nodeB"]
        assert not a.peers_changed()
        b.stop()  # nodeB dies
        deadline = time.time() + 5
        while not a.peers_changed() and time.time() < deadline:
            time.sleep(0.1)
        assert a.peers_changed()
        assert a.alive_peers() == ["nodeA"]
    finally:
        a.stop()
        b.stop()
        master.close()


def test_enable_elastic_env(monkeypatch):
    from paddle_tpu.distributed.fleet.elastic import enable_elastic

    monkeypatch.setenv("PADDLE_ELASTIC_NNODES", "2:4")
    assert enable_elastic()
    monkeypatch.setenv("PADDLE_ELASTIC_NNODES", "4")
    assert not enable_elastic()


ELASTIC_TRAIN_WORKER = """
import json, os, sys, time
sys.path.insert(0, os.getcwd())   # repo root (controller inherits test cwd)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import paddle_tpu as paddle

out, total, kill_at = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
restart = int(os.environ.get("PADDLE_RESTART_NUM", "0"))

paddle.seed(0)
model = paddle.nn.Linear(4, 4)
opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
ckpt = os.path.join(out, "ckpt.pdparams")
start = 0
if os.path.exists(ckpt):
    state = paddle.load(ckpt)
    model.set_state_dict(state["model"])
    start = int(state["step"])

rng = np.random.default_rng(7)
x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
y = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
log = os.path.join(out, f"loss_rank{rank}.jsonl")
for step in range(start, total):
    loss = ((model(x) - y) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    with open(log, "a") as f:
        f.write(json.dumps({"step": step, "restart": restart,
                            "loss": float(loss.numpy())}) + "\\n")
    if rank == 0:
        tmp = ckpt + ".tmp"
        paddle.save({"model": model.state_dict(), "step": step + 1}, tmp)
        os.replace(tmp, ckpt)
    if restart == 0 and rank == 1 and step + 1 == kill_at:
        os._exit(7)   # simulated hard worker failure
    time.sleep(0.05)
"""


@pytest.mark.slow   # subprocess relaunch pays a fresh jax import + compile
#                     (~11s); elastic resume keeps fast in-process coverage in
#                     test_lifecycle plus the tier-2 lifecycle_e2e drill
def test_elastic_relaunch_resumes_from_checkpoint(tmp_path):
    """End-to-end elastic drill (round 5, VERDICT item 6): a worker dies
    mid-train, the elastic controller detects the fault, relaunches the
    generation, and training RESUMES from the checkpoint with loss
    continuity — reference launch/controllers/collective.py:262 +
    fleet/elastic/manager.py:125 fault model (restart from checkpoint)."""
    from paddle_tpu.distributed.launch.controllers import (
        CollectiveElasticController)

    script = tmp_path / "train.py"
    script.write_text(ELASTIC_TRAIN_WORKER)
    total, kill_at = 30, 8
    args = LaunchArgs(script=str(script),
                      script_args=[str(tmp_path), str(total), str(kill_at)],
                      nproc_per_node=2, elastic_level=3,
                      log_dir=str(tmp_path / "log"))
    code = CollectiveElasticController(Context(args)).run()
    assert code == 0

    recs = [json.loads(ln) for ln in
            (tmp_path / "loss_rank0.jsonl").read_text().splitlines()]
    gen0 = [r for r in recs if r["restart"] == 0]
    gen1 = [r for r in recs if r["restart"] >= 1]
    # the relaunch actually happened and RESUMED mid-run (not from scratch)
    assert gen1, "no relaunched generation recorded"
    assert gen1[0]["step"] > 0, "restart began from step 0 — checkpoint ignored"
    assert gen1[0]["step"] >= min(kill_at - 1, gen0[-1]["step"])
    # the full run completed across the restart boundary
    assert recs[-1]["step"] == total - 1
    # loss continuity: resumed loss continues the descent rather than
    # re-starting at the fresh-init loss
    assert gen1[0]["loss"] < gen0[0]["loss"]
    assert recs[-1]["loss"] < gen1[0]["loss"]
