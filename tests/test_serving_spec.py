"""Speculative multi-token decoding in the fused mega-step
(inference/serving.py ``speculative=SpecConfig(...)`` — docs/SERVING.md
"Speculative decode").

The contract under test: greedy speculative token streams are
BYTE-IDENTICAL to the non-speculative mega-step — drafts only change how
many tokens a dispatch emits, never which — across slot widths, warm/cold
radix admissions, COW divergence, migration and crash replay, with
acceptance > 0 on a repetitive workload. Engine waves are slow-marked
(tier-1 sits near its 870 s ceiling); the FAST pins below cover the pure
accept/reject math and the device drafter with no model or compile.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                          KVCacheConfig, PrefixCacheConfig,
                                          Request, SpecConfig, ngram_draft,
                                          spec_accept)


# ---------------------------------------------------------------------------
# FAST pins: pure host-testable accept/reject + drafter math (no model)
# ---------------------------------------------------------------------------

def test_spec_accept_longest_prefix_plus_bonus():
    drafts = np.array([[5, 6, 7],      # all accepted -> 3 drafts + bonus
                       [5, 9, 7],      # reject at 1 -> 1 draft + bonus
                       [1, 2, 3]])     # reject at 0 -> bonus only
    targets = np.array([[5, 6, 7, 8],
                        [5, 6, 7, 8],
                        [9, 9, 9, 9]])
    caps = np.array([10, 10, 10])
    out, emit, n_acc = (np.asarray(x) for x in
                        spec_accept(drafts, targets, caps))
    assert list(n_acc) == [3, 1, 0]
    assert list(emit) == [4, 2, 1]
    # emitted tokens == accepted drafts + the model's own next token
    assert list(out[0][:4]) == [5, 6, 7, 8]
    assert list(out[1][:2]) == [5, 6]
    assert list(out[2][:1]) == [9]


def test_spec_accept_caps_clamp_and_mask():
    drafts = np.array([[5, 6], [5, 6]])
    targets = np.array([[5, 6, 7], [5, 6, 7]])
    out, emit, n_acc = (np.asarray(x) for x in
                        spec_accept(drafts, targets, np.array([2, 0])))
    assert list(emit) == [2, 0]        # cap truncates; cap 0 masks the row
    assert list(out[0][:2]) == [5, 6]  # truncation keeps the draft prefix


def test_ngram_draft_continuation_and_fallback():
    H, k, n = 8, 3, 2
    # ring holds tokens [1,2,3,4,1,2] (hlen=6 < H: slots 0..5), last=3 ->
    # tail (2, 3) matched at global positions 1..2, continuation 4, 1, 2
    hist = np.zeros((2, H), np.int32)
    hist[0, :6] = [1, 2, 3, 4, 1, 2]
    hlen = np.array([6, 0], np.int32)
    last = np.array([3, 7], np.int32)
    drafts = np.asarray(ngram_draft(hist, hlen, last, k, n))
    assert list(drafts[0]) == [4, 1, 2]
    # row 1 has no history -> fallback repeats the last token
    assert list(drafts[1]) == [7, 7, 7]


def test_ngram_draft_ring_wraparound():
    H, k, n = 4, 2, 2
    # 6 tokens written through a 4-ring: global g at slot g % 4 ->
    # ring holds [4, 5, 2, 3] for stream [.., 2, 3, 4, 5]; last = 2 ->
    # window is [2, 3, 4, 5, 2]; tail (5, 2) has no earlier match ->
    # fallback; tail (2, 3)... use last=3 after stream [1,2,3,4,2,3]:
    stream = [1, 2, 3, 4, 2, 3]
    hist = np.zeros((1, H), np.int32)
    for g, t in enumerate(stream):
        hist[0, g % H] = t
    hlen = np.array([len(stream)], np.int32)
    last = np.array([4], np.int32)
    # window (last H + last_tok) = [3, 4, 2, 3, 4]; tail (3, 4) matches at
    # window start 0 -> continuation [2, 3]
    drafts = np.asarray(ngram_draft(hist, hlen, last, k, n))
    assert list(drafts[0]) == [2, 3]


def test_spec_config_validation():
    with pytest.raises(ValueError, match="k .* must be >= 1|>= 1"):
        _Cfg = SpecConfig(k=0)
        _validate_engine(speculative=_Cfg)
    with pytest.raises(ValueError, match="history .* too short"):
        _validate_engine(speculative=SpecConfig(k=4, history=4))
    with pytest.raises(ValueError, match="fused"):
        _validate_engine(speculative=True, fused=False)
    with pytest.raises(ValueError, match="unsupported KV cache dtype"):
        KVCacheConfig(dtype="int4")


def _validate_engine(**kw):
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(11)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    kw.setdefault("fused", True)
    return ContinuousBatchingEngine(LlamaForCausalLM(cfg), max_batch=2,
                                    max_len=32, page_size=8, **kw)


def test_spec_seed_ring_layout():
    """Activation seeds lay prompt tokens at ring slot g % H so the spec
    program's ring arithmetic continues seamlessly, including prompts
    longer than the ring."""
    eng = _validate_engine(speculative=SpecConfig(history=8))
    row, hlen = eng._spec_seed(np.arange(100, 112, dtype=np.int32))
    assert hlen == 12
    # last 8 tokens (global 4..11) at slots 4%8..11%8
    expect = np.zeros(8, np.int32)
    for g in range(4, 12):
        expect[g % 8] = 100 + g
    assert list(row) == list(expect)
    # migration seed appends delivered tokens after the prompt
    row2, hlen2 = eng._spec_seed(np.arange(3, dtype=np.int32),
                                 extra=[7, 8])
    assert hlen2 == 5 and row2[3] == 7 and row2[4] == 8


def test_spec_metrics_families_render_at_zero():
    """pt_spec_* + pt_kv_quant_blocks are REQUIRED families: they must
    render on a fresh engine (zeros) — scrape dashboards never lose them."""
    from paddle_tpu.observability import engine_collector

    eng = _validate_engine(speculative=True)
    fams = {f.name: f for f in engine_collector(eng)()}
    for name in ("pt_spec_proposed_total", "pt_spec_accepted_total",
                 "pt_spec_acceptance_rate", "pt_kv_quant_blocks"):
        assert name in fams, sorted(fams)
        assert fams[name].samples


# ---------------------------------------------------------------------------
# engine waves (slow): byte-identity across widths/warm/cold/COW/replay
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    paddle.seed(11)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    return cfg, LlamaForCausalLM(cfg)


def _wave(cfg, rng_seed=300):
    rng = np.random.default_rng(rng_seed)
    motif = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)
    # prompt 0 is long-repetitive with a long continuation (the drafter's
    # food — greedy streams of a tiny model settle into loops the n-gram
    # lookup then predicts); 16/24 are full-page multiples so a warm
    # re-serve takes the full-prompt-hit COW path
    prompts = [np.tile(motif, 6),
               np.tile(motif, 4),
               rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32),
               rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)]
    kws = [dict(max_new_tokens=24), dict(max_new_tokens=10),
           dict(max_new_tokens=8), dict(max_new_tokens=6)]
    return prompts, kws


def _serve(eng, prompts, kws, stagger=True):
    reqs = [Request(p, **k) for p, k in zip(prompts, kws)]
    head, tail = (reqs[:2], reqs[2:]) if stagger else (reqs, [])
    for r in head:
        eng.add_request(r)
    eng.step()
    for r in tail:
        eng.add_request(r)
    eng.run_until_done(max_steps=800)
    return [list(r.tokens) for r in reqs]


@pytest.mark.slow   # several engine compiles (spec + nonspec, two widths,
#                     prefix on/off) — fast pins above cover the math
def test_spec_byte_identity_cross_widths_warm_cold_cow(model):
    cfg, m = model
    prompts, kws = _wave(cfg)
    ref = _serve(ContinuousBatchingEngine(
        m, max_batch=4, max_len=64, page_size=8, block_size=2, fused=True),
        prompts, kws)
    # width 4, prefix off
    s4 = ContinuousBatchingEngine(
        m, max_batch=4, max_len=64, page_size=8, block_size=2, fused=True,
        speculative=SpecConfig(k=3))
    assert _serve(s4, prompts, kws) == ref
    # cross slot width (6 slots, different mega shape) + prefix cache:
    # cold then warm re-serve — the warm wave takes the full-prompt-hit
    # COW path for the repeated 16-token prompts
    s6 = ContinuousBatchingEngine(
        m, max_batch=6, max_len=64, page_size=8, block_size=2, fused=True,
        speculative=SpecConfig(k=3),
        prefix_cache=PrefixCacheConfig(prefill_chunk=16, extra_blocks=12))
    cold = _serve(s6, prompts, kws)
    warm = _serve(s6, prompts, kws)
    assert cold == ref
    assert warm == ref
    assert s6.stats["cow_copies"] >= 1          # the COW path really ran
    # acceptance > 0 on the repetitive workload (the ISSUE acceptance pin)
    assert s4.stats["spec_accepted"] > 0
    assert s4.stats["spec_proposed"] > 0
    assert 0 < s4.stats["spec_steps"] < sum(
        k["max_new_tokens"] for k in kws)       # multi-token dispatches


@pytest.mark.slow   # supervisor replay recompiles the engine mid-test
def test_spec_crash_replay_byte_identical(model, tmp_path):
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec
    from paddle_tpu.inference.serving import ServingSupervisor

    cfg, m = model
    prompts, kws = _wave(cfg)

    def build():
        return ContinuousBatchingEngine(
            m, max_batch=4, max_len=64, page_size=8, block_size=2,
            fused=True, speculative=SpecConfig(k=3),
            prefix_cache=PrefixCacheConfig(extra_blocks=8))

    ref_eng = build()
    reqs = [Request(p, **k) for p, k in zip(prompts, kws)]
    for r in reqs:
        ref_eng.add_request(r)
    ref_eng.run_until_done(max_steps=800)
    refs = [list(r.tokens) for r in reqs]

    plan = FaultPlan(seed=5, specs=[
        FaultSpec("serving.step", "kill", at=3, count=1)])
    sup = ServingSupervisor(build, str(tmp_path / "j.jrnl"))
    reqs2 = [Request(p, **k) for p, k in zip(prompts, kws)]
    with plan:
        for r in reqs2:
            sup.submit(r)
        sup.run_until_done(max_steps=2000)
    assert plan.log, "the mid-decode kill never fired"
    assert sup.stats["recoveries"] >= 1
    assert [list(r.tokens) for r in reqs2] == refs


@pytest.mark.slow   # tiered-router migration wave (two engines + codec)
def test_spec_stream_survives_migration(model, tmp_path):
    """A chain exported mid-decode from a spec engine and spliced into
    another spec engine continues byte-identically — the migrated drafter
    ring is re-seeded from prompt + delivered tokens."""
    from paddle_tpu.inference.disagg import KVChainCodec

    cfg, m = model
    rng = np.random.default_rng(9)
    prompt = np.tile(rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32),
                     4)

    def build():
        return ContinuousBatchingEngine(
            m, max_batch=2, max_len=64, page_size=8, block_size=2,
            fused=True, speculative=SpecConfig(k=3), prefix_cache=True)

    ref_eng = build()
    r0 = Request(prompt, max_new_tokens=16)
    ref_eng.add_request(r0)
    ref_eng.run_until_done(max_steps=400)
    ref = list(r0.tokens)

    src = build()
    r1 = Request(prompt, max_new_tokens=16)
    src.add_request(r1)
    src.step()                        # prefill + first tokens scheduled
    assert src.migration_ready() == [r1.rid]
    codec = KVChainCodec()
    art = codec.export_chain(src, r1.rid)
    src.withdraw_active(r1.rid)
    dst = build()
    twin = codec.import_chain(dst, art)
    dst.run_until_done(max_steps=400)
    assert list(twin.tokens) == ref


@pytest.mark.slow   # one spec engine wave with eos materialization
def test_spec_eos_and_mixed_sampling_fallback(model):
    cfg, m = model
    prompts, kws = _wave(cfg)

    def build(**kw):
        return ContinuousBatchingEngine(
            m, max_batch=4, max_len=64, page_size=8, block_size=2,
            fused=True, **kw)

    # eos: pick a token the greedy stream actually emits so early-exit
    # fires inside a speculative dispatch
    ref0 = _serve(build(), prompts, kws, stagger=False)
    eos = ref0[0][4]
    kws_eos = [dict(k, eos_token_id=eos) for k in kws]
    ref = _serve(build(), prompts, kws_eos, stagger=False)
    got = _serve(build(speculative=SpecConfig(k=3)), prompts, kws_eos,
                 stagger=False)
    assert got == ref
    # mixed greedy + seeded sampling: sampled blocks keep the legacy
    # mega-step; streams still match the non-spec engine exactly
    kws_mix = [dict(kws[0]), dict(kws[1], temperature=0.9, seed=7),
               dict(kws[2]), dict(kws[3], temperature=1.1, seed=3)]
    ref_mix = _serve(build(), prompts, kws_mix)
    got_mix = _serve(build(speculative=SpecConfig(k=3)), prompts, kws_mix)
    assert got_mix == ref_mix
