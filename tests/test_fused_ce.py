"""Fused chunked linear+CE (ops/fused_ce.py) vs the plain materialized path.

Reference semantics: LlamaPretrainingCriterion (shifted causal-LM CE,
fp32 softmax, ignore_index masking) — the fused op must match value AND
gradients (wrt hidden and lm-head weight) since it swaps in transparently
via LlamaConfig.fused_ce.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models.llama.modeling import LlamaConfig, LlamaForCausalLM, \
    LlamaPretrainingCriterion
from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy


def _plain(hidden, w, labels, ignore_index=-100):
    logits = jnp.matmul(hidden, w)
    return LlamaPretrainingCriterion.compute(logits, labels,
                                             ignore_index=ignore_index)


@pytest.mark.parametrize("seq,chunk", [(16, 8), (10, 4), (7, 16)])
def test_fused_ce_matches_plain(seq, chunk):
    rng = np.random.default_rng(0)
    b, h, v = 2, 32, 64
    hidden = jnp.asarray(rng.normal(size=(b, seq, h)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(h, v)).astype(np.float32) * 0.1)
    labels = jnp.asarray(rng.integers(0, v, (b, seq)).astype(np.int32))

    ref = _plain(hidden, w, labels)
    got = fused_linear_cross_entropy(hidden, w, labels, chunk=chunk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5)


def test_fused_ce_ignore_index():
    rng = np.random.default_rng(1)
    b, s, h, v = 2, 12, 16, 32
    hidden = jnp.asarray(rng.normal(size=(b, s, h)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(h, v)).astype(np.float32) * 0.1)
    labels = rng.integers(0, v, (b, s)).astype(np.int32)
    labels[0, 3:7] = -100
    labels[1, -2:] = -100
    labels = jnp.asarray(labels)

    ref = _plain(hidden, w, labels)
    got = fused_linear_cross_entropy(hidden, w, labels, chunk=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5)


def test_fused_ce_grads_match():
    rng = np.random.default_rng(2)
    b, s, h, v = 2, 12, 16, 32
    hidden = jnp.asarray(rng.normal(size=(b, s, h)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(h, v)).astype(np.float32) * 0.1)
    labels = rng.integers(0, v, (b, s)).astype(np.int32)
    labels[1, 5] = -100
    labels = jnp.asarray(labels)

    g_ref = jax.grad(lambda hh, ww: _plain(hh, ww, labels), argnums=(0, 1))(
        hidden, w)
    g_fus = jax.grad(
        lambda hh, ww: fused_linear_cross_entropy(hh, ww, labels, chunk=4),
        argnums=(0, 1))(hidden, w)
    for a, b_ in zip(g_fus, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


def test_llama_loss_fused_vs_plain():
    """Model-level: LlamaConfig.fused_ce swaps the loss implementation only."""
    cfg_f = LlamaConfig.tiny(fused_ce=True, fused_ce_chunk=8)
    model = LlamaForCausalLM(cfg_f)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg_f.vocab_size, (2, 16)).astype(np.int32))

    loss_fused = model.loss_fn(ids, ids)
    model.config.fused_ce = False
    loss_plain = model.loss_fn(ids, ids)
    np.testing.assert_allclose(np.asarray(loss_fused), np.asarray(loss_plain),
                               rtol=1e-5)


def test_llama_loss_fused_tied_embeddings():
    cfg = LlamaConfig.tiny(fused_ce=True, fused_ce_chunk=8,
                           tie_word_embeddings=True)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(4)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32))
    loss_fused = model.loss_fn(ids, ids)
    model.config.fused_ce = False
    loss_plain = model.loss_fn(ids, ids)
    np.testing.assert_allclose(np.asarray(loss_fused), np.asarray(loss_plain),
                               rtol=1e-5)


def test_fused_ce_bf16_dw_fp32_accumulation():
    """bf16 params: dW must accumulate across chunks in fp32 (scan carry),
    so the chunked grad tracks the unfused fp32 reference within bf16
    resolution even with many chunks."""
    rng = np.random.default_rng(5)
    b, s, h, v = 2, 64, 32, 48
    hidden_f = rng.normal(size=(b, s, h)).astype(np.float32)
    w_f = (rng.normal(size=(h, v)) * 0.1).astype(np.float32)
    labels = jnp.asarray(rng.integers(0, v, (b, s)).astype(np.int32))

    # fp32 unfused reference grad
    _, dw_ref = jax.grad(lambda hh, ww: _plain(hh, ww, labels),
                         argnums=(0, 1))(jnp.asarray(hidden_f),
                                         jnp.asarray(w_f))
    hidden_bf = jnp.asarray(hidden_f).astype(jnp.bfloat16)
    w_bf = jnp.asarray(w_f).astype(jnp.bfloat16)
    _, dw_bf = jax.grad(
        lambda hh, ww: fused_linear_cross_entropy(hh, ww, labels, chunk=8),
        argnums=(0, 1))(hidden_bf, w_bf)
    assert dw_bf.dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits; fp32 accumulation keeps the error at
    # single-rounding scale instead of sqrt(n_chunks) growth
    np.testing.assert_allclose(np.asarray(dw_bf, np.float32),
                               np.asarray(dw_ref), rtol=0.05, atol=3e-3)
