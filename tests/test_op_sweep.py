"""Numeric sweep over the public op surface (round-5 response to VERDICT
"numeric op-test breadth").

Every spec in op_sweep_specs.SPECS runs through op_test.check_output in BOTH
eager and jit modes against its numpy/scipy reference; the differentiable
subset additionally runs op_test.check_grad (numeric central differences vs
the tape). The distinct-symbol count is gated here AND in test_ci_gates so
coverage can only ratchet up.

Reference model: test/legacy_test/op_test.py:418 (check_output :2910,
check_grad :3114) applied across 1,183 files; here one parametrized driver
covers the table.
"""

from __future__ import annotations

import numpy as np
import pytest

from op_test import check_grad, check_output
from op_sweep_specs import SPECS, distinct_symbols, grad_specs

MIN_DISTINCT_SYMBOLS = 650
MIN_GRAD_SPECS = 60


@pytest.mark.parametrize("spec", SPECS, ids=[s.name for s in SPECS])
def test_op_numeric(spec):
    check_output(spec.fn, spec.ref, list(spec.inputs), rtol=spec.rtol,
                 atol=spec.atol, modes=spec.modes)


@pytest.mark.parametrize("spec", grad_specs(),
                         ids=[s.name for s in grad_specs()])
def test_op_grad(spec):
    check_grad(spec.fn, list(spec.grad_inputs or spec.inputs),
               grad_idx=spec.grad_idx)


def test_sweep_symbol_coverage():
    """Coverage floor: the sweep exercises >= MIN_DISTINCT_SYMBOLS distinct
    manifest symbols (paddle:/method:/functional:/linalg:/fft:/incubate:).
    Raising coverage should raise the floor; lowering it must fail CI."""
    syms = distinct_symbols()
    assert len(syms) >= MIN_DISTINCT_SYMBOLS, (
        f"op sweep covers {len(syms)} symbols, need {MIN_DISTINCT_SYMBOLS}")


def test_sweep_grad_coverage():
    assert len(grad_specs()) >= MIN_GRAD_SPECS
