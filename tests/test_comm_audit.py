"""PT-COMM — the static collective-communication auditor
(paddle_tpu/static/comm, docs/STATIC_ANALYSIS.md "Collective
communication" section).

Everything here is PURE TRACING — shard_map under a symbolic
``AbstractMesh`` through ``trace_to_program``, no XLA compile, no
devices — so the whole module runs in seconds. The end-to-end pins (the
real MULTICHIP sweep, the seeded-defect selftest, the zero-compile
counter) run as subprocess gates in tests/test_ci_gates.py via
tools/audit_collectives.py.
"""

import json
import os
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.framework import jax_compat
from paddle_tpu.static.analysis import run_analysis, trace_to_program
from paddle_tpu.static.comm import (CollectiveCommPass, CommManifest,
                                    CommPathSpec, abstract_mesh,
                                    check_comm_contract, check_gather_reduce,
                                    check_loop_invariant_collectives,
                                    check_mesh_scaling, check_replication,
                                    compute_comm_manifest, iter_collectives,
                                    mesh_scaling_verdict, mesh_spec,
                                    wire_bytes)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def _trace(fn, *structs, names=None):
    return trace_to_program(fn, *structs,
                            input_names=names or [f"in{i}" for i
                                                  in range(len(structs))])


def _sharded(body, width=4, in_specs=None, out_specs=P(), axes=None):
    mesh = abstract_mesh(axes or {"x": width})
    return jax_compat.shard_map(body, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=False)


# ---------------------------------------------------------------------------
# ring wire-byte rules
# ---------------------------------------------------------------------------

def test_wire_bytes_ring_formulas():
    """Per device per dispatch, n-member ring, b payload bytes:
    psum 2(n-1)/n*b, all_gather (n-1)*b, reduce_scatter and all_to_all
    (n-1)/n*b, ppermute b."""
    b, n = 1024.0, 4
    assert wire_bytes("psum", b, n) == pytest.approx(2 * 3 / 4 * b)
    assert wire_bytes("pmax", b, n) == pytest.approx(2 * 3 / 4 * b)
    assert wire_bytes("all_gather", b, n) == pytest.approx(3 * b)
    assert wire_bytes("reduce_scatter", b, n) == pytest.approx(3 / 4 * b)
    assert wire_bytes("all_to_all", b, n) == pytest.approx(3 / 4 * b)
    assert wire_bytes("ppermute", b, n) == pytest.approx(b)


def test_wire_bytes_degenerate_group_is_free():
    """A group of one moves nothing — the same rule that makes the
    eager single-controller collective wrappers semantically free."""
    for prim in ("psum", "all_gather", "reduce_scatter", "all_to_all",
                 "ppermute", "pmin", "pmax"):
        assert wire_bytes(prim, 4096.0, 1) == 0.0


# ---------------------------------------------------------------------------
# the collective walker
# ---------------------------------------------------------------------------

def _census_prog(width=4):
    """shard_map body with one psum, one direct all_gather, and one
    loop-INVARIANT all_gather inside a scan of length 3."""

    def body(w, x):
        h = lax.psum(x @ w, "x")                      # [8, 16]
        g = lax.all_gather(x, "x", axis=0, tiled=True)

        def sbody(c, _):
            gw = lax.all_gather(w, "x", axis=0, tiled=True)  # w: scan const
            return c + gw.sum(), None

        s, _ = lax.scan(sbody, jnp.float32(0), jnp.arange(3))
        return h.sum() + g.sum() + s

    fn = _sharded(body, width=width,
                  in_specs=(P("x", None), P(None, None)))
    return _trace(fn, _spec((4 * width, 16), np.float32),
                  _spec((8, 4), np.float32), names=["w", "x"])


def test_iter_collectives_census():
    cs = list(iter_collectives(_census_prog()))
    by_prim = {}
    for c in cs:
        by_prim.setdefault(c.prim, []).append(c)
    assert sorted(by_prim) == ["all_gather", "psum"]
    assert len(by_prim["psum"]) == 1 and len(by_prim["all_gather"]) == 2
    for c in cs:
        assert c.axes == ("x",)
        assert c.group_size == 4        # resolved from the shard_map mesh
        assert c.axis_sizes.get("x") == 4


def test_scan_multiplies_dispatches_and_marks_invariance():
    cs = list(iter_collectives(_census_prog()))
    in_scan = [c for c in cs if "/scan" in c.scope]
    assert len(in_scan) == 1
    c = in_scan[0]
    assert c.mult == 3                  # scan length multiplies dispatches
    assert c.loop_invariant             # gathers a scan const every step
    assert all(o.mult == 1 and not o.loop_invariant
               for o in cs if o is not c)


def test_scan_carry_dependent_collective_not_invariant():
    def body(x):
        def sbody(c, _):
            return lax.psum(c * 2.0, "x"), None   # depends on the carry

        s, _ = lax.scan(sbody, x.sum(), jnp.arange(5))
        return s

    fn = _sharded(body, in_specs=(P(None, None),))
    prog = _trace(fn, _spec((4, 4), np.float32))
    (c,) = iter_collectives(prog)
    assert c.mult == 5 and "/scan" in c.scope
    assert not c.loop_invariant


def test_wire_bytes_use_per_shard_payload():
    """Byte volumes come from the avals the collective actually sees
    INSIDE shard_map (per-shard), not the global operand shapes."""
    cs = {c.prim: c for c in iter_collectives(_census_prog())}
    # x is [8, 16] per shard in f32 -> 512 B payload
    assert cs["psum"].payload_bytes == 8 * 16 * 4
    assert cs["psum"].bytes_wire == pytest.approx(2 * 3 / 4 * 512)


# ---------------------------------------------------------------------------
# manifest + mesh-scaling law
# ---------------------------------------------------------------------------

def test_comm_manifest_census_and_roundtrip():
    prog = _census_prog()
    spec = CommPathSpec("census@4", mesh={"x": 4}, width=4)
    m = compute_comm_manifest(prog, name="census@4", spec=spec)
    assert m.collective_eqns == 3
    assert m.collectives == {"psum": 1, "all_gather": 2}
    assert m.dispatches == 1 + 1 + 3            # scan body counts 3x
    assert m.loop_invariant_eqns == 1
    assert m.per_axis["x"]["eqns"] == 3
    assert m.comm_bytes == pytest.approx(m.per_axis["x"]["bytes"])
    assert prog._comm_manifest is m             # attached for reuse
    m2 = CommManifest.from_dict(json.loads(json.dumps(m.to_dict())))
    assert m2.collectives == m.collectives
    assert m2.comm_bytes == pytest.approx(m.comm_bytes)
    assert m2.width == 4 and not m2.unsharded


def _man(width, comm_bytes, eqns=2):
    return CommManifest(program=f"fam@{width}", width=width,
                        comm_bytes=comm_bytes, collective_eqns=eqns)


def test_mesh_scaling_law_ring_envelope():
    """(n-1)-shaped growth is the legal envelope: 2 -> 4 devices may
    TRIPLE ring bytes (ratio 1.0); an O(n^2) family fails."""
    rec = mesh_scaling_verdict([_man(2, 1000.0), _man(4, 3000.0)])
    assert rec["verdict"] == "<=ring"
    assert rec["worst_ring_ratio"] == pytest.approx(1.0)
    rec = mesh_scaling_verdict([_man(2, 1000.0), _man(4, 4000.0)])
    assert rec["verdict"] == "superlinear"
    # comm appearing from nothing with width is superlinear by definition
    rec = mesh_scaling_verdict([_man(2, 0.0, eqns=0), _man(4, 64.0)])
    assert rec["verdict"] == "superlinear"
    assert rec["worst_ring_ratio"] == "inf"


def test_mesh_scaling_needs_width_pair():
    with pytest.raises(ValueError, match="widths"):
        mesh_scaling_verdict([_man(2, 10.0)])
    with pytest.raises(ValueError, match="widths"):
        mesh_scaling_verdict([_man(2, 10.0), CommManifest(program="p")])


def test_check_mesh_scaling_finding_is_stable():
    ms = [_man(2, 1000.0), _man(4, 8000.0)]
    (d,) = check_mesh_scaling(ms)
    assert d.code == "PT-COMM-003"
    assert d.finding_id == "PT-COMM-003:fam:superlinear"
    assert ms[0].scaling["verdict"] == "superlinear"
    assert check_mesh_scaling([_man(2, 1000.0), _man(4, 3000.0)]) == []


# ---------------------------------------------------------------------------
# program-local checks
# ---------------------------------------------------------------------------

def test_check_replication_flags_large_replicated_operand():
    def body(w, r):
        return (w.sum() + r.sum())[None]

    fn = _sharded(body, in_specs=(P("x", None), P(None, None)),
                  out_specs=P("x"))
    big = _trace(fn, _spec((8, 8), np.float32),
                 _spec((512, 512), np.float32), names=["w", "r"])
    (d,) = check_replication(big, "prog")
    assert d.code == "PT-COMM-001"
    assert d.finding_id == "PT-COMM-001:prog:replicated:in1:512x512"
    # small replicated operands are fine (scalars/biases ride along)
    small = _trace(fn, _spec((8, 8), np.float32),
                   _spec((8, 8), np.float32), names=["w", "r"])
    assert check_replication(small, "prog") == []


def test_check_replication_ignores_fully_replicated_programs():
    """No sharded sibling -> replication IS the contract; and the ids
    carry no trace positions, so retracing keeps them identical."""
    def body(r):
        return r.sum()[None]

    fn = _sharded(body, in_specs=(P(None, None),), out_specs=P("x"))
    prog = _trace(fn, _spec((512, 512), np.float32))
    assert check_replication(prog, "prog") == []


def test_check_loop_invariant_collective():
    (d,) = [x for x in check_loop_invariant_collectives(
        _census_prog(), "prog") if x.code == "PT-COMM-002"]
    assert d.finding_id == "PT-COMM-002:prog:all_gather/shard_map/scan"
    assert "hoist" in d.message or "every step" in d.message


def test_check_gather_reduce_fires_only_on_gathered_dim():
    def bad(x):
        g = lax.all_gather(x, "x", axis=0, tiled=True)
        return g.sum()                       # reduce eats the gathered dim

    def ok(x):
        g = lax.all_gather(x, "x", axis=0, tiled=True)
        return g.sum(axis=1).max()           # reduce over a local dim only

    pb = _trace(_sharded(bad, in_specs=(P("x", None),)),
                _spec((16, 8), np.float32))
    hits = [d for d in check_gather_reduce(pb, "p")
            if d.code == "PT-COMM-004"]
    assert hits and hits[0].finding_id.startswith(
        "PT-COMM-004:p:all_gather+reduce_sum")
    po = _trace(_sharded(ok, in_specs=(P("x", None),)),
                _spec((16, 8), np.float32))
    assert [d for d in check_gather_reduce(po, "p")
            if d.code == "PT-COMM-004"] == []


def test_check_comm_contract_drift_and_unbaselined():
    spec = CommPathSpec("census@4", mesh={"x": 4}, width=4)
    m = compute_comm_manifest(_census_prog(), name="census@4", spec=spec)
    base = m.to_dict()
    assert check_comm_contract(m, base) == []
    (d,) = check_comm_contract(m, None)
    assert d.code == "PT-COMM-005"
    assert d.finding_id == "PT-COMM-005:census@4:unbaselined"
    shrunk = dict(base, collectives={"psum": 1, "all_gather": 1},
                  comm_bytes=base["comm_bytes"] / 4)
    codes = {d.finding_id for d in check_comm_contract(m, shrunk)}
    assert "PT-COMM-005:census@4:all_gather-drift" in codes
    assert "PT-COMM-005:census@4:comm-bytes-blowup" in codes


def test_check_comm_contract_unsharded():
    spec = CommPathSpec("serve", unsharded=True)
    m = compute_comm_manifest(_census_prog(), name="serve", spec=spec)
    codes = {d.finding_id for d in check_comm_contract(m, m.to_dict())}
    assert "PT-COMM-005:serve:unsharded-contract" in codes


# ---------------------------------------------------------------------------
# pass composition
# ---------------------------------------------------------------------------

def test_comm_pass_composes_with_run_analysis():
    prog = _census_prog()
    p = CollectiveCommPass(spec=CommPathSpec("census@4", mesh={"x": 4},
                                             width=4))
    rep = run_analysis(prog, passes=[p])
    # the fixture's two gather+sum sites also (correctly) trip PT-COMM-004
    assert sorted(d.code for d in rep) == ["PT-COMM-002", "PT-COMM-004",
                                           "PT-COMM-004"]
    assert p.manifest is not None and p.manifest.collective_eqns == 3
    assert prog._comm_manifest is p.manifest
    rep2 = run_analysis(prog, passes=[CollectiveCommPass(
        spec=CommPathSpec("census@4"),
        suppress=("PT-COMM-002", "PT-COMM-004"))])
    assert len(rep2) == 0


# ---------------------------------------------------------------------------
# mesh helpers
# ---------------------------------------------------------------------------

def test_abstract_mesh_and_spec_helpers():
    mesh = abstract_mesh({"dp": 2, "tp": 4})
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError):
        abstract_mesh({})
    axes = {"dp": 2, "tp": 4}
    assert mesh_spec(axes, "dp", "tp") == P("dp", "tp")
    # absent axes are masked to None so one spec serves every mesh shape
    assert mesh_spec(axes, "fsdp", "tp") == P(None, "tp")
    assert mesh_spec(axes, ("dp", "fsdp"), None) == P("dp", None)
    assert mesh_spec(axes) == P()


# ---------------------------------------------------------------------------
# contract-program hookpoints (distributed.auto_parallel.comm_programs)
# ---------------------------------------------------------------------------

def test_train_step_comm_dp_only_census():
    from paddle_tpu.distributed.auto_parallel import train_step_comm

    fn, structs, names, axes = train_step_comm({"dp": 2, "pp": 1})
    assert axes == {"dp": 2}            # size-1 axes are dropped
    m = compute_comm_manifest(_trace(fn, *structs, names=names),
                              name="dp", spec=CommPathSpec("dp", mesh=axes))
    assert set(m.collectives) == {"psum"}       # grads + loss only
    assert m.collectives["psum"] == 3
    assert m.per_axis["dp"]["eqns"] == 3


def test_moe_combine_comm_census():
    from paddle_tpu.distributed.auto_parallel import moe_combine_comm

    fn, structs, names, axes = moe_combine_comm(4)
    m = compute_comm_manifest(_trace(fn, *structs, names=names),
                              name="moe", spec=CommPathSpec("moe", mesh=axes))
    assert m.collectives == {"all_to_all": 2}   # dispatch + combine
    assert m.per_axis["ep"]["eqns"] == 2


# ---------------------------------------------------------------------------
# jax_compat shard_map resolution (satellite: both orders by injection)
# ---------------------------------------------------------------------------

def test_resolve_shard_map_prefers_promoted_api():
    sentinel = object()
    fake_jax = types.SimpleNamespace(shard_map=sentinel)
    fn, origin = jax_compat._resolve_shard_map(jax_module=fake_jax)
    assert fn is sentinel and origin == "jax"   # used as-is, unwrapped


def test_resolve_shard_map_falls_back_to_experimental_wrapped():
    calls = {}

    def legacy(f, mesh=None, in_specs=None, out_specs=None, **kw):
        calls.update(kw, mesh=mesh)
        return f

    fake_jax = types.SimpleNamespace()          # no shard_map attribute

    def fake_import(path):
        assert path == "jax.experimental.shard_map"
        return types.SimpleNamespace(shard_map=legacy)

    fn, origin = jax_compat._resolve_shard_map(jax_module=fake_jax,
                                               import_module=fake_import)
    assert origin == "experimental"
    mesh = abstract_mesh({"x": 2, "y": 2})
    fn(lambda v: v, mesh=mesh, in_specs=P("x"), out_specs=P("x"),
       check_vma=False)
    # the wrapper translated the promoted kwarg names to the legacy ones
    assert calls["check_rep"] is False and "check_vma" not in calls
    assert calls["mesh"] is mesh


def test_resolve_shard_map_neither_location_names_both():
    def no_import(path):
        raise ImportError(path)

    with pytest.raises(ImportError, match="jax.shard_map"):
        jax_compat._resolve_shard_map(jax_module=types.SimpleNamespace(),
                                      import_module=no_import)


def test_wrap_legacy_translates_axis_names_to_auto():
    seen = {}

    def legacy(f, mesh=None, in_specs=None, out_specs=None, **kw):
        seen.update(kw)
        return f

    wrapped = jax_compat._wrap_legacy_shard_map(legacy)
    mesh = abstract_mesh({"x": 2, "y": 2})
    wrapped(lambda v: v, mesh=mesh, in_specs=P("x"), out_specs=P("x"),
            axis_names={"x"})
    # manual over {x} == automatic over the complement {y}
    assert seen["auto"] == frozenset({"y"})


def test_module_shard_map_resolved_and_usable():
    """Whatever origin this jax picked, the module-level symbol traces."""
    assert jax_compat._SHARD_MAP_ORIGIN in ("jax", "experimental")
    prog = _census_prog(width=2)
    assert compute_comm_manifest(prog).collective_eqns == 3


# ---------------------------------------------------------------------------
# eager collective wrappers under a world of 1 (satellite: the byte rules
# agree with the degenerate-group semantics)
# ---------------------------------------------------------------------------

class TestFunctionalWorldOfOne:
    """distributed.communication.functional over a group of ONE rank
    (the test harness forces 8 host devices, so the world group is not
    usable for this): every wrapper must degenerate to the
    zero-communication identity the ring rule predicts
    (wire_bytes(prim, b, 1) == 0) — the eager single-controller regime
    the module docstring promises."""

    def _g1(self):
        from paddle_tpu.distributed.communication.group import Group

        # unbound axis name -> the eager branch; one rank -> n == 1
        return Group([0], 97, axis_name="pt_comm_test_unbound")

    def test_all_reduce_identity(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.communication.functional import \
            all_reduce

        t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        nbytes = t.numpy().nbytes
        all_reduce(t, group=self._g1())   # SUM over a group of one
        np.testing.assert_allclose(t.numpy(),
                                   np.arange(6, dtype=np.float32)
                                   .reshape(2, 3))
        assert wire_bytes("psum", nbytes, 1) == 0.0

    def test_all_gather_single_copy(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.communication.functional import (
            all_gather, all_gather_into_tensor)

        x = np.ones((3, 2), np.float32)
        parts = all_gather(None, paddle.to_tensor(x), group=self._g1())
        assert len(parts) == 1
        np.testing.assert_allclose(parts[0].numpy(), x)
        out = all_gather_into_tensor(None, paddle.to_tensor(x),
                                     group=self._g1())
        np.testing.assert_allclose(out.numpy(), x)   # concat of one shard
        assert wire_bytes("all_gather", x.nbytes, 1) == 0.0

    def test_reduce_scatter_keeps_own_shard(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.communication.functional import \
            reduce_scatter

        x = np.arange(8, dtype=np.float32).reshape(4, 2)
        out = paddle.zeros([4, 2])
        reduce_scatter(out, paddle.to_tensor(x), group=self._g1())
        np.testing.assert_allclose(out.numpy(), x)   # n=1: shard == input
        assert wire_bytes("reduce_scatter", x.nbytes, 1) == 0.0

    def test_alltoall_identity(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.communication.functional import (
            alltoall, alltoall_single)

        x = np.arange(4, dtype=np.float32).reshape(1, 4)
        parts = alltoall(None, [paddle.to_tensor(x[0])], group=self._g1())
        assert len(parts) == 1
        np.testing.assert_allclose(parts[0].numpy(), x[0])
        out = alltoall_single(None, paddle.to_tensor(x), group=self._g1())
        np.testing.assert_allclose(out.numpy(), x)
        assert wire_bytes("all_to_all", x.nbytes, 1) == 0.0

    def test_broadcast_identity(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.communication.functional import \
            broadcast

        x = np.full((2, 2), 7.0, np.float32)
        t = paddle.to_tensor(x)
        broadcast(t, src=0, group=self._g1())
        np.testing.assert_allclose(t.numpy(), x)
        assert wire_bytes("ppermute", x.nbytes, 1) == 0.0


# ---------------------------------------------------------------------------
# gate plumbing (in-process — the subprocess pins live in test_ci_gates)
# ---------------------------------------------------------------------------

def test_comm_baseline_waiver_requires_justification(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import audit_collectives as gate
    finally:
        sys.path.pop(0)
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"programs": {},
                             "waivers": [{"id": "PT-COMM-001:x:rep"}]}))
    with pytest.raises(SystemExit, match="justification"):
        gate.load_baseline(str(p))


def test_committed_comm_baseline_loads_and_covers_registry():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import audit_collectives as gate
    finally:
        sys.path.pop(0)
    programs, waivers = gate.load_baseline()
    # every recorded MULTICHIP shape has its per-mesh manifest committed
    for key in gate.MULTICHIP_MESHES:
        name = f"mesh_train_step@{key}"
        assert name in programs, name
        assert programs[name]["collective_eqns"] > 0, name
    # serving programs carry the per-mesh tp contract (column-parallel:
    # all_gather-only — a psum appearing here would break byte-identity)
    for name in ("mega_step@8", "spec_verify@8", "prefill_chunk"):
        assert programs[name]["unsharded"] is False
        assert programs[name]["mesh"] == {"tp": 2}
        assert programs[name]["collective_eqns"] > 0
        assert set(programs[name]["collectives"]) == {"all_gather"}
    for fam in ("flash_ring", "moe_combine", "tp_train"):
        for w in gate.SCALING_WIDTHS:
            assert programs[f"{fam}@{w}"]["scaling"]["verdict"] == "<=ring"
