"""Per-op SPMD propagation tests (reference: test/auto_parallel/spmd_rules/ —
per-op rule unit tests over infermeta/spmd_rules/*.cc).

TPU-native: the "rule engine" is GSPMD. Each test jits one op with explicitly
sharded inputs and asserts the output sharding GSPMD propagates — the same
contract the reference tests per rule (matmul, embedding, layer_norm,
reduction, elementwise).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.auto_parallel import make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"x": 2, "y": 4})


def _sharded(mesh, arr, spec):
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _out_spec(mesh, fn, *args):
    out = jax.jit(fn)(*args)
    return out.sharding.spec if hasattr(out.sharding, "spec") else None


def test_matmul_row_parallel(mesh):
    """[b@x, k] @ [k, n] -> [b@x, n] (batch-dim sharding propagates)."""
    a = _sharded(mesh, jnp.ones((8, 16)), P("x", None))
    w = _sharded(mesh, jnp.ones((16, 32)), P(None, None))
    spec = _out_spec(mesh, jnp.matmul, a, w)
    assert tuple(spec) [0] == "x"


def test_matmul_column_parallel(mesh):
    """[b, k] @ [k, n@y] -> [b, n@y] (Megatron column-parallel rule)."""
    a = _sharded(mesh, jnp.ones((8, 16)), P(None, None))
    w = _sharded(mesh, jnp.ones((16, 32)), P(None, "y"))
    spec = _out_spec(mesh, jnp.matmul, a, w)
    assert tuple(spec)[-1] == "y"


def test_matmul_contraction_produces_partial_then_reduced(mesh):
    """[b, k@y] @ [k@y, n]: contraction over a sharded dim — GSPMD inserts
    the all-reduce; the result is fully computed (values correct)."""
    rng = np.random.default_rng(0)
    av = rng.standard_normal((4, 8)).astype(np.float32)
    wv = rng.standard_normal((8, 6)).astype(np.float32)
    a = _sharded(mesh, jnp.asarray(av), P(None, "y"))
    w = _sharded(mesh, jnp.asarray(wv), P("y", None))
    out = jax.jit(jnp.matmul)(a, w)
    np.testing.assert_allclose(np.asarray(out), av @ wv, rtol=1e-5)


def test_embedding_rule(mesh):
    """table[v, h@y] gathered by ids[b@x] -> [b@x, s, h@y]."""
    table = _sharded(mesh, jnp.ones((64, 16)), P(None, "y"))
    ids = _sharded(mesh, jnp.zeros((8, 4), jnp.int32), P("x", None))
    spec = _out_spec(mesh, lambda t, i: jnp.take(t, i, axis=0), table, ids)
    assert tuple(spec)[0] == "x" and tuple(spec)[-1] == "y"


def test_elementwise_preserves_sharding(mesh):
    a = _sharded(mesh, jnp.ones((8, 16)), P("x", "y"))
    spec = _out_spec(mesh, lambda t: jnp.tanh(t) * 2 + 1, a)
    assert tuple(spec)[:2] == ("x", "y")


def test_reduction_drops_reduced_axis(mesh):
    a = _sharded(mesh, jnp.ones((8, 16)), P("x", "y"))
    out = jax.jit(lambda t: t.sum(axis=1))(a)
    spec = tuple(out.sharding.spec)
    assert spec and spec[0] == "x"  # batch sharding survives; y reduced away
    np.testing.assert_allclose(np.asarray(out), 16.0)


def test_layer_norm_rule(mesh):
    """LN over the feature dim keeps batch sharding, feature stats correct."""
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((8, 16)).astype(np.float32)
    x = _sharded(mesh, jnp.asarray(xv), P("x", None))

    def ln(t):
        mu = t.mean(-1, keepdims=True)
        var = t.var(-1, keepdims=True)
        return (t - mu) * jax.lax.rsqrt(var + 1e-5)

    out = jax.jit(ln)(x)
    assert tuple(out.sharding.spec)[0] == "x"
    ref = (xv - xv.mean(-1, keepdims=True)) / np.sqrt(
        xv.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_reshard_constraint(mesh):
    """with_sharding_constraint mid-graph == the reference's reshard op."""
    a = _sharded(mesh, jnp.ones((8, 16)), P("x", None))

    def f(t):
        t = t * 2
        return jax.lax.with_sharding_constraint(
            t, NamedSharding(mesh, P(None, "y")))

    out = jax.jit(f)(a)
    assert tuple(out.sharding.spec)[:2] == (None, "y")


def test_flash_attention_batch_sharded(mesh):
    """Attention with batch/head sharded q/k/v keeps the sharding on out."""
    from paddle_tpu.nn.functional.flash_attention import _xla_attention

    rng = np.random.default_rng(2)
    q = _sharded(mesh, jnp.asarray(
        rng.standard_normal((8, 16, 4, 8)), jnp.float32), P("x", None, "y", None))
    out = jax.jit(lambda q: _xla_attention(q, q, q, causal=True))(q)
    spec = tuple(out.sharding.spec)
    assert spec[0] == "x"
