"""Per-op SPMD propagation tests (reference: test/auto_parallel/spmd_rules/ —
per-op rule unit tests over infermeta/spmd_rules/*.cc).

TPU-native: the "rule engine" is GSPMD. Each test jits one op with explicitly
sharded inputs and asserts the output sharding GSPMD propagates — the same
contract the reference tests per rule (matmul, embedding, layer_norm,
reduction, elementwise).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.auto_parallel import make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"x": 2, "y": 4})


def _sharded(mesh, arr, spec):
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _out_spec(mesh, fn, *args):
    out = jax.jit(fn)(*args)
    return out.sharding.spec if hasattr(out.sharding, "spec") else None


def test_matmul_row_parallel(mesh):
    """[b@x, k] @ [k, n] -> [b@x, n] (batch-dim sharding propagates)."""
    a = _sharded(mesh, jnp.ones((8, 16)), P("x", None))
    w = _sharded(mesh, jnp.ones((16, 32)), P(None, None))
    spec = _out_spec(mesh, jnp.matmul, a, w)
    assert tuple(spec) [0] == "x"


def test_matmul_column_parallel(mesh):
    """[b, k] @ [k, n@y] -> [b, n@y] (Megatron column-parallel rule)."""
    a = _sharded(mesh, jnp.ones((8, 16)), P(None, None))
    w = _sharded(mesh, jnp.ones((16, 32)), P(None, "y"))
    spec = _out_spec(mesh, jnp.matmul, a, w)
    assert tuple(spec)[-1] == "y"


def test_matmul_contraction_produces_partial_then_reduced(mesh):
    """[b, k@y] @ [k@y, n]: contraction over a sharded dim — GSPMD inserts
    the all-reduce; the result is fully computed (values correct)."""
    rng = np.random.default_rng(0)
    av = rng.standard_normal((4, 8)).astype(np.float32)
    wv = rng.standard_normal((8, 6)).astype(np.float32)
    a = _sharded(mesh, jnp.asarray(av), P(None, "y"))
    w = _sharded(mesh, jnp.asarray(wv), P("y", None))
    out = jax.jit(jnp.matmul)(a, w)
    np.testing.assert_allclose(np.asarray(out), av @ wv, rtol=1e-5)


def test_embedding_rule(mesh):
    """table[v, h@y] gathered by ids[b@x] -> [b@x, s, h@y]."""
    table = _sharded(mesh, jnp.ones((64, 16)), P(None, "y"))
    ids = _sharded(mesh, jnp.zeros((8, 4), jnp.int32), P("x", None))
    spec = _out_spec(mesh, lambda t, i: jnp.take(t, i, axis=0), table, ids)
    assert tuple(spec)[0] == "x" and tuple(spec)[-1] == "y"


def test_elementwise_preserves_sharding(mesh):
    a = _sharded(mesh, jnp.ones((8, 16)), P("x", "y"))
    spec = _out_spec(mesh, lambda t: jnp.tanh(t) * 2 + 1, a)
    assert tuple(spec)[:2] == ("x", "y")


def test_reduction_drops_reduced_axis(mesh):
    a = _sharded(mesh, jnp.ones((8, 16)), P("x", "y"))
    out = jax.jit(lambda t: t.sum(axis=1))(a)
    spec = tuple(out.sharding.spec)
    assert spec and spec[0] == "x"  # batch sharding survives; y reduced away
    np.testing.assert_allclose(np.asarray(out), 16.0)


def test_layer_norm_rule(mesh):
    """LN over the feature dim keeps batch sharding, feature stats correct."""
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((8, 16)).astype(np.float32)
    x = _sharded(mesh, jnp.asarray(xv), P("x", None))

    def ln(t):
        mu = t.mean(-1, keepdims=True)
        var = t.var(-1, keepdims=True)
        return (t - mu) * jax.lax.rsqrt(var + 1e-5)

    out = jax.jit(ln)(x)
    assert tuple(out.sharding.spec)[0] == "x"
    ref = (xv - xv.mean(-1, keepdims=True)) / np.sqrt(
        xv.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_reshard_constraint(mesh):
    """with_sharding_constraint mid-graph == the reference's reshard op."""
    a = _sharded(mesh, jnp.ones((8, 16)), P("x", None))

    def f(t):
        t = t * 2
        return jax.lax.with_sharding_constraint(
            t, NamedSharding(mesh, P(None, "y")))

    out = jax.jit(f)(a)
    assert tuple(out.sharding.spec)[:2] == (None, "y")


def test_flash_attention_batch_sharded(mesh):
    """Attention with batch/head sharded q/k/v keeps the sharding on out."""
    from paddle_tpu.nn.functional.flash_attention import _xla_attention

    rng = np.random.default_rng(2)
    q = _sharded(mesh, jnp.asarray(
        rng.standard_normal((8, 16, 4, 8)), jnp.float32), P("x", None, "y", None))
    out = jax.jit(lambda q: _xla_attention(q, q, q, causal=True))(q)
    spec = tuple(out.sharding.spec)
    assert spec[0] == "x"


# ---------------------------------------------------------------------------
# Framework-routed SPMD tests (VERDICT r1 #8): the ops go through paddle_tpu
# dispatch + logical_sharding.constrain / logical_to_spec, and the compiled
# HLO is grepped for the collectives GSPMD must insert — a regression in the
# dispatch or constraint layer breaks these, not just raw-GSPMD behavior.
# Reference: test/auto_parallel/spmd_rules/ per-op rule tests.
# ---------------------------------------------------------------------------

def _hlo_count(fn, *args, word="all-reduce"):
    txt = jax.jit(fn).lower(*args).compile().as_text()
    return txt.count(f" {word}(") + txt.count(f" {word}-start(")


@pytest.fixture(scope="module")
def lmesh():
    from paddle_tpu.distributed.auto_parallel import make_mesh

    return make_mesh({"dp": 2, "fsdp": 1, "sep": 1, "tp": 4})


def test_framework_matmul_logical_spec(lmesh):
    """paddle matmul through the dispatcher + constrain produces the spec
    logical_to_spec maps ('batch','mlp') to."""
    from paddle_tpu.distributed.auto_parallel.logical_sharding import (
        axis_rules, constrain, logical_to_spec)
    import paddle_tpu.tensor as pt

    def f(a, w):
        with axis_rules(lmesh):
            out = pt.matmul(a, w)
            out = out._data if hasattr(out, "_data") else out
            return constrain(out, "batch", "mlp")

    a = _sharded(lmesh, jnp.ones((8, 16)), P("dp", None))
    w = _sharded(lmesh, jnp.ones((16, 32)), P(None, "tp"))
    out = jax.jit(f)(a, w)
    want = NamedSharding(lmesh, logical_to_spec(("batch", "mlp"), lmesh))
    assert out.sharding.is_equivalent_to(want, out.ndim)


def test_framework_embedding_logical_spec(lmesh):
    from paddle_tpu.distributed.auto_parallel.logical_sharding import (
        axis_rules, constrain)
    import paddle_tpu.nn.functional as F

    def f(table, ids):
        with axis_rules(lmesh):
            out = F.embedding(ids, table)
            out = out._data if hasattr(out, "_data") else out
            return constrain(out, "batch", "seq", "embed")

    table = _sharded(lmesh, jnp.ones((64, 16)), P(None, None))
    ids = _sharded(lmesh, jnp.zeros((8, 4), jnp.int32), P("dp", None))
    out = jax.jit(f)(table, ids)
    assert tuple(out.sharding.spec)[0] == "dp"


def test_framework_layer_norm_keeps_batch(lmesh):
    import paddle_tpu.nn.functional as F

    def f(x, w, b):
        out = F.layer_norm(x, [16], w, b, 1e-5)
        return out._data if hasattr(out, "_data") else out

    x = _sharded(lmesh, jnp.ones((8, 16)), P("dp", None))
    w = _sharded(lmesh, jnp.ones((16,)), P(None))
    b = _sharded(lmesh, jnp.zeros((16,)), P(None))
    out = jax.jit(f)(x, w, b)
    assert tuple(out.sharding.spec)[0] == "dp"


def test_framework_reduction_spec(lmesh):
    import paddle_tpu.tensor as pt

    def f(x):
        out = pt.sum(x, axis=1)
        return out._data if hasattr(out, "_data") else out

    x = _sharded(lmesh, jnp.ones((8, 16)), P("dp", "tp"))
    out = jax.jit(f)(x)
    assert tuple(out.sharding.spec)[0] == "dp"


def test_column_parallel_linear_fwd_no_allreduce():
    """Column-parallel keeps the output mp-sharded: forward must compile to
    ZERO all-reduces (Megatron rule; mp_layers.py ColumnParallelLinear)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
        ColumnParallelLinear)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    lin = ColumnParallelLinear(16, 32, gather_output=False)
    if lin.mesh is None:
        pytest.skip("no mp mesh in this environment")

    def f(x, w, b):
        lin.weight._data, lin.bias._data = w, b
        out = lin(paddle.to_tensor(x) if not hasattr(x, "aval") else x)
        return out._data if hasattr(out, "_data") else out

    x = jnp.ones((4, 16))
    n_ar = _hlo_count(f, x, lin.weight._data, lin.bias._data)
    assert n_ar == 0, f"column-parallel fwd emitted {n_ar} all-reduces"


def test_row_parallel_linear_fwd_has_allreduce():
    """Row-parallel contracts over the sharded dim: the dispatcher's constrain
    must make GSPMD insert at least one all-reduce in forward."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
        RowParallelLinear)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    lin = RowParallelLinear(32, 16)
    if lin.mesh is None:
        pytest.skip("no mp mesh in this environment")

    def f(x, w, b):
        lin.weight._data, lin.bias._data = w, b
        out = lin(x)
        return out._data if hasattr(out, "_data") else out

    x = jnp.ones((4, 32))
    n_ar = _hlo_count(f, x, lin.weight._data, lin.bias._data)
    assert n_ar >= 1, "row-parallel fwd must all-reduce the partial sums"


def test_flash_attention_framework_sharded(lmesh):
    """F.scaled_dot_product_attention via the dispatcher keeps batch/heads
    sharding on the output."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.auto_parallel.logical_sharding import axis_rules

    rng = np.random.default_rng(2)
    q = _sharded(lmesh, jnp.asarray(
        rng.standard_normal((8, 16, 4, 8)), jnp.float32),
        P("dp", None, "tp", None))

    def f(q):
        with axis_rules(lmesh):
            out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
            return out._data if hasattr(out, "_data") else out

    out = jax.jit(f)(q)
    assert tuple(out.sharding.spec)[0] == "dp"


def test_moe_expert_axis_constrain():
    """'expert' logical axis maps to the ep mesh axis through constrain (the
    dispatch layout GShard MoE relies on)."""
    from paddle_tpu.distributed.auto_parallel import make_mesh
    from paddle_tpu.distributed.auto_parallel.logical_sharding import (
        axis_rules, constrain, logical_to_spec)

    mesh = make_mesh({"ep": 2, "fsdp": 4})

    def f(x):
        with axis_rules(mesh):
            return constrain(x * 2.0, "expert", None, "embed")

    x = _sharded(mesh, jnp.ones((4, 8, 16)), P(None, None, None))
    out = jax.jit(f)(x)
    want = NamedSharding(mesh, logical_to_spec(("expert", None, "embed"), mesh))
    assert out.sharding.is_equivalent_to(want, out.ndim)
    assert tuple(out.sharding.spec)[0] == "ep"
