"""Sequence-parallel utils + callbacks tests (reference:
fleet/utils/sequence_parallel_utils.py test patterns +
hybrid_parallel_mp_model_with_sequence_parallel.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils as spu


@pytest.fixture(scope="module", autouse=True)
def _fleet():
    fleet.init(is_collective=True, strategy=None)


def test_scatter_gather_roundtrip():
    x = paddle.to_tensor(np.random.rand(2, 8, 16).astype(np.float32))
    s = spu.ScatterOp.apply(x)
    g = spu.GatherOp.apply(s)
    np.testing.assert_allclose(g.numpy(), x.numpy())


def test_column_row_sp_linear_matches_dense():
    paddle.seed(0)
    col = spu.ColumnSequenceParallelLinear(16, 32)
    row = spu.RowSequenceParallelLinear(32, 16)
    x = paddle.to_tensor(np.random.rand(2, 8, 16).astype(np.float32))
    out = spu.GatherOp.apply(row(col(x)))
    ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
        @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_mark_and_register_are_port_compatible():
    lin = paddle.nn.Linear(4, 4)
    spu.mark_as_sequence_parallel_parameter(lin.weight)
    assert lin.weight.sequence_parallel
    assert spu.register_sequence_parallel_allreduce_hooks(lin) is lin


def test_reduce_lr_on_plateau():
    import paddle_tpu.hapi.callbacks as cb

    lin = paddle.nn.Linear(2, 1)
    opt = paddle.optimizer.SGD(learning_rate=1.0,
                               parameters=list(lin.parameters()))

    class _M:
        _optimizer = opt

    c = cb.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2, verbose=0)
    c.model = _M()
    for loss in (1.0, 1.0, 1.0, 1.0):
        c.on_epoch_end(0, {"loss": loss})
    assert opt.get_lr() == 0.5  # plateaued -> halved


@pytest.mark.slow   # full llama SP-vs-dense compile pair (~18s, tier-1 870s
#                     budget); the sp unit tests in this file keep the
#                     scatter/gather and linear-vs-dense contracts fast
def test_llama_megatron_sp_matches_dense(mesh8):
    """cfg.sequence_parallel shards the residual stream over tp (Megatron-SP);
    training must match the non-SP model exactly (same seed/data)."""
    import numpy as np

    from paddle_tpu.distributed.auto_parallel import Engine, axis_rules, make_mesh
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    losses = {}
    for sp in (False, True):
        paddle.seed(42)
        mesh = make_mesh({"dp": 1, "fsdp": 2, "sep": 1, "tp": 4})
        with axis_rules(mesh):
            cfg = LlamaConfig.tiny(sequence_parallel=sp)
            model = LlamaForCausalLM(cfg)
        eng = Engine(model, mesh, lr=1e-3)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (4, 64)).astype(np.int32)
        a, b = eng.shard_batch(ids, ids)
        losses[sp] = [float(eng.step(a, b)) for _ in range(3)]
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-4)


def test_llama_sp_residual_sharded_over_tp(mesh8):
    """Trace the decoder layer: with sequence_parallel the block OUTPUT comes
    back sequence-sharded over tp (the Megatron-SP residual-stream layout);
    without the flag it does not."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.distributed.auto_parallel import axis_rules, make_mesh
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.llama.modeling import _rope_cos_sin

    mesh = make_mesh({"dp": 1, "fsdp": 2, "sep": 1, "tp": 4})
    specs = {}
    for sp in (False, True):
        paddle.seed(0)
        with axis_rules(mesh):
            cfg = LlamaConfig.tiny(sequence_parallel=sp)
            model = LlamaForCausalLM(cfg)
        layer = model.model.layers[0]
        cos, sin = _rope_cos_sin(64, cfg.head_dim, cfg.rope_theta, np.float32)

        def f(x):
            with axis_rules(mesh):
                return layer(x, cos, sin)

        x = jax.device_put(np.zeros((4, 64, cfg.hidden_size), np.float32),
                           NamedSharding(mesh, P(None, None, None)))
        out = jax.jit(f)(x)
        seq_part = tuple(out.sharding.spec)[1] if len(tuple(out.sharding.spec)) > 1 else None
        parts = (seq_part if isinstance(seq_part, tuple)
                 else (seq_part,) if seq_part else ())
        specs[sp] = parts
    assert "tp" in specs[True], specs
    assert "tp" not in specs[False], specs
