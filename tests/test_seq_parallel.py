"""Sequence-parallel utils + callbacks tests (reference:
fleet/utils/sequence_parallel_utils.py test patterns +
hybrid_parallel_mp_model_with_sequence_parallel.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils as spu


@pytest.fixture(scope="module", autouse=True)
def _fleet():
    fleet.init(is_collective=True, strategy=None)


def test_scatter_gather_roundtrip():
    x = paddle.to_tensor(np.random.rand(2, 8, 16).astype(np.float32))
    s = spu.ScatterOp.apply(x)
    g = spu.GatherOp.apply(s)
    np.testing.assert_allclose(g.numpy(), x.numpy())


def test_column_row_sp_linear_matches_dense():
    paddle.seed(0)
    col = spu.ColumnSequenceParallelLinear(16, 32)
    row = spu.RowSequenceParallelLinear(32, 16)
    x = paddle.to_tensor(np.random.rand(2, 8, 16).astype(np.float32))
    out = spu.GatherOp.apply(row(col(x)))
    ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
        @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_mark_and_register_are_port_compatible():
    lin = paddle.nn.Linear(4, 4)
    spu.mark_as_sequence_parallel_parameter(lin.weight)
    assert lin.weight.sequence_parallel
    assert spu.register_sequence_parallel_allreduce_hooks(lin) is lin


def test_reduce_lr_on_plateau():
    import paddle_tpu.hapi.callbacks as cb

    lin = paddle.nn.Linear(2, 1)
    opt = paddle.optimizer.SGD(learning_rate=1.0,
                               parameters=list(lin.parameters()))

    class _M:
        _optimizer = opt

    c = cb.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2, verbose=0)
    c.model = _M()
    for loss in (1.0, 1.0, 1.0, 1.0):
        c.on_epoch_end(0, {"loss": loss})
    assert opt.get_lr() == 0.5  # plateaued -> halved
