"""Checkpoint-lifecycle tests: the generation-fenced LATEST pointer
(checkpoint/latest.py, PT-CKPT-005), the async-save commit fence on
ResilientTrainer (a kill mid-flush can never publish a torn resume point),
dual-failure replica naming, ComposedFaultPlan determinism, the exact-step
bit-equal reshard-resume pin, and CheckpointPublisher's verify → load →
swap handoff with its lifecycle stats/spans.

The full chaos-tested arc (train → async checkpoint → elastic shrink →
resume → publish → serve under a composed three-site plan) runs in
tools/fault_drill.py --drill lifecycle_e2e, gated by tests/test_ci_gates.py;
these are the fast deterministic pins behind it (docs/RESILIENCE.md
"Checkpoint lifecycle").
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.checkpoint import (
    CheckpointCorruptionError,
    StaleGenerationError,
    claim_generation,
    commit_latest,
    load_state_dict,
    read_latest,
    save_state_dict,
)
from paddle_tpu.distributed.checkpoint.latest import latest_generation
from paddle_tpu.distributed.resilience import (
    ComposedFaultPlan,
    FaultPlan,
    FaultSpec,
    ResilientTrainer,
    corrupt,
    maybe_inject,
)
from paddle_tpu.distributed.resilience.lifecycle import (
    LIFECYCLE_PHASES,
    CheckpointPublisher,
    lifecycle_stats,
    reset_lifecycle_stats,
    set_lifecycle_phase,
)


# ---------------------------------------------------------------------------
# generation-fenced LATEST pointer
# ---------------------------------------------------------------------------

class TestGenerationFence:
    def test_commit_and_read_roundtrip(self, tmp_path):
        d = str(tmp_path)
        assert read_latest(d) is None
        assert latest_generation(d) == 0
        commit_latest(d, 5, 1)
        assert read_latest(d) == (5, 1)
        assert latest_generation(d) == 1

    def test_stale_writer_fenced_pt_ckpt_005(self, tmp_path):
        d = str(tmp_path)
        commit_latest(d, 10, 3)
        with pytest.raises(StaleGenerationError) as ei:
            commit_latest(d, 12, 2)       # newer step, OLDER generation
        assert ei.value.code == "PT-CKPT-005"
        assert ei.value.committed == 3 and ei.value.attempted == 2
        assert ei.value.path == d
        # the fence held: the pointer never moved
        assert read_latest(d) == (10, 3)

    def test_same_generation_moves_its_own_pointer(self, tmp_path):
        d = str(tmp_path)
        commit_latest(d, 2, 2)
        commit_latest(d, 4, 2)            # same writer, later save
        assert read_latest(d) == (4, 2)

    def test_legacy_bare_int_reads_as_generation_zero(self, tmp_path):
        (tmp_path / "LATEST").write_text("7")
        d = str(tmp_path)
        assert read_latest(d) == (7, 0)
        # any fenced writer supersedes a legacy pointer
        assert claim_generation(d) == 1
        commit_latest(d, 9, 1)
        assert read_latest(d) == (9, 1)

    def test_claim_generation_is_strictly_increasing(self, tmp_path):
        d = str(tmp_path)
        g1 = claim_generation(d)
        commit_latest(d, 1, g1)
        g2 = claim_generation(d)
        assert g2 == g1 + 1
        commit_latest(d, 3, g2)
        with pytest.raises(StaleGenerationError):
            commit_latest(d, 5, g1)       # the old claimant is now fenced


# ---------------------------------------------------------------------------
# toy engine fixtures (mirrors tests/test_resilience.py conventions)
# ---------------------------------------------------------------------------

def _toy_build(alive, d=8):
    from jax.sharding import Mesh
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.nn.layer.layers import Layer

    class Toy(Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(d, d)

        def loss_fn(self, x, y):
            out = self.fc(Tensor(x))
            diff = out._data - y
            return (diff * diff).mean()

    n = 8 if len(alive) >= 2 else 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    paddle.seed(0)
    return Engine(Toy(), mesh, lr=0.05, clip_norm=None)


def _data_fn(step, b=8, d=8):
    rng = np.random.default_rng(1000 + step)
    return (rng.standard_normal((b, d)).astype(np.float32),
            rng.standard_normal((b, d)).astype(np.float32))


def _leaves(tree, prefix=""):
    """Flatten a state dict to {path: np.ndarray} for bit-equality pins."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_leaves(v, f"{prefix}/{k}"))
        return out
    arr = tree._data if hasattr(tree, "_data") else tree
    out[prefix] = np.asarray(arr)
    return out


# ---------------------------------------------------------------------------
# trainer commit fence — kill mid-flush can never publish a torn LATEST
# ---------------------------------------------------------------------------

class TestTrainerCommitFence:
    def test_kill_mid_flush_leaves_previous_latest_loadable(self, tmp_path):
        d = str(tmp_path)
        t = ResilientTrainer(_toy_build, d, save_every=2, async_save=True)
        eng = _toy_build(["a", "b"])
        for s in range(2):
            eng.step(*eng.shard_batch(*_data_fn(s)))
        t.save(eng, 2, sync=True)         # durable baseline: LATEST = step 2
        assert read_latest(d) == (2, t.generation)
        with FaultPlan(specs=[FaultSpec("checkpoint.shard", "error")]):
            t.save(eng, 4)                # async flush dies on the writer
            with pytest.raises(RuntimeError, match="fault injected"):
                t.commit()
        # the torn run is invisible: pointer still names the durable step,
        # and a LATER commit must not resurrect the abandoned move
        assert read_latest(d) == (2, t.generation)
        t.commit()
        assert read_latest(d) == (2, t.generation)
        # a fresh trainer resumes from the durable checkpoint
        t2 = ResilientTrainer(_toy_build, d, save_every=2)
        eng2 = _toy_build(["solo"])
        assert t2.resume(eng2) == 2

    def test_zombie_trainer_commit_is_fenced(self, tmp_path):
        """The stale-writer drill: a pre-shrink trainer still holding an
        old generation token must get PT-CKPT-005, not rewind the job."""
        d = str(tmp_path)
        old = ResilientTrainer(_toy_build, d, save_every=2, async_save=False)
        eng = _toy_build(["a", "b"])
        old.save(eng, 2, sync=True)
        # a NEW trainer takes ownership (post-shrink restart) and commits
        new = ResilientTrainer(_toy_build, d, save_every=2, async_save=False)
        assert new.generation == old.generation + 1
        new.save(eng, 4, sync=True)
        assert read_latest(d) == (4, new.generation)
        # the zombie's late save is refused and the pointer holds
        with pytest.raises(StaleGenerationError):
            old.save(eng, 6, sync=True)
        assert read_latest(d) == (4, new.generation)


# ---------------------------------------------------------------------------
# replica fallback — dual failure names BOTH copies
# ---------------------------------------------------------------------------

class TestReplicaDualFailure:
    def _flip(self, path):
        blob = bytearray(path.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        path.write_bytes(bytes(blob))

    def test_both_copies_corrupt_names_each(self, tmp_path):
        sd = {"w": Tensor(jnp.arange(512, dtype=jnp.float32))}
        save_state_dict(sd, str(tmp_path), replica=True)
        self._flip(tmp_path / "0_0.distcp")
        self._flip(tmp_path / "0_0.distcp.replica")
        target = {"w": Tensor(jnp.zeros(512, jnp.float32))}
        with pytest.raises(CheckpointCorruptionError) as ei:
            load_state_dict(target, str(tmp_path))
        msg = str(ei.value)
        assert "primary and replica both failed" in msg
        assert "0_0.distcp.replica" in msg
        # and neither copy loaded: the target is untouched
        np.testing.assert_array_equal(np.asarray(target["w"]._data),
                                      np.zeros(512, np.float32))


# ---------------------------------------------------------------------------
# ComposedFaultPlan — per-spec RNG streams, interleaving-proof
# ---------------------------------------------------------------------------

class TestComposedFaultPlan:
    PAYLOAD = bytes(range(256)) * 16

    def _damage(self, order, cls=ComposedFaultPlan):
        plan = cls(seed=5, specs=[
            FaultSpec("site.a", "bitflip", arg=8),
            FaultSpec("site.b", "bitflip", arg=8)])
        out = {}
        with plan:
            for site in order:
                out[site] = corrupt(site, "f", self.PAYLOAD)
        return out

    def test_per_site_damage_is_order_independent(self):
        d1 = self._damage(["site.a", "site.b"])
        d2 = self._damage(["site.b", "site.a"])
        assert d1 == d2                     # byte-identical per site
        assert d1["site.a"] != self.PAYLOAD
        assert d1["site.a"] != d1["site.b"]  # streams are per-spec, not shared

    def test_base_plan_shares_one_stream(self):
        """The contrast that motivates the subclass: the base plan's single
        RNG makes damage depend on cross-site call order."""
        d1 = self._damage(["site.a", "site.b"], cls=FaultPlan)
        d2 = self._damage(["site.b", "site.a"], cls=FaultPlan)
        assert d1["site.a"] != d2["site.a"]

    def test_threaded_damage_is_deterministic(self):
        """PT-RACE posture: each site's events are serialized by its own
        thread, so concurrent sites replay byte-identically run to run."""
        def run():
            plan = ComposedFaultPlan(seed=9, specs=[
                FaultSpec("site.a", "bitflip", count=3, arg=4),
                FaultSpec("site.b", "garbage", count=3)])
            out = {"site.a": [], "site.b": []}

            def loop(site):
                for _ in range(3):
                    out[site].append(corrupt(site, "f", self.PAYLOAD))

            with plan:
                ts = [threading.Thread(target=loop, args=(s,))
                      for s in out]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
            return out

        r1, r2 = run(), run()
        assert r1 == r2
        assert len(set(r1["site.a"])) == 3  # successive draws differ

    def test_fired_counts_every_site(self):
        plan = ComposedFaultPlan(seed=1, specs=[
            FaultSpec("x", "stall", at=0, count=2, arg=0.0),
            FaultSpec("y", "bitflip", at=0, count=1, arg=1)])
        with plan:
            maybe_inject("x")
            maybe_inject("x")
            corrupt("y", "f", b"\x00" * 64)
        assert plan.fired() == {"x": 2, "y": 1}

    def test_rng_for_is_stable_per_spec(self):
        specs = [FaultSpec("x", "bitflip"), FaultSpec("y", "garbage")]
        plan = ComposedFaultPlan(seed=3, specs=specs)
        assert plan.rng_for(specs[0]) is plan.rng_for(specs[0])
        assert plan.rng_for(specs[0]) is not plan.rng_for(specs[1])
        base = FaultPlan(seed=3, specs=specs)
        assert base.rng_for(specs[0]) is base.rng


# ---------------------------------------------------------------------------
# exact-step bit-equal reshard resume (fast pin behind the slow drill)
# ---------------------------------------------------------------------------

class TestExactReshardResume:
    def test_shrink_resume_exact_step_bit_equal_state(self, tmp_path):
        """dp8 → dp4 shrink resumes at EXACTLY the recorded step with
        bit-equal params AND optimizer moments — the deterministic pin
        behind the lifecycle_e2e drill's elastic leg (reshard-on-load must
        be a pure re-placement, never a recompute)."""
        d = str(tmp_path)
        t1 = ResilientTrainer(_toy_build, d, save_every=3, async_save=False)
        out = t1.fit(_data_fn, 3)          # final sync save at step 3
        ref = _leaves(out["engine"].state_dict())

        t2 = ResilientTrainer(lambda alive: _toy_build(["solo"]), d,
                              save_every=3)
        eng2 = _toy_build(["solo"])        # dp4 survivors' mesh
        assert t2.resume(eng2) == 3        # the exact recorded step
        got = _leaves(eng2.state_dict())
        assert set(got) == set(ref)
        for path in sorted(ref):
            np.testing.assert_array_equal(got[path], ref[path], err_msg=path)


# ---------------------------------------------------------------------------
# CheckpointPublisher — verify → load → swap, fenced and observable
# ---------------------------------------------------------------------------

def _trained_ckpt(tmp_path, steps=2):
    t = ResilientTrainer(_toy_build, str(tmp_path), save_every=1,
                         async_save=False)
    out = t.fit(_data_fn, steps)
    return t, out["engine"]


class TestCheckpointPublisher:
    def test_publish_fills_model_bit_equal(self, tmp_path):
        reset_lifecycle_stats()
        t, eng = _trained_ckpt(tmp_path)
        pub_model = _toy_build(["solo"]).model   # fresh (re-seeded) weights
        publisher = CheckpointPublisher(str(tmp_path))
        pub = publisher.publish(pub_model)
        assert pub["step"] == 2 and pub["generation"] == t.generation
        assert pub["shards"] >= 1 and pub["params"] >= 1
        ref = _leaves(eng.state_dict()["model"])
        got = _leaves(pub_model.state_dict())
        assert set(got) == set(ref)
        for path in sorted(ref):
            np.testing.assert_array_equal(got[path], ref[path], err_msg=path)
        stats = lifecycle_stats()
        assert stats["publish_total"] == 1
        assert stats["generation"] == t.generation
        assert stats["phase"] == "serve"

    def test_corrupt_checkpoint_refused_weights_intact(self, tmp_path):
        reset_lifecycle_stats()
        t, _ = _trained_ckpt(tmp_path)
        shard = tmp_path / "step_00000002" / "0_0.distcp"
        blob = bytearray(shard.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        shard.write_bytes(bytes(blob))
        pub_model = _toy_build(["solo"]).model
        before = _leaves(pub_model.state_dict())
        publisher = CheckpointPublisher(str(tmp_path))
        with pytest.raises(CheckpointCorruptionError):
            publisher.publish(pub_model)
        # verification runs BEFORE the in-place load: serving weights held
        after = _leaves(pub_model.state_dict())
        for path in sorted(before):
            np.testing.assert_array_equal(after[path], before[path])
        stats = lifecycle_stats()
        assert stats["publish_failures"] == 1 and stats["publish_total"] == 0

    def test_publisher_fences_generation_rollback(self, tmp_path):
        """After serving generation g, a request to publish an unfenced
        older step (generation 0 — e.g. a zombie writer's leftovers) is
        refused; a same-generation republish is allowed."""
        t, _ = _trained_ckpt(tmp_path)     # step dirs 1 and 2, LATEST (2, g)
        pub_model = _toy_build(["solo"]).model
        publisher = CheckpointPublisher(str(tmp_path))
        pub = publisher.publish(pub_model)
        assert pub["generation"] >= 1
        with pytest.raises(StaleGenerationError) as ei:
            publisher.publish(pub_model, step=1)
        assert ei.value.code == "PT-CKPT-005"
        pub2 = publisher.publish(pub_model)   # same weights, same generation
        assert pub2["generation"] == pub["generation"]

    def test_publish_and_resume_emit_tracer_spans(self, tmp_path):
        from paddle_tpu.observability import TraceRecorder

        t, _ = _trained_ckpt(tmp_path)
        tr = TraceRecorder()
        publisher = CheckpointPublisher(str(tmp_path), tracer=tr)
        pub_model = _toy_build(["solo"]).model
        publisher.publish(pub_model)
        spans = [e for e in tr.events if e["name"] == "publish"]
        assert len(spans) == 1
        args = spans[0]["args"]
        assert args["step"] == 2 and args["ok"] is True
        assert args["generation"] == t.generation and args["shards"] >= 1
        # failure spans carry ok=False (the scrape side of publish_failures)
        shard = tmp_path / "step_00000002" / "0_0.distcp"
        blob = bytearray(shard.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        shard.write_bytes(bytes(blob))
        with pytest.raises(CheckpointCorruptionError):
            publisher.publish(pub_model)
        spans = [e for e in tr.events if e["name"] == "publish"]
        assert spans[-1]["args"]["ok"] is False
        # the resume span helper stamps step + surviving world size
        tr.resume(tr.now(), step=3, world=4)
        res = [e for e in tr.events if e["name"] == "resume"]
        assert res and res[0]["args"] == {"step": 3, "world": 4}


# ---------------------------------------------------------------------------
# lifecycle stats + checkpoint collector
# ---------------------------------------------------------------------------

class TestLifecycleObservability:
    def _families(self, collect):
        return {f.name: f for f in collect()}

    def test_phase_gauge_validates_and_is_one_hot(self):
        from paddle_tpu.observability.collectors import checkpoint_collector

        reset_lifecycle_stats()
        with pytest.raises(ValueError, match="unknown lifecycle phase"):
            set_lifecycle_phase("reticulating")
        for phase in LIFECYCLE_PHASES:
            set_lifecycle_phase(phase)
            fams = self._families(checkpoint_collector())
            samples = fams["pt_lifecycle_phase"].samples
            hot = [lbl["phase"] for _s, lbl, v in samples if v == 1.0]
            assert hot == [phase]
            assert sum(v for _s, _l, v in samples) == 1.0
        reset_lifecycle_stats()
        assert lifecycle_stats()["phase"] == "idle"

    def test_zero_state_renders_required_families(self):
        """With no publisher ever constructed the collector must still
        render every family (they are REQUIRED unconditionally in
        tools/scrape_metrics.py --selftest)."""
        from paddle_tpu.observability.collectors import checkpoint_collector

        reset_lifecycle_stats()
        fams = self._families(checkpoint_collector())
        assert fams["pt_checkpoint_generation"].samples[0][2] == 0.0
        assert fams["pt_checkpoint_publish_total"].samples[0][2] == 0.0
        assert fams["pt_checkpoint_publish_failures"].samples[0][2] == 0.0

    def test_stats_fn_injection(self):
        from paddle_tpu.observability.collectors import checkpoint_collector

        fams = self._families(checkpoint_collector(lambda: {
            "generation": 3, "publish_total": 2, "publish_failures": 1,
            "phase": "publish"}))
        assert fams["pt_checkpoint_generation"].samples[0][2] == 3.0
        assert fams["pt_checkpoint_publish_total"].samples[0][2] == 2.0
        assert fams["pt_checkpoint_publish_failures"].samples[0][2] == 1.0
        hot = [lbl["phase"] for _s, lbl, v
               in fams["pt_lifecycle_phase"].samples if v == 1.0]
        assert hot == ["publish"]
