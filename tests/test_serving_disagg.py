"""Disaggregated prefill/decode tiers + KV-block migration
(inference/disagg.py — docs/SERVING.md "Disaggregated tiers").

Fast in-process pins (unmarked, one tiny 1-layer engine set each): the
codec round trip is bit-identical, corruption is a typed PT-SRV-007
refusal, pool/slot shortfall is ``EngineSaturated`` with the destination
untouched, ``migr-kv`` is terminal in the journal replay set, and the
migration telemetry renders. The compile-heavy end-to-end cases —
TieredRouter bit-identity over warm/cold radix + COW, mid-migration crash
replay — are slow-marked (tier-1 sits near its wall-clock ceiling); the
CI-gated ``kv_migration_corruption`` drill covers the corruption arms
end-to-end (tools/fault_drill.py).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.disagg import (KVChainCodec, KVChainCorrupt,
                                         TieredRouter)
from paddle_tpu.inference.recovery import RequestJournal
from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                          EngineSaturated, Request)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def model():
    paddle.seed(11)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    return cfg, LlamaForCausalLM(cfg)


def _build(m, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("page_size", 8)
    kw.setdefault("block_size", 2)
    kw.setdefault("prefix_cache", True)
    return ContinuousBatchingEngine(m, **kw)


@pytest.fixture(scope="module")
def chain(model):
    """One exported finished-prefill chain + the uninterrupted reference
    stream, shared by the fast pins (ONE source-engine compile set)."""
    cfg, m = model
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    kw = dict(max_new_tokens=8, seed=50)

    ref_eng = _build(m)
    r_ref = Request(prompt, **kw)
    ref_eng.add_request(r_ref)
    ref_eng.run_until_done(max_steps=200)

    src = _build(m)
    req = Request(prompt, **kw)
    src.add_request(req)
    guard = 0
    while not src.migration_ready() and guard < 50:
        src.step()
        guard += 1
    art = KVChainCodec().export_chain(src, req.rid)
    return dict(prompt=prompt, kw=kw, refs=list(r_ref.tokens),
                artifact=art, src=src, rid=req.rid)


class TestCodec:
    def test_spliced_stream_bit_identical(self, model, chain):
        """Import into a fresh engine and decode to completion: the
        continued stream must be byte-identical to the uninterrupted
        single-engine run — stateless sample keys + byte-identical pages
        are the whole argument."""
        _, m = model
        codec = KVChainCodec()
        hdr = codec.peek(chain["artifact"])
        assert hdr["pos"] == len(chain["prompt"]) + len(hdr["delivered"])
        assert hdr["delivered"] == chain["refs"][: len(hdr["delivered"])]
        dst = _build(m)
        req = codec.import_chain(dst, chain["artifact"])
        # migrated prefix is cache-visible on the destination radix
        assert len(dst._radix) >= len(chain["prompt"]) // dst.page_size
        dst.run_until_done(max_steps=200)
        assert list(req.tokens) == chain["refs"]
        assert req.done and not req.failed

    def test_source_unchanged_and_withdraw_active(self, model, chain):
        """Export does not disturb the source: it decodes to the same
        stream. withdraw_active then releases the slot + decrefs pages
        with no terminal bookkeeping (the handoff's source half)."""
        src = chain["src"]
        assert chain["rid"] in src.migration_ready()
        done = src.run_until_done(max_steps=200)
        req = done[chain["rid"]]
        assert list(req.output) == chain["refs"]
        # a second request: withdraw mid-decode
        r2 = Request(chain["prompt"], **chain["kw"])
        src.add_request(r2)
        guard = 0
        while not src.migration_ready() and guard < 50:
            src.step()
            guard += 1
        free_before = src._alloc.free_blocks + len(src._radix)
        assert src.withdraw_active(r2.rid)
        assert src.slot_of(r2.rid) is None
        assert not r2.done and not r2.failed
        # pages went back to free or stayed radix-cached — never leaked
        assert src._alloc.free_blocks + len(src._radix) >= free_before
        assert not src.withdraw_active(r2.rid)

    def test_corruption_detected(self, chain):
        codec = KVChainCodec()
        art = chain["artifact"]
        # flipped payload byte: per-page crc32 names the damaged page
        bad = bytearray(art)
        bad[-10] ^= 0xFF
        with pytest.raises(KVChainCorrupt, match="crc32"):
            codec.import_chain(None, bytes(bad))
        # truncated in transit: structural refusal before any crc work
        with pytest.raises(KVChainCorrupt, match="payload"):
            codec.import_chain(None, art[:-7])
        # not an artifact at all
        with pytest.raises(KVChainCorrupt, match="magic"):
            codec.import_chain(None, b"garbage")
        # digest covers the whole header, not just the pages: a flipped
        # resume position OR a flipped delivered-token id (the last-token
        # carry decode resumes from) must refuse, never silently diverge
        import json as _json

        hdr, payload = codec._parse(art)
        for mutate in (lambda h: h.update(pos=h["pos"] + 8),
                       lambda h: h.update(
                           delivered=[h["delivered"][0] + 1]
                           + h["delivered"][1:])):
            hdr2 = dict(hdr)
            mutate(hdr2)
            hj = _json.dumps(hdr2, separators=(",", ":")).encode()
            forged = (KVChainCodec.MAGIC + (b"%08x" % len(hj)) + hj
                      + bytes(payload))
            with pytest.raises(KVChainCorrupt, match="digest"):
                codec.import_chain(None, forged)

    def test_shortfall_is_engine_saturated(self, model, chain):
        """Slot or pool shortfall refuses the splice with the destination
        untouched — the router's retry-elsewhere contract."""
        _, m = model
        codec = KVChainCodec()
        dst = _build(m)
        held = dst._alloc.hold(dst._alloc.num_blocks)
        assert held == dst._alloc.num_blocks
        with pytest.raises(EngineSaturated, match="shortfall"):
            codec.import_chain(dst, chain["artifact"])
        dst._alloc.release_held()
        assert dst._alloc.free_blocks == dst._alloc.num_blocks
        assert not dst._occupied and len(dst._radix) == 0
        dst._free_slots.clear()            # every slot busy
        with pytest.raises(EngineSaturated, match="slot"):
            codec.import_chain(dst, chain["artifact"])
        assert dst._alloc.free_blocks == dst._alloc.num_blocks

    def test_geometry_mismatch_is_config_error(self, model, chain):
        """A mismatched pool (different page size) is a deployment bug,
        not transit corruption — typed apart from PT-SRV-007."""
        _, m = model
        dst = _build(m, page_size=16, max_len=64)
        with pytest.raises(ValueError, match="geometry|pages"):
            KVChainCodec().import_chain(dst, chain["artifact"])


class TestJournalAndTelemetry:
    def test_migr_kv_terminal_in_replay_set(self, tmp_path):
        p = str(tmp_path / "j.jrnl")
        j = RequestJournal(p)
        base = dict(prompt=[1, 2], max_new=4, eos=None, temp=0.0,
                    top_p=1.0, top_k=0, seed=1, deadline_s=None, priority=1)
        j.append("admit", rid=1, **base)
        j.append("admit", rid=2, **base)
        j.append("migr-kv", rid=1, digest="ab" * 16)
        j.close()
        recs = RequestJournal.load(p)
        # rid 1's chain moved to the decode tier: replaying it here would
        # double-serve; rid 2 is still this journal's responsibility
        assert [r["rid"] for r in RequestJournal.pending(recs)] == [2]

    def test_migration_telemetry_renders(self):
        from paddle_tpu.observability import (TraceRecorder,
                                              parse_prometheus_text)

        tracer = TraceRecorder()
        t0 = tracer.now()
        tracer.migrate(7, 0, 1, pages=3, nbytes=4096, t0=t0)
        tracer.migration_failure(8, "corrupt")
        text = tracer.registry.dump()
        fams = parse_prometheus_text(text)
        assert fams["pt_migration_total"].samples[0][2] == 1.0
        assert fams["pt_migration_pages_total"].samples[0][2] == 3.0
        assert any(lbl.get("reason") == "corrupt" and v == 1.0
                   for _, lbl, v in
                   fams["pt_migration_failures_total"].samples)
        hist = fams["pt_migration_time_ms"]
        assert any(s[0] == "_count" and s[2] >= 1 for s in hist.samples)
        names = [e["name"] for e in tracer.events]
        assert "migrate" in names and "migrate_failure" in names

    def test_zero_state_families_still_render(self):
        """A fresh recorder (no migration yet) must still expose the
        pt_migration_* families — the scrape gate REQUIREs them."""
        from paddle_tpu.observability import (TraceRecorder,
                                              parse_prometheus_text)

        fams = parse_prometheus_text(TraceRecorder().registry.dump())
        for name in ("pt_migration_total", "pt_migration_pages_total",
                     "pt_migration_failures_total", "pt_migration_time_ms"):
            assert name in fams and fams[name].samples, name


def test_prefixless_tier_refused_at_construction(model, tmp_path):
    """A tier built without a prefix cache cannot export/splice chains —
    refused when the router is built, not on the first finished prefill."""
    cfg, m = model
    with pytest.raises(ValueError, match="prefix cache"):
        TieredRouter(lambda: _build(m, prefix_cache=False),
                     lambda: _build(m), str(tmp_path), num_prefill=1,
                     num_decode=1)


def test_incompatible_decode_tier_stays_in_place(model, tmp_path):
    """A decode tier whose pool geometry cannot hold the chain (different
    page size) is filtered by the pre-handoff compatibility gate: the
    candidate decodes to completion on the prefill tier — never retired
    toward a destination that would strand it (the migr-kv handoff is
    only journaled once a compatible target exists)."""
    cfg, m = model
    rng = np.random.default_rng(9)
    p = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    tiered = TieredRouter(lambda: _build(m),
                          lambda: _build(m, page_size=16, max_len=64),
                          str(tmp_path), num_prefill=1, num_decode=1)
    try:
        req = Request(p, max_new_tokens=4, seed=12)
        tiered.submit(req)
        tiered.run_until_done(max_steps=500)
    finally:
        tiered.close()
    assert req.done and not req.failed and len(req.tokens) == 4
    assert tiered.stats["migrations"] == 0
    assert tiered.stats["migration_reprefill"] == 0
    assert tiered.stats["migration_deferred"] >= 1
    recs = RequestJournal.load(tiered.replicas[0].journal_path)
    assert not any(r["k"] == "migr-kv" for r in recs)


def _wave_kwargs(cfg, n=4, shared_page=True):
    """Mixed greedy/seeded wave; with ``shared_page`` the first prompt is
    one full page repeated later — the repeat takes the full-prompt-hit
    COW path on a warm radix."""
    rng = np.random.default_rng(77)
    pa = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    kws = [dict(prompt_ids=pa, max_new_tokens=6, seed=300)]
    for i in range(1, n - 1):
        p = rng.integers(0, cfg.vocab_size, (6 + i,)).astype(np.int32)
        kw = dict(prompt_ids=p, max_new_tokens=8, seed=300 + i)
        if i % 2 == 1:
            kw.update(temperature=0.9)
        kws.append(kw)
    kws.append(dict(prompt_ids=pa,
                    max_new_tokens=8, seed=300) if shared_page
               else dict(prompt_ids=pa, max_new_tokens=8, seed=399))
    return kws


@pytest.mark.slow   # two tier engines + a reference engine compile; the
#                     fast arm is TestCodec above (one chain, bit-identity
#                     pinned in-process)
def test_tiered_router_bit_identity_warm_cold_cow(model, tmp_path):
    """End-to-end acceptance: a 1-prefill+1-decode TieredRouter serves a
    mixed greedy/seeded wave — including a full-page repeat that takes the
    COW path on the warm prefill radix — byte-identical to a single
    engine, twice (cold then warm radix)."""
    cfg, m = model
    kws = _wave_kwargs(cfg)

    def build():
        return _build(m)

    eng = build()
    refs = []
    for _ in range(2):                      # cold wave, then warm radix
        reqs = [Request(**kw) for kw in kws]
        for r in reqs:
            eng.add_request(r)
        eng.run_until_done(max_steps=500)
        refs.append([list(r.tokens) for r in reqs])

    tiered = TieredRouter(build, build, str(tmp_path), num_prefill=1,
                          num_decode=1)
    try:
        for wave in range(2):
            reqs = [Request(**kw) for kw in kws]
            for r in reqs:
                tiered.submit(r)
            tiered.run_until_done(max_steps=2000)
            streams = [list(r.tokens) for r in reqs]
            assert streams == refs[wave], (wave, streams, refs[wave])
        assert tiered.stats["migrations"] >= 2
        assert tiered.stats["migration_pages"] >= 2
        # the handoff is journaled: every migrated rid is terminal in the
        # prefill replica's journal (failover there must not re-serve)
        recs = RequestJournal.load(tiered.replicas[0].journal_path)
        assert sum(r["k"] == "migr-kv" for r in recs) == \
            tiered.stats["migrations"] + tiered.stats["migration_reprefill"]
        assert not RequestJournal.pending(recs)
    finally:
        tiered.close()


@pytest.mark.slow   # replica kill + failover replay recompiles; behavior
#                     also CI-gated via the kv_migration_corruption drill
def test_mid_migration_crash_replay(model, tmp_path):
    """The decode replica dies AFTER chains were spliced into it: the
    fleet's journal-backed failover re-admits them from the decode
    journal's admit + high-water marks, re-runs prefill on the surviving
    prefill replica, verifies the delivered prefix byte-for-byte — never
    double-serving, streams byte-identical to an uninterrupted run."""
    from paddle_tpu.distributed.resilience import FaultPlan, FaultSpec

    cfg, m = model
    kws = _wave_kwargs(cfg, shared_page=False)

    def build():
        return _build(m)

    eng = build()
    reqs0 = [Request(**kw) for kw in kws]
    for r in reqs0:
        eng.add_request(r)
    eng.run_until_done(max_steps=500)
    refs = [list(r.tokens) for r in reqs0]

    plan = FaultPlan(seed=5, specs=[
        FaultSpec("fleet.replica_kill", "kill", at=2, count=1,
                  match="replica:1:")])
    tiered = TieredRouter(build, build, str(tmp_path), num_prefill=1,
                          num_decode=1)
    try:
        reqs = [Request(**kw) for kw in kws]
        with plan:
            for r in reqs:
                tiered.submit(r)
            tiered.run_until_done(max_steps=3000)
    finally:
        tiered.close()
    assert plan.log, "replica kill never fired"
    assert tiered.stats["replica_deaths"] == 1
    assert tiered.stats["failovers"] == 1
    assert not [r.rid for r in reqs if r.failed or not r.done]
    streams = [list(r.tokens) for r in reqs]
    assert streams == refs, [i for i, (s, f) in enumerate(zip(streams, refs))
                             if s != f]
