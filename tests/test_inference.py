"""Inference Predictor tests (reference: test/inference API tests over
AnalysisPredictor; here: jit.save artifact -> Config -> create_predictor ->
handle API -> outputs match eager)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    paddle.seed(0)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
    path = str(tmp_path_factory.mktemp("infer") / "net")
    paddle.jit.save(net, path,
                    input_spec=[paddle.static.InputSpec([2, 8], "float32")])
    return net, path


def test_predictor_handle_api(saved_model):
    net, path = saved_model
    config = inference.Config(path)
    pred = inference.create_predictor(config)

    assert pred.get_input_names() == ["x0"]
    x = np.random.rand(2, 8).astype(np.float32)
    h = pred.get_input_handle("x0")
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_predictor_direct_run(saved_model):
    net, path = saved_model
    pred = inference.create_predictor(inference.Config(path))
    x = np.random.rand(2, 8).astype(np.float32)
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, net(paddle.to_tensor(x)).numpy(), rtol=1e-5)


def test_predictor_shape_mismatch_raises(saved_model):
    _, path = saved_model
    pred = inference.create_predictor(inference.Config(path))
    with pytest.raises(ValueError, match="exported"):
        pred.run([np.zeros((3, 8), np.float32)])


def test_predictor_with_non_persistable_buffer(tmp_path):
    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(4, 2)
            self.register_buffer("offset",
                                 paddle.to_tensor(np.ones(2, np.float32)),
                                 persistable=False)

        def forward(self, x):
            return self.lin(x) + self.offset

    net = Net()
    path = str(tmp_path / "buf")
    paddle.jit.save(net, path,
                    input_spec=[paddle.static.InputSpec([2, 4], "float32")])
    pred = inference.create_predictor(inference.Config(path))
    assert len(pred.get_input_names()) == 1  # buffer is state, not an input
    x = np.random.rand(2, 4).astype(np.float32)
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, net(paddle.to_tensor(x)).numpy(), rtol=1e-5)


def test_predictor_arity_check(saved_model):
    _, path = saved_model
    pred = inference.create_predictor(inference.Config(path))
    with pytest.raises(ValueError, match="expected 1 inputs"):
        pred.run([np.zeros((2, 8), np.float32), np.zeros((2, 8), np.float32)])


def test_predictor_bf16(saved_model):
    net, path = saved_model
    config = inference.Config(path)
    config.enable_bf16()
    pred = inference.create_predictor(config)
    x = np.random.rand(2, 8).astype(np.float32)
    (out,) = pred.run([x])
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=3e-2, atol=3e-2)


def test_predictor_donate_inputs(saved_model):
    """Config.enable_donate_inputs (the PT-COST donation triage —
    ``_donate_inputs`` was a write-only knob before): per-call input
    buffers are donated to XLA, weights are NOT (they must survive every
    run), outputs match the undonated predictor bit-for-bit, and repeated
    runs keep working (fresh uploads each call)."""
    import warnings

    net, path = saved_model
    config = inference.Config(path)
    config.enable_donate_inputs()
    assert config._donate_inputs is True
    pred = inference.create_predictor(config)
    ref_pred = inference.create_predictor(inference.Config(path))
    x = np.random.rand(2, 8).astype(np.float32)
    with warnings.catch_warnings():
        # CPU may decline to alias a particular buffer; that's a memory
        # detail, not a correctness signal
        warnings.simplefilter("ignore")
        (out1,) = pred.run([x])
        (out2,) = pred.run([x])          # weights survived the donation
    (ref,) = ref_pred.run([x])
    np.testing.assert_array_equal(out1, ref)
    np.testing.assert_array_equal(out2, ref)
    # bf16 + donation compose
    cfg2 = inference.Config(path)
    cfg2.enable_bf16()
    cfg2.enable_donate_inputs()
    pred2 = inference.create_predictor(cfg2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        (out3,) = pred2.run([x])
    np.testing.assert_allclose(out3, net(paddle.to_tensor(x)).numpy(),
                               rtol=3e-2, atol=3e-2)
