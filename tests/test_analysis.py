"""Program-analysis suite tests: shape/dtype verifier, trace-hazard linter,
SPMD consistency checker, graph-health reporter, traced-program import, and
the satellite guarantees (pass idempotence, strict Scope lookup, alias-chain
liveness).

Each analyzer gets PAIRED tests: a seeded defect of its class is detected
with the right diagnostic code, and the clean program produces zero
error-severity findings (the CLI-level equivalent lives in
tools/lint_graph.py --selftest, gated by test_ci_gates.py).
"""

import warnings

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static import Executor, program_guard
from paddle_tpu.static.analysis import (
    AnalysisReport,
    GraphHealthReporter,
    Severity,
    ShapeDtypeVerifier,
    SpmdConsistencyChecker,
    TraceHazardLinter,
    check_placements,
    layer_to_program,
    lint_executor,
    lint_scope,
    lint_static_function,
    run_analysis,
)
from paddle_tpu.static.passes import apply_default_passes, live_ops


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _record_linear():
    main = static.Program()
    with program_guard(main):
        x = static.data("x", [4, 8], "float32")
        lin = paddle.nn.Linear(8, 2)
        out = lin(x)
    return main, out, lin


# ---------------------------------------------------------------------------
# shape/dtype verifier
# ---------------------------------------------------------------------------

class TestShapeDtypeVerifier:
    def test_clean_program_no_findings(self):
        main, out, _ = _record_linear()
        rep = AnalysisReport(ShapeDtypeVerifier().analyze(main))
        assert rep.ok and len(rep) == 0

    def test_shape_mismatch_detected_with_provenance(self):
        main, out, _ = _record_linear()
        op = next(o for o in main.global_block().ops if o.outputs)
        v = op.outputs[0]
        v._data = jax.ShapeDtypeStruct(tuple(v._data.shape) + (1,),
                                       v._data.dtype)
        rep = AnalysisReport(ShapeDtypeVerifier().analyze(main))
        hits = rep.by_code("PT-SHAPE-001")
        assert hits and hits[0].severity == Severity.ERROR
        assert hits[0].op_type == op.type and hits[0].op_idx == op.idx
        assert hits[0].source and "test_analysis" in hits[0].source

    def test_dtype_mismatch_detected(self):
        main, out, _ = _record_linear()
        op = next(o for o in main.global_block().ops if o.outputs)
        v = op.outputs[0]
        v._data = jax.ShapeDtypeStruct(tuple(v._data.shape), np.int32)
        rep = AnalysisReport(ShapeDtypeVerifier().analyze(main))
        assert rep.by_code("PT-SHAPE-002")

    def test_fp64_leak_detected(self):
        main, out, _ = _record_linear()
        op = next(o for o in main.global_block().ops if o.outputs)
        v = op.outputs[0]
        v._data = jax.ShapeDtypeStruct(tuple(v._data.shape), np.float64)
        rep = AnalysisReport(ShapeDtypeVerifier().analyze(main))
        hits = rep.by_code("PT-DTYPE-001")
        assert hits and hits[0].severity == Severity.ERROR
        assert "fp64" in hits[0].message or "float64" in hits[0].message

    def test_promotion_surprise_is_warning(self):
        main = static.Program()
        with program_guard(main):
            i = static.data("i", [4], "int32")
            j = static.data("j", [4], "int32")
            # an op whose kernel silently promotes ints to float
            from paddle_tpu.core.op_registry import apply_fn

            out = apply_fn("promote_surprise",
                           lambda a, b: (a + b) * np.float32(1.0), i, j)
        rep = AnalysisReport(ShapeDtypeVerifier().analyze(main))
        hits = rep.by_code("PT-DTYPE-002")
        assert hits and hits[0].severity == Severity.WARNING
        assert rep.ok  # warning-severity only: no errors

    def test_broken_op_flagged_not_raised(self):
        main, out, _ = _record_linear()
        op = next(o for o in main.global_block().ops if o.inputs)
        op.kwargs["nonsense_kwarg"] = object()
        rep = AnalysisReport(ShapeDtypeVerifier().analyze(main))
        assert rep.by_code("PT-SHAPE-003")


# ---------------------------------------------------------------------------
# trace-hazard linter
# ---------------------------------------------------------------------------

class TestTraceHazardLinter:
    def test_unseeded_stochastic_detected(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [8], "float32")
            y = paddle.nn.functional.dropout(x, p=0.5, training=True)
        rep = AnalysisReport(TraceHazardLinter(
            assume_seeded=False).analyze(main))
        hits = rep.by_code("PT-TRACE-003")
        assert hits and hits[0].severity == Severity.ERROR
        assert "dropout" in (hits[0].op_type or "")

    def test_unseeded_recording_not_laundered_by_later_seed(self):
        # seededness is stamped at RECORD time: seeding after the fact must
        # not hide that the recording itself was unreproducible
        from paddle_tpu.framework import random as frandom

        frandom._global["seeded"] = False
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [8], "float32")
            paddle.nn.functional.dropout(x, p=0.5, training=True)
        paddle.seed(7)  # later, unrelated
        rep = AnalysisReport(TraceHazardLinter().analyze(main))
        assert rep.by_code("PT-TRACE-003")
        # post-hoc program.random_seed must not launder it either (the
        # Executor never consumes it; replays stay unreproducible)
        main.random_seed = 1
        rep2 = AnalysisReport(TraceHazardLinter().analyze(main))
        assert rep2.by_code("PT-TRACE-003")

    def test_set_rng_state_counts_as_seeded(self):
        # restoring a saved key is an explicit seeding decision: no
        # false-positive PT-TRACE-003 for resumed runs
        from paddle_tpu.framework import random as frandom

        frandom._global["seeded"] = False
        frandom.set_rng_state(jax.random.key(5))
        assert frandom.explicitly_seeded()

    def test_seeded_stochastic_clean(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [8], "float32")
            paddle.nn.functional.dropout(x, p=0.5, training=True)
        # conftest autouse fixture calls paddle.seed → explicitly seeded
        rep = AnalysisReport(TraceHazardLinter().analyze(main))
        assert not rep.by_code("PT-TRACE-003")

    def test_feed_signature_churn_detected(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [None, 4], "float32")
            y = x * 2.0
        exe = Executor()
        for b in (1, 2, 3):
            exe.run(main, feed={"x": np.ones((b, 4), np.float32)},
                    fetch_list=[y])
        hits = [d for d in lint_executor(exe) if d.code == "PT-TRACE-001"]
        assert hits and hits[0].severity == Severity.ERROR

    def test_stable_feed_signature_clean(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [None, 4], "float32")
            y = x * 2.0
        exe = Executor()
        for _ in range(4):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[y])
        assert not lint_executor(exe)

    def test_scalar_kwarg_capture_detected(self):
        paddle.disable_static()
        try:
            @paddle.jit.to_static(full_graph=True)
            def f(x, scale=1.0):
                return x * 2.0

            xv = paddle.to_tensor(np.ones(3, np.float32))
            for s in (0.1, 0.2, 0.3):  # python scalar varies per call
                f(xv, scale=s)
            hits = [d for d in lint_static_function(f)
                    if d.code == "PT-TRACE-002"]
            assert hits and "scale" in hits[0].message
        finally:
            paddle.enable_static()

    def test_stable_kwargs_clean_and_host_sync_warns(self):
        paddle.disable_static()
        try:
            @paddle.jit.to_static(full_graph=True)
            def g(x):
                y = x * 2.0
                _ = float(np.float32(1.0))  # benign host math, not a sync
                return y

            @paddle.jit.to_static(full_graph=False)
            def h(x):
                return float(x.sum().numpy()) + 0 * x  # host sync in source

            xv = paddle.to_tensor(np.ones(3, np.float32))
            g(xv)
            assert not lint_static_function(g)
            hits = [d for d in lint_static_function(h)
                    if d.code == "PT-TRACE-004"]
            assert hits and hits[0].severity == Severity.WARNING
            assert hits[0].source  # names the file:line
        finally:
            paddle.enable_static()


# ---------------------------------------------------------------------------
# host-borrow lint (PT-TRACE-005 — the PR-4 serving bug class)
# ---------------------------------------------------------------------------

class TestHostBorrowLint:
    def test_mutation_after_upload_flagged(self):
        from paddle_tpu.static.analysis import lint_host_borrow

        def dispatch(tables):
            import jax.numpy as jnp

            dev = jnp.asarray(tables)       # borrows the host buffer
            tables[0] = -1                  # mutated while transfer in flight
            return dev

        hits = [d for d in lint_host_borrow(dispatch)
                if d.code == "PT-TRACE-005"]
        assert hits and hits[0].severity == Severity.ERROR
        assert "tables" in hits[0].message and ".copy()" in hits[0].message

    def test_loop_mutation_races_previous_iterations_upload(self):
        from paddle_tpu.static.analysis import lint_host_borrow

        # textually the mutation PRECEDES the upload, but inside a loop the
        # next iteration's store races the previous iteration's transfer —
        # exactly how the serving engine hit it
        src = (
            "def tick(buf):\n"
            "    import jax.numpy as jnp\n"
            "    for i in range(8):\n"
            "        buf[i] = i\n"
            "        dev = jnp.asarray(buf)\n"
            "    return dev\n")
        assert any(d.code == "PT-TRACE-005" for d in lint_host_borrow(src))

    def test_whole_array_augassign_flagged_rebind_clean(self):
        from paddle_tpu.static.analysis import lint_host_borrow

        # ``buf += 1`` mutates the SAME ndarray in place — as much a race
        # as a subscript store; a plain ``buf = ...`` rebinds and is clean
        src = (
            "def f(buf):\n"
            "    import jax.numpy as jnp\n"
            "    dev = jnp.asarray(buf)\n"
            "    buf += 1\n"
            "    return dev\n")
        assert any(d.code == "PT-TRACE-005" for d in lint_host_borrow(src))
        rebind = (
            "def g(buf):\n"
            "    import jax.numpy as jnp\n"
            "    dev = jnp.asarray(buf)\n"
            "    buf = make_fresh()\n"
            "    return dev\n")
        assert not lint_host_borrow(rebind)

    def test_copy_upload_and_pre_mutation_clean(self):
        from paddle_tpu.static.analysis import lint_host_borrow

        def safe(tables):
            import jax.numpy as jnp

            tables[0] = -1                  # before the upload: sequenced
            dev = jnp.asarray(tables.copy())   # snapshot, no borrow
            return dev

        assert not lint_host_borrow(safe)

    def test_wired_through_trace_hazard_linter(self):
        def bad(buf):
            import jax.numpy as jnp

            dev = jnp.asarray(buf)
            buf.fill(0)                     # in-place mutator method
            return dev

        main = static.Program()
        with program_guard(main):
            static.data("x", [2], "float32")
        rep = AnalysisReport(
            TraceHazardLinter(borrow_fns=[bad]).analyze(main))
        assert rep.by_code("PT-TRACE-005")


# ---------------------------------------------------------------------------
# SPMD consistency checker
# ---------------------------------------------------------------------------

class TestSpmdChecker:
    def _mesh(self, shape, names):
        from paddle_tpu.distributed.auto_parallel import ProcessMesh

        return ProcessMesh(shape=shape, dim_names=names)

    def test_valid_placement_clean(self):
        from paddle_tpu.distributed.auto_parallel import Replicate, Shard

        mesh = self._mesh([2, 4], ["dp", "mp"])
        assert check_placements((8, 6), mesh, [Shard(0), Replicate()]) == []

    def test_invalid_shard_dim_detected(self):
        from paddle_tpu.distributed.auto_parallel import Replicate, Shard

        mesh = self._mesh([2, 4], ["dp", "mp"])
        out = check_placements((8, 6), mesh, [Shard(5), Replicate()])
        assert out and out[0].code == "PT-SPMD-001"
        assert "wrap" in out[0].message  # names the silent-wrap hazard

    def test_placement_count_rules(self):
        from paddle_tpu.distributed.auto_parallel import Replicate, Shard

        mesh = self._mesh([2, 4], ["dp", "mp"])
        # FEWER placements than mesh axes is valid: the rest replicate
        # (matches placements_to_spec's zip semantics)
        assert check_placements((8, 8), mesh, [Shard(0)]) == []
        # MORE placements are silently dropped at lowering — flagged
        out = check_placements((8, 8), mesh,
                               [Shard(0), Replicate(), Shard(1)])
        assert out and out[0].code == "PT-SPMD-001"
        assert "dropped" in out[0].message

    def test_uneven_shard_detected(self):
        from paddle_tpu.distributed.auto_parallel import Replicate, Shard

        mesh = self._mesh([2, 4], ["dp", "mp"])
        out = check_placements((8, 6), mesh, [Replicate(), Shard(1)])
        assert out and out[0].code == "PT-SPMD-002"  # 6 % 4 != 0

    def test_dynamic_dim_skipped(self):
        from paddle_tpu.distributed.auto_parallel import Replicate, Shard

        mesh = self._mesh([2, 4], ["dp", "mp"])
        assert check_placements((-1, 8), mesh, [Shard(0), Replicate()]) == []

    def test_shard_tensor_warns_before_lowering(self):
        from paddle_tpu.distributed.auto_parallel import (Replicate, Shard,
                                                          shard_tensor)

        paddle.disable_static()
        try:
            mesh = self._mesh([8], ["mp"])
            # uneven shard: the named diagnostic precedes jax's opaque error
            with pytest.warns(UserWarning, match="PT-SPMD-002"):
                with pytest.raises(ValueError, match="divisible"):
                    shard_tensor(paddle.to_tensor(
                        np.zeros((6, 4), np.float32)), mesh, [Shard(0)])
            # out-of-range dim: placements_to_spec silently WRAPS it, so the
            # warning is the only signal at all
            with pytest.warns(UserWarning, match="PT-SPMD-001"):
                shard_tensor(paddle.to_tensor(
                    np.zeros((16, 4), np.float32)), mesh, [Shard(6)])
        finally:
            paddle.enable_static()

    def test_conflicting_shardings_on_one_op(self):
        from paddle_tpu.distributed.auto_parallel import (ProcessMesh,
                                                          Replicate, Shard)

        main = static.Program()
        with program_guard(main):
            a = static.data("a", [8, 4], "float32")
            b = static.data("b", [8, 4], "float32")
            c = a + b
        mesh = ProcessMesh(shape=[2], dim_names=["dp"])
        a.process_mesh = mesh
        a.placements = [Shard(0)]
        b.process_mesh = mesh
        b.placements = [Replicate()]
        rep = AnalysisReport(SpmdConsistencyChecker().analyze(main))
        hits = rep.by_code("PT-SPMD-003")
        assert hits and "conflicting" in hits[0].message

    def test_aligned_shardings_clean(self):
        from paddle_tpu.distributed.auto_parallel import ProcessMesh, Shard

        main = static.Program()
        with program_guard(main):
            a = static.data("a", [8, 4], "float32")
            b = static.data("b", [8, 4], "float32")
            c = a + b
        mesh = ProcessMesh(shape=[2], dim_names=["dp"])
        for t in (a, b):
            t.process_mesh = mesh
            t.placements = [Shard(0)]
        assert not SpmdConsistencyChecker().analyze(main)

    def test_finding_ids_are_stable_and_line_number_free(self):
        """Every SPMD diagnostic carries a ``CODE:scope:detail`` finding
        id (the PT-RACE/PT-COST baseline scheme): the same defect must
        keep the same id no matter WHERE in the program it sits — ids
        name what is wrong, never source positions — while distinct
        defects get distinct ids."""
        from paddle_tpu.distributed.auto_parallel import (ProcessMesh,
                                                          Replicate, Shard)

        def build(n_padding_ops):
            """The same mesh-conflict defect after n unrelated ops."""
            main = static.Program()
            with program_guard(main):
                for i in range(n_padding_ops):   # shift op indices around
                    static.data(f"pad{i}", [2], "float32") * 2.0
                a = static.data("a", [8, 4], "float32")
                b = static.data("b", [8, 4], "float32")
                a + b
            a.process_mesh = ProcessMesh(shape=[2], dim_names=["dp"])
            a.placements = [Shard(0)]
            b.process_mesh = ProcessMesh(shape=[4], dim_names=["mp"])
            b.placements = [Replicate()]
            return [d for d in SpmdConsistencyChecker().analyze(main)
                    if d.code == "PT-SPMD-003"]

        ids0 = sorted(d.finding_id for d in build(0))
        ids5 = sorted(d.finding_id for d in build(5))
        assert ids0 and ids0 == ids5         # position-independent
        assert "PT-SPMD-003:add:mesh-conflict:a:b" in ids0
        for fid in ids0:                     # never a source position
            assert ":line" not in fid and ".py" not in fid

        # check_placements details are defect-shaped, not positional,
        # and distinct defect classes never collide
        mesh = ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
        bad_dim = check_placements((8, 6), mesh, [Shard(5), Replicate()],
                                   where="input 'w'")
        uneven = check_placements((8, 6), mesh, [Replicate(), Shard(1)],
                                  where="input 'w'")
        assert bad_dim[0].finding_id == "PT-SPMD-001:input_w:shard-dim:5:dp"
        assert uneven[0].finding_id == "PT-SPMD-002:input_w:uneven:dim1:x4"
        assert bad_dim[0].finding_id != uneven[0].finding_id
        # identical defect described twice -> identical id (baselinable)
        again = check_placements((8, 6), mesh, [Shard(5), Replicate()],
                                 where="input 'w'")
        assert again[0].finding_id == bad_dim[0].finding_id


# ---------------------------------------------------------------------------
# graph health / Program.diagnose
# ---------------------------------------------------------------------------

class TestGraphHealth:
    def test_dead_op_and_duplicate_reported(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [4], "float32")
            a = paddle.exp(x)
            b = paddle.exp(x)      # duplicate (CSE candidate)
            used = a + 1.0
            _dead = x * 5.0        # dead relative to targets
        rep = main.diagnose(targets=[used])
        assert rep.by_code("PT-GRAPH-001")  # dead op
        assert rep.by_code("PT-GRAPH-002")  # duplicate subgraph

    def test_unused_parameter_detected(self):
        main, out, lin = _record_linear()
        ghost = paddle.Tensor(np.zeros((3, 3), np.float32))
        ghost.is_parameter = True
        ghost.name = "ghost"
        rep = run_analysis(main, targets=[out],
                           parameters=list(lin.parameters()) + [ghost])
        hits = rep.by_code("PT-GRAPH-003")
        assert hits and hits[0].severity == Severity.ERROR
        assert "ghost" in hits[0].message

    def test_used_parameters_clean(self):
        main, out, lin = _record_linear()
        rep = run_analysis(main, targets=[out],
                           parameters=list(lin.parameters()))
        assert not rep.by_code("PT-GRAPH-003")

    def test_diagnose_clean_program_ok(self):
        main, out, _ = _record_linear()
        rep = main.diagnose(targets=[out])
        assert rep.ok

    def test_analysis_does_not_mutate_or_invalidate_cache(self):
        main, out, _ = _record_linear()
        n_ops, version = main.num_ops, main._version
        exe = Executor()
        exe.run(main, feed={"x": np.ones((4, 8), np.float32)},
                fetch_list=[out])
        main.diagnose(targets=[out])
        assert main.num_ops == n_ops and main._version == version
        exe.run(main, feed={"x": np.ones((4, 8), np.float32)},
                fetch_list=[out])
        assert len(exe._cache) == 1  # compiled plan survived the analysis


# ---------------------------------------------------------------------------
# traced-program import (model families)
# ---------------------------------------------------------------------------

class TestTraceImport:
    def test_layer_imports_and_lints_clean(self):
        paddle.disable_static()
        try:
            lin = paddle.nn.Linear(4, 2)
            prog = layer_to_program(
                lin, jax.ShapeDtypeStruct((3, 4), np.float32),
                input_names=["x"])
        finally:
            paddle.enable_static()
        assert prog.num_ops >= 2
        params = [v for v in prog.list_vars()
                  if getattr(v, "is_parameter", False)]
        assert len(params) == 2  # weight + bias, named
        assert any("weight" in v.name for v in params)
        rep = run_analysis(prog, targets=prog._outputs,
                           parameters=list(lin.parameters()))
        assert rep.ok, rep.summary()

    def test_imported_program_replays_in_executor(self):
        paddle.disable_static()
        try:
            lin = paddle.nn.Linear(4, 2)
            prog = layer_to_program(
                lin, jax.ShapeDtypeStruct((3, 4), np.float32),
                input_names=["x"])
        finally:
            paddle.enable_static()
        exe = Executor()
        xv = np.random.rand(3, 4).astype(np.float32)
        feed = {"x": xv}
        for v in prog.list_vars():
            if getattr(v, "is_parameter", False):
                feed[v.name] = v._param.numpy()
        (got,) = exe.run(prog, feed=feed, fetch_list=[prog._outputs[0]])
        ref = xv @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_imported_random_ops_not_flagged_unseeded(self):
        # a traced jax.random draw bakes its key into the jaxpr: replays are
        # bit-identical, so PT-TRACE-003 must not fire even when the process
        # never called paddle.seed
        from paddle_tpu.static.analysis import trace_to_program

        prog = trace_to_program(
            lambda x: jax.random.uniform(jax.random.key(0), (4,)) + x,
            jax.ShapeDtypeStruct((4,), np.float32))
        assert any("rand" in op.type for op in prog.global_block().ops)
        rep = AnalysisReport(TraceHazardLinter(
            assume_seeded=False).analyze(prog))
        assert not rep.by_code("PT-TRACE-003")

    def test_import_carries_source_provenance(self):
        paddle.disable_static()
        try:
            lin = paddle.nn.Linear(4, 2)
            prog = layer_to_program(
                lin, jax.ShapeDtypeStruct((3, 4), np.float32))
        finally:
            paddle.enable_static()
        assert any(op.src for op in prog.global_block().ops)


# ---------------------------------------------------------------------------
# satellites: Scope strict lookup, pass idempotence, alias-chain liveness
# ---------------------------------------------------------------------------

class TestScopeStrict:
    def test_strict_raises_on_unknown(self):
        from paddle_tpu.static import Scope

        sc = Scope()
        with pytest.raises(KeyError, match="never written"):
            sc.var("missing", strict=True)

    def test_lenient_read_is_tracked_and_linted(self):
        from paddle_tpu.static import Scope

        sc = Scope()
        t = sc.var("phantom")  # silently materialized ()-float32 zero
        sc.var("phantom")      # second read of a still-never-written name
        assert t.shape == []
        assert sc._lazy_reads["phantom"] == 2
        hits = [d for d in lint_scope(sc) if d.code == "PT-SCOPE-001"]
        assert hits and hits[0].severity == Severity.WARNING
        assert "phantom" in hits[0].message and "2x" in hits[0].message
        # strict lookup still fails on the materialized-but-never-written name
        with pytest.raises(KeyError, match="never written"):
            sc.var("phantom", strict=True)
        # a later write cures it
        sc.set("phantom", paddle.Tensor(np.ones((), np.float32)))
        sc.var("phantom", strict=True)
        assert not lint_scope(sc)

    def test_written_then_read_clean(self):
        from paddle_tpu.static import Scope

        sc = Scope()
        sc.set("x", paddle.Tensor(np.ones(2, np.float32)))
        sc.var("x")
        sc.var("x", strict=True)  # strict lookup of a written var is fine
        assert not lint_scope(sc)

    def test_executor_fetch_writes_scope(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [2], "float32")
            y = x + 1.0
        exe = Executor()
        sc = static.Scope()
        exe.run(main, feed={"x": np.zeros(2, np.float32)}, fetch_list=[y],
                scope=sc)
        assert not lint_scope(sc)  # fetched var was WRITTEN, not lazy-read


class TestPassIdempotence:
    def test_default_passes_reach_fixpoint_in_one_run(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [4], "float32")
            c = paddle.ones([4]) * 3.0 + 1.0   # foldable
            a = paddle.exp(x)
            b = paddle.exp(x)                  # CSE duplicate
            used = a + b + c
            _dead = x * 7.0                    # DCE target
        stats1 = apply_default_passes(main, targets=[used])
        assert sum(stats1.values()) > 0
        stats2 = apply_default_passes(main, targets=[used])
        assert sum(stats2.values()) == 0, (
            f"second pass run must be a no-op, got {stats2}")
        # and the program still computes the right thing
        exe = Executor()
        (o,) = exe.run(main, feed={"x": np.zeros(4, np.float32)},
                       fetch_list=[used])
        np.testing.assert_allclose(o, 2 * np.exp(0.0) + 4.0)


class TestLiveOpsAliasChain:
    def test_chain_of_aliased_views_keeps_producer_alive(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [4], "float32")
            base = paddle.exp(x)       # producer
            v1 = paddle.reshape(base, [4])
            v2 = paddle.reshape(v1, [2, 2])
        ops = main.global_block().ops
        base_op = next(o for o in ops if o.type == "exp")
        # simulate a view-op alias CHAIN: v2 -> v1 -> base (multi-hop)
        aliases = {id(v2): id(v1), id(v1): id(base)}
        kept = live_ops(ops, [id(v2)], aliases)
        assert base_op in kept, "alias chain dropped the producing op"

    def test_resolve_alias_follows_chain_and_tolerates_cycles(self):
        from paddle_tpu.static.passes import resolve_alias

        assert resolve_alias({1: 2, 2: 3}, 1) == 3
        assert resolve_alias({}, 7) == 7
        assert resolve_alias({1: 2, 2: 1}, 1) in (1, 2)  # no infinite loop

    def test_executor_fetch_through_alias_chain(self):
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [4], "float32")
            y = paddle.exp(x)
            z = paddle.exp(x)
        from paddle_tpu.static.passes import (
            CommonSubexpressionEliminationPass)

        CommonSubexpressionEliminationPass().apply(main)
        exe = Executor()
        xv = np.random.rand(4).astype(np.float32)
        (o,) = exe.run(main, feed={"x": xv}, fetch_list=[z])
        np.testing.assert_allclose(o, np.exp(xv), rtol=1e-6)

    def test_executor_fetch_through_multi_hop_alias_chain(self):
        # liveness (live_ops) and replay (fetch/resolve) must agree on the
        # canonical id when the alias map is MULTI-hop (stacked view passes)
        main = static.Program()
        with program_guard(main):
            x = static.data("x", [4], "float32")
            base = paddle.exp(x)
            v1 = paddle.reshape(base, [4])
            v2 = paddle.reshape(v1, [4])
        blk = main.global_block()
        # drop the view ops and alias their outputs back to the producer,
        # exactly what a view-collapsing pass would record
        blk.ops = [op for op in blk.ops
                   if not any(o is v1 or o is v2 for o in op.outputs)]
        main._aliases = {id(v2): id(v1), id(v1): id(base)}
        exe = Executor()
        xv = np.random.rand(4).astype(np.float32)
        (o,) = exe.run(main, feed={"x": xv}, fetch_list=[v2])
        np.testing.assert_allclose(o, np.exp(xv), rtol=1e-6)


# ---------------------------------------------------------------------------
# run_analysis composition through PassManager
# ---------------------------------------------------------------------------

def test_analysis_passes_compose_with_pass_manager():
    from paddle_tpu.static import PassManager
    from paddle_tpu.static.passes import ConstantFoldingPass

    main = static.Program()
    with program_guard(main):
        x = static.data("x", [2], "float32")
        c = paddle.ones([2]) * 3.0
        y = x + c
    pm = PassManager([ConstantFoldingPass(), ShapeDtypeVerifier(),
                      GraphHealthReporter(targets=[y])])
    stats = pm.run(main)
    assert stats["constant_folding"] >= 1
    assert stats["shape_dtype_verifier"] == 0  # clean after folding
    assert stats["graph_health_reporter"] == 0
    # latest analysis report per pass name lives on the program
    assert set(main._analysis_reports) == {
        "shape_dtype_verifier", "graph_health_reporter"}
    # repeated runs replace, not accumulate
    pm.run(main)
    assert len(main._analysis_reports) == 2


def test_cse_key_distinguishes_literal_types():
    # True == 1 == 1.0 under dict equality; merging on it would change dtypes
    from paddle_tpu.core.static_graph import Operation
    from paddle_tpu.static.passes import cse_key

    def fn(a):
        return a

    k_float = cse_key(Operation(0, "add", fn, [1.0], {}), {})
    k_bool = cse_key(Operation(1, "add", fn, [True], {}), {})
    k_int = cse_key(Operation(2, "add", fn, [1], {}), {})
    assert len({k_float, k_bool, k_int}) == 3


def test_suppress_drops_findings_by_code():
    main = static.Program()
    with program_guard(main):
        x = static.data("x", [4], "float32")
        used = paddle.exp(x)
        _dead = x * 5.0
    rep = run_analysis(main, targets=[used], suppress=("PT-GRAPH-001",))
    assert not rep.by_code("PT-GRAPH-001")
