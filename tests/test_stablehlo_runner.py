"""Native (C++) consumption of the jit.save StableHLO artifact.

Parity anchor: the reference executes jit.save'd programs from C++ via
jit::Layer (/root/reference/paddle/fluid/jit/layer.h:1) and ships non-Python
clients (r/, goapi). Here jit.save emits ``path.mlir`` (StableHLO text) next
to the serialized export, and ``native/src/stablehlo_runner.cc`` executes it
with zero Python in the process — outputs must match the Python model.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "paddle_tpu", "native", "src", "stablehlo_runner.cc")

gxx = shutil.which("g++")


@pytest.fixture(scope="module")
def runner_bin(tmp_path_factory):
    if gxx is None:
        pytest.skip("g++ not available")
    out = tmp_path_factory.mktemp("bin") / "stablehlo_runner"
    subprocess.run([gxx, "-O2", "-std=c++17", "-o", str(out), SRC], check=True)
    return str(out)


class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        h = paddle.tanh(self.fc1(x))
        return self.fc2(h)


def test_cpp_runner_matches_python(runner_bin, tmp_path):
    paddle.seed(3)
    net = _Net()
    m = paddle.jit.to_static(net)
    path = str(tmp_path / "net")
    paddle.jit.save(m, path,
                    input_spec=[paddle.static.InputSpec([2, 8], "float32")])
    assert os.path.exists(path + ".mlir")

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 8)).astype(np.float32)
    want = net(paddle.to_tensor(x)).numpy()

    # write the state (in _collect_state order == signature order) + input
    from paddle_tpu.jit.api import _collect_state

    _, tensors = _collect_state(net)
    bins = []
    for i, t in enumerate(tensors):
        b = tmp_path / f"state{i}.bin"
        np.asarray(t.numpy(), np.float32).tofile(b)
        bins.append(str(b))
    xb = tmp_path / "x.bin"
    x.tofile(xb)
    bins.append(str(xb))

    res = subprocess.run(
        [runner_bin, path + ".mlir", *bins, "--out", str(tmp_path / "out")],
        capture_output=True, text=True, check=True)
    assert "out0" in res.stdout
    got = np.fromfile(tmp_path / "out0.bin", np.float32).reshape(want.shape)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_cpp_runner_deeper_net_with_ln(runner_bin, tmp_path):
    """A deeper net (3 layers + sigmoid head) through the same pipeline."""

    class Deep(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(6, 32)
            self.b = nn.Linear(32, 32)
            self.c = nn.Linear(32, 3)

        def forward(self, x):
            h = paddle.tanh(self.a(x))
            h = paddle.nn.functional.sigmoid(self.b(h))
            return self.c(h)

    paddle.seed(4)
    net = Deep()
    m = paddle.jit.to_static(net)
    path = str(tmp_path / "deep")
    paddle.jit.save(m, path,
                    input_spec=[paddle.static.InputSpec([5, 6], "float32")])

    rng = np.random.default_rng(1)
    x = rng.normal(size=(5, 6)).astype(np.float32)
    want = net(paddle.to_tensor(x)).numpy()

    from paddle_tpu.jit.api import _collect_state

    _, tensors = _collect_state(net)
    bins = []
    for i, t in enumerate(tensors):
        b = tmp_path / f"s{i}.bin"
        np.asarray(t.numpy(), np.float32).tofile(b)
        bins.append(str(b))
    xb = tmp_path / "x.bin"
    x.tofile(xb)
    bins.append(str(xb))

    subprocess.run(
        [runner_bin, path + ".mlir", *bins, "--out", str(tmp_path / "o")],
        capture_output=True, text=True, check=True)
    got = np.fromfile(tmp_path / "o0.bin", np.float32).reshape(want.shape)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_cpp_runner_rejects_wrong_input_count(runner_bin, tmp_path):
    paddle.seed(5)
    net = _Net()
    m = paddle.jit.to_static(net)
    path = str(tmp_path / "net2")
    paddle.jit.save(m, path,
                    input_spec=[paddle.static.InputSpec([2, 8], "float32")])
    res = subprocess.run([runner_bin, path + ".mlir"],
                         capture_output=True, text=True)
    assert res.returncode != 0
    assert "expects" in res.stderr
