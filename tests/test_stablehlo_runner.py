"""Native (C++) consumption of the jit.save StableHLO artifact.

Parity anchor: the reference executes jit.save'd programs from C++ via
jit::Layer (/root/reference/paddle/fluid/jit/layer.h:1) and ships non-Python
clients (r/, goapi). Here jit.save emits ``path.mlir`` (StableHLO text) next
to the serialized export, and ``native/src/stablehlo_runner.cc`` executes it
with zero Python in the process — outputs must match the Python model.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "paddle_tpu", "native", "src", "stablehlo_runner.cc")

gxx = shutil.which("g++")


@pytest.fixture(scope="module")
def runner_bin(tmp_path_factory):
    if gxx is None:
        pytest.skip("g++ not available")
    out = tmp_path_factory.mktemp("bin") / "stablehlo_runner"
    subprocess.run([gxx, "-O2", "-std=c++17", "-o", str(out), SRC], check=True)
    return str(out)


class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        h = paddle.tanh(self.fc1(x))
        return self.fc2(h)


def test_cpp_runner_matches_python(runner_bin, tmp_path):
    paddle.seed(3)
    net = _Net()
    m = paddle.jit.to_static(net)
    path = str(tmp_path / "net")
    paddle.jit.save(m, path,
                    input_spec=[paddle.static.InputSpec([2, 8], "float32")])
    assert os.path.exists(path + ".mlir")

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 8)).astype(np.float32)
    want = net(paddle.to_tensor(x)).numpy()

    # write the state (in _collect_state order == signature order) + input
    from paddle_tpu.jit.api import _collect_state

    _, tensors = _collect_state(net)
    bins = []
    for i, t in enumerate(tensors):
        b = tmp_path / f"state{i}.bin"
        np.asarray(t.numpy(), np.float32).tofile(b)
        bins.append(str(b))
    xb = tmp_path / "x.bin"
    x.tofile(xb)
    bins.append(str(xb))

    res = subprocess.run(
        [runner_bin, path + ".mlir", *bins, "--out", str(tmp_path / "out")],
        capture_output=True, text=True, check=True)
    assert "out0" in res.stdout
    got = np.fromfile(tmp_path / "out0.bin", np.float32).reshape(want.shape)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_cpp_runner_deeper_net_with_ln(runner_bin, tmp_path):
    """A deeper net (3 layers + sigmoid head) through the same pipeline."""

    class Deep(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(6, 32)
            self.b = nn.Linear(32, 32)
            self.c = nn.Linear(32, 3)

        def forward(self, x):
            h = paddle.tanh(self.a(x))
            h = paddle.nn.functional.sigmoid(self.b(h))
            return self.c(h)

    paddle.seed(4)
    net = Deep()
    m = paddle.jit.to_static(net)
    path = str(tmp_path / "deep")
    paddle.jit.save(m, path,
                    input_spec=[paddle.static.InputSpec([5, 6], "float32")])

    rng = np.random.default_rng(1)
    x = rng.normal(size=(5, 6)).astype(np.float32)
    want = net(paddle.to_tensor(x)).numpy()

    from paddle_tpu.jit.api import _collect_state

    _, tensors = _collect_state(net)
    bins = []
    for i, t in enumerate(tensors):
        b = tmp_path / f"s{i}.bin"
        np.asarray(t.numpy(), np.float32).tofile(b)
        bins.append(str(b))
    xb = tmp_path / "x.bin"
    x.tofile(xb)
    bins.append(str(xb))

    subprocess.run(
        [runner_bin, path + ".mlir", *bins, "--out", str(tmp_path / "o")],
        capture_output=True, text=True, check=True)
    got = np.fromfile(tmp_path / "o0.bin", np.float32).reshape(want.shape)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_cpp_runner_rejects_wrong_input_count(runner_bin, tmp_path):
    paddle.seed(5)
    net = _Net()
    m = paddle.jit.to_static(net)
    path = str(tmp_path / "net2")
    paddle.jit.save(m, path,
                    input_spec=[paddle.static.InputSpec([2, 8], "float32")])
    res = subprocess.run([runner_bin, path + ".mlir"],
                         capture_output=True, text=True)
    assert res.returncode != 0
    assert "expects" in res.stderr


@pytest.fixture(scope="module")
def capi_lib(tmp_path_factory):
    if gxx is None:
        pytest.skip("g++ not available")
    src = os.path.join(REPO, "paddle_tpu", "native", "src", "capi_runner.cc")
    out = tmp_path_factory.mktemp("lib") / "libpaddle_tpu_infer.so"
    subprocess.run([gxx, "-O2", "-std=c++17", "-shared", "-fPIC",
                    "-o", str(out), src], check=True)
    return str(out)


def test_capi_library_matches_python(capi_lib, tmp_path):
    """C-ABI inference library (component #69: language bindings): load the
    jit.save StableHLO artifact through plain C entry points via ctypes —
    the same C surface Go/R/Rust would bind — and match the
    Python model bit-for-bit in fp32."""
    import ctypes

    paddle.seed(3)
    net = _Net()
    x = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)
    ref = net(paddle.to_tensor(x)).numpy()

    path = str(tmp_path / "net")
    paddle.jit.save(net, path,
                    input_spec=[paddle.static.InputSpec([4, 8], "float32")])

    lib = ctypes.CDLL(capi_lib)
    lib.ptpu_load.restype = ctypes.c_void_p
    lib.ptpu_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_input_numel.restype = ctypes.c_longlong
    lib.ptpu_input_numel.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_num_inputs.argtypes = [ctypes.c_void_p]
    lib.ptpu_num_outputs.argtypes = [ctypes.c_void_p]
    lib.ptpu_run.argtypes = [ctypes.c_void_p,
                             ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                             ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_output_numel.restype = ctypes.c_longlong
    lib.ptpu_output_numel.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_get_output.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_float)]
    lib.ptpu_free.argtypes = [ctypes.c_void_p]

    err = ctypes.create_string_buffer(256)
    h = lib.ptpu_load((path + ".mlir").encode(), err, 256)
    assert h, err.value
    # signature = state tensors (in _collect_state order) + the input
    from paddle_tpu.jit.api import _collect_state

    _, tensors = _collect_state(net)
    n_in = lib.ptpu_num_inputs(h)
    assert n_in == len(tensors) + 1
    bufs = [np.ascontiguousarray(np.asarray(t.numpy(), np.float32)
                                 .reshape(-1)) for t in tensors]
    bufs.append(np.ascontiguousarray(x.reshape(-1)))
    for i, b in enumerate(bufs):
        assert lib.ptpu_input_numel(h, i) == b.size
    arr_t = ctypes.POINTER(ctypes.c_float) * n_in
    ins = arr_t(*[b.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                  for b in bufs])
    rc = lib.ptpu_run(h, ins, err, 256)
    assert rc == 0, err.value
    assert lib.ptpu_num_outputs(h) == 1
    n = lib.ptpu_output_numel(h, 0)
    out = np.zeros(n, np.float32)
    lib.ptpu_get_output(h, 0, out.ctypes.data_as(
        ctypes.POINTER(ctypes.c_float)))
    np.testing.assert_allclose(out.reshape(ref.shape), ref,
                               rtol=1e-5, atol=1e-6)

    # run_partial: re-run uploading only the activation input (weights
    # persist from the first run) — a second x must give the model's output
    lib.ptpu_run_partial.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    x2 = np.random.default_rng(1).standard_normal((4, 8)).astype(np.float32)
    ref2 = net(paddle.to_tensor(x2)).numpy()
    x2in = np.ascontiguousarray(x2.reshape(-1))
    one = (ctypes.POINTER(ctypes.c_float) * 1)(
        x2in.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    rc = lib.ptpu_run_partial(h, one, n_in - 1, err, 256)
    assert rc == 0, err.value
    out2 = np.zeros(n, np.float32)
    lib.ptpu_get_output(h, 0, out2.ctypes.data_as(
        ctypes.POINTER(ctypes.c_float)))
    np.testing.assert_allclose(out2.reshape(ref2.shape), ref2,
                               rtol=1e-5, atol=1e-6)

    # error path: bad artifact -> NULL + message, no crash
    bad = tmp_path / "bad.mlir"
    bad.write_text("not an mlir module")
    assert not lib.ptpu_load(str(bad).encode(), err, 256)
    assert b"main" in err.value
    lib.ptpu_free(h)


def test_capi_guards(capi_lib, tmp_path):
    """C-API hardening: output queries before a run and bad first_input
    must fail loudly, never UB (round-4 review findings)."""
    import ctypes

    paddle.seed(5)
    net = _Net()
    path = str(tmp_path / "net")
    paddle.jit.save(net, path,
                    input_spec=[paddle.static.InputSpec([4, 8], "float32")])
    lib = ctypes.CDLL(capi_lib)
    lib.ptpu_load.restype = ctypes.c_void_p
    lib.ptpu_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_output_numel.restype = ctypes.c_longlong
    lib.ptpu_output_numel.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_run_partial.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_num_inputs.argtypes = [ctypes.c_void_p]
    lib.ptpu_free.argtypes = [ctypes.c_void_p]

    err = ctypes.create_string_buffer(256)
    h = lib.ptpu_load((path + ".mlir").encode(), err, 256)
    assert h
    # outputs before any run: -1, no crash
    assert lib.ptpu_output_numel(h, 0) == -1
    # partial before full run: error, and a RETRY must still error (the
    # env must not be half-initialized by the rejected call)
    x = np.zeros(32, np.float32)
    one = (ctypes.POINTER(ctypes.c_float) * 1)(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    assert lib.ptpu_run_partial(h, one, lib.ptpu_num_inputs(h) - 1,
                                err, 256) == -1
    assert lib.ptpu_run_partial(h, one, lib.ptpu_num_inputs(h) - 1,
                                err, 256) == -1
    # out-of-range first_input
    assert lib.ptpu_run_partial(h, one, -1, err, 256) == -1
    assert b"range" in err.value
    lib.ptpu_free(h)


def test_capi_passthrough_return_survives_reruns(capi_lib, tmp_path):
    """A return value that aliases an input (pass-through) must be COPIED
    out of the env, not moved — a moved-from input would be silently empty
    on the next run (round-4 review finding)."""
    import ctypes

    class Echo(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return x, self.fc(x)

    paddle.seed(9)
    net = Echo()
    path = str(tmp_path / "echo")
    paddle.jit.save(net, path,
                    input_spec=[paddle.static.InputSpec([2, 4], "float32")])

    lib = ctypes.CDLL(capi_lib)
    lib.ptpu_load.restype = ctypes.c_void_p
    lib.ptpu_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_num_inputs.argtypes = [ctypes.c_void_p]
    lib.ptpu_num_outputs.argtypes = [ctypes.c_void_p]
    lib.ptpu_input_numel.restype = ctypes.c_longlong
    lib.ptpu_input_numel.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_run.argtypes = [ctypes.c_void_p,
                             ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                             ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_run_partial.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_output_numel.restype = ctypes.c_longlong
    lib.ptpu_output_numel.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_get_output.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_float)]
    lib.ptpu_free.argtypes = [ctypes.c_void_p]

    err = ctypes.create_string_buffer(256)
    h = lib.ptpu_load((path + ".mlir").encode(), err, 256)
    assert h, err.value
    n_in = lib.ptpu_num_inputs(h)

    from paddle_tpu.jit.api import _collect_state

    _, tensors = _collect_state(net)
    x1 = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
    bufs = [np.ascontiguousarray(np.asarray(t.numpy(), np.float32)
                                 .reshape(-1)) for t in tensors]
    bufs.append(np.ascontiguousarray(x1.reshape(-1)))
    arr_t = ctypes.POINTER(ctypes.c_float) * n_in
    ins = arr_t(*[b.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                  for b in bufs])
    assert lib.ptpu_run(h, ins, err, 256) == 0, err.value
    assert lib.ptpu_num_outputs(h) == 2

    def out(k, shape):
        n = lib.ptpu_output_numel(h, k)
        buf = np.zeros(n, np.float32)
        lib.ptpu_get_output(h, k, buf.ctypes.data_as(
            ctypes.POINTER(ctypes.c_float)))
        return buf.reshape(shape)

    np.testing.assert_allclose(out(0, (2, 4)), x1, rtol=1e-6)

    # second run via run_partial (weights persist): the pass-through input
    # must still be alive server-side and reflect the NEW activation
    x2 = np.random.default_rng(1).standard_normal((2, 4)).astype(np.float32)
    x2in = np.ascontiguousarray(x2.reshape(-1))
    one = (ctypes.POINTER(ctypes.c_float) * 1)(
        x2in.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    assert lib.ptpu_run_partial(h, one, n_in - 1, err, 256) == 0, err.value
    np.testing.assert_allclose(out(0, (2, 4)), x2, rtol=1e-6)
    ref2 = np.asarray((net(paddle.to_tensor(x2))[1]).numpy())
    np.testing.assert_allclose(out(1, (2, 4)), ref2, rtol=1e-5, atol=1e-6)
    lib.ptpu_free(h)


def test_capi_duplicate_return_operands(capi_lib, tmp_path):
    """A module whose @main returns the same non-arg SSA value twice
    (`return %5, %5`) must yield identical, non-empty data for BOTH
    outputs — moving the first occurrence out of the env would leave the
    second copying a moved-from husk (round-5 advisor finding)."""
    import ctypes

    class Twice(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            y = self.fc(x)
            return y, y

    paddle.seed(11)
    net = Twice()
    path = str(tmp_path / "twice")
    paddle.jit.save(net, path,
                    input_spec=[paddle.static.InputSpec([2, 4], "float32")])

    lib = ctypes.CDLL(capi_lib)
    lib.ptpu_load.restype = ctypes.c_void_p
    lib.ptpu_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_num_inputs.argtypes = [ctypes.c_void_p]
    lib.ptpu_num_outputs.argtypes = [ctypes.c_void_p]
    lib.ptpu_run.argtypes = [ctypes.c_void_p,
                             ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                             ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_output_numel.restype = ctypes.c_longlong
    lib.ptpu_output_numel.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_get_output.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_float)]
    lib.ptpu_free.argtypes = [ctypes.c_void_p]

    err = ctypes.create_string_buffer(256)
    h = lib.ptpu_load((path + ".mlir").encode(), err, 256)
    assert h, err.value

    from paddle_tpu.jit.api import _collect_state

    _, tensors = _collect_state(net)
    x = np.random.default_rng(2).standard_normal((2, 4)).astype(np.float32)
    ref = np.asarray(net(paddle.to_tensor(x))[0].numpy())
    bufs = [np.ascontiguousarray(np.asarray(t.numpy(), np.float32)
                                 .reshape(-1)) for t in tensors]
    bufs.append(np.ascontiguousarray(x.reshape(-1)))
    n_in = lib.ptpu_num_inputs(h)
    assert n_in == len(bufs)
    arr_t = ctypes.POINTER(ctypes.c_float) * n_in
    ins = arr_t(*[b.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                  for b in bufs])
    assert lib.ptpu_run(h, ins, err, 256) == 0, err.value
    assert lib.ptpu_num_outputs(h) == 2
    for k in range(2):
        n = lib.ptpu_output_numel(h, k)
        assert n == ref.size, f"output {k} numel {n} (moved-from husk?)"
        buf = np.zeros(n, np.float32)
        lib.ptpu_get_output(h, k, buf.ctypes.data_as(
            ctypes.POINTER(ctypes.c_float)))
        np.testing.assert_allclose(buf.reshape(ref.shape), ref,
                                   rtol=1e-5, atol=1e-6)
    lib.ptpu_free(h)
